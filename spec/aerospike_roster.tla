---- MODULE aerospike_roster ----
(***************************************************************************)
(* Formal model of the roster-based strong-consistency membership that    *)
(* the aerospike suite's nemesis exercises (kill / partition / revive /   *)
(* recluster — see jepsen_tpu/suites/aerospike.py, mirroring the          *)
(* reference's aerospike/spec/aerospike.tla, modeled independently).      *)
(*                                                                        *)
(* Nodes share a static Roster.  Each live node holds a *view*: the set   *)
(* of roster nodes it currently believes reachable.  A sub-cluster may    *)
(* accept writes for a partition only if its view contains a strict       *)
(* majority of the roster (or all replicas of the partition — we model    *)
(* the coarser majority rule).  Kills remove nodes; partitions split      *)
(* views; recluster recomputes views from current reachability; revive    *)
(* readmits a dead namespace only after operator action.                  *)
(*                                                                        *)
(* Safety (WriteExclusivity): two disjoint views can never both be        *)
(* write-authoritative — the property whose violation would surface as a  *)
(* lost update or split-brain in the cas-register workload.               *)
(***************************************************************************)

EXTENDS Naturals, FiniteSets

CONSTANT Roster            \* static set of nodes, e.g. {n1, n2, n3, n4, n5}
CONSTANT MaxDead           \* nemesis cap on simultaneously-dead nodes

VARIABLES
  dead,        \* set of killed nodes (asd not running)
  partition,   \* a set of sets: the connectivity components
  view,        \* view[n]: the component n believed at last recluster
  revived      \* set of nodes whose namespace was revived after death

vars == <<dead, partition, view, revived>>

Majority(S) == 2 * Cardinality(S) > Cardinality(Roster)

Live == Roster \ dead

ComponentOf(n) == CHOOSE c \in partition : n \in c

TypeOK ==
  /\ dead \subseteq Roster
  /\ revived \subseteq Roster
  /\ \A c \in partition : c \subseteq Roster
  /\ UNION partition = Roster
  /\ \A n \in Roster : view[n] \subseteq Roster

Init ==
  /\ dead = {}
  /\ revived = Roster
  /\ partition = {Roster}
  /\ view = [n \in Roster |-> Roster]

(* Nemesis: kill a node, respecting the max-dead cap                      *)
Kill(n) ==
  /\ n \in Live
  /\ Cardinality(dead) < MaxDead
  /\ dead' = dead \cup {n}
  /\ revived' = revived \ {n}
  /\ UNCHANGED <<partition, view>>

(* Nemesis: restart a killed node; it rejoins with an empty view until    *)
(* the next recluster                                                      *)
Restart(n) ==
  /\ n \in dead
  /\ dead' = dead \ {n}
  /\ view' = [view EXCEPT ![n] = {n}]
  /\ UNCHANGED <<partition, revived>>

(* Nemesis: partition the roster into two halves                          *)
Partition(c) ==
  /\ c \subseteq Roster /\ c # {} /\ c # Roster
  /\ partition' = {c, Roster \ c}
  /\ UNCHANGED <<dead, view, revived>>

Heal ==
  /\ partition' = {Roster}
  /\ UNCHANGED <<dead, view, revived>>

(* Operator: revive a restarted node's namespace                          *)
Revive(n) ==
  /\ n \in Live
  /\ revived' = revived \cup {n}
  /\ UNCHANGED <<dead, partition, view>>

(* Operator: recluster — every live node recomputes its view as the live, *)
(* revived members of its connectivity component                           *)
Recluster ==
  /\ view' = [n \in Roster |->
                IF n \in Live THEN (ComponentOf(n) \cap Live) \cap revived
                ELSE view[n]]
  /\ UNCHANGED <<dead, partition, revived>>

Next ==
  \/ \E n \in Roster : Kill(n) \/ Restart(n) \/ Revive(n)
  \/ \E c \in SUBSET Roster : Partition(c)
  \/ Heal
  \/ Recluster

Spec == Init /\ [][Next]_vars

(* A view is write-authoritative iff it holds a roster majority and all   *)
(* its members are live and mutually reachable                            *)
Authoritative(n) ==
  /\ n \in Live
  /\ Majority(view[n])
  /\ view[n] \subseteq (ComponentOf(n) \cap Live)

(* Two authoritative nodes must share a view member: no disjoint          *)
(* sub-clusters may both accept writes                                     *)
WriteExclusivity ==
  \A m, n \in Roster :
    (Authoritative(m) /\ Authoritative(n)) =>
      (view[m] \cap view[n]) # {}

THEOREM Spec => [](TypeOK /\ WriteExclusivity)

====
