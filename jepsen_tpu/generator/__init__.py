"""Pure-functional operation scheduler (the reference's generator system,
`jepsen/src/jepsen/generator.clj`).

A *generator* is a value that, given a test map and a scheduling context,
yields the next operation to perform and an evolved generator. Generators
are immutable: `op` and `update` return new generators, never mutate. This
purity is what makes the deterministic simulator (generator/simulate.py)
and the interpreter's single-threaded scheduler loop possible.

Anything op-shaped can be a generator (`generator.clj:545-590`):

  * a dict is a one-shot generator of itself (fields :type/:process/:time
    filled from the context),
  * a callable is called for the next generator each time an op is needed,
  * a list/tuple runs its elements in sequence,
  * None is the exhausted generator,
  * Gen subclasses implement the protocol directly.

Scheduling context (`generator.clj:453-464`): `Context(time, free_threads,
workers)` where threads are 0..concurrency-1 plus "nemesis", and workers
maps thread -> current process (processes are retired and replaced when
they crash; `next_process`, `generator.clj:519-527`).

Times are integer nanoseconds since the start of the test.

Randomness goes through this module's `rng` (a `random.Random`) so the
simulator and tests can pin a seed (`fixed_rng`), mirroring the
reference's `with-fixed-rand-int` test harness (`generator/test.clj:33-48`).
"""

from __future__ import annotations

import builtins
import dataclasses
import inspect
import logging
import random
import threading
from typing import Any, Callable, Optional

LOG = logging.getLogger("jepsen_tpu.generator")

NEMESIS = "nemesis"


class _Pending:
    """Sentinel: the generator has ops, but can't emit one right now."""

    def __repr__(self):
        return ":pending"


PENDING = _Pending()

_RNG_TLS = threading.local()
_DEFAULT_RNG = random.Random()


class _RngProxy:
    """`gen.rng`, made worker-safe: delegates every method to the
    calling thread's pinned stream (`fixed_rng`) or, unpinned, to one
    process-wide default. Pinning used to rebind the module global, so
    N concurrent `simulate()` workers shared (and clobbered) a single
    seed-45100 stream; with thread-local pinning each worker owns an
    independent deterministic stream and unrelated threads never see
    another worker's seed. Attribute lookup is the only indirection —
    call sites (`gen.rng.randrange(...)`) are unchanged."""

    @staticmethod
    def _current() -> random.Random:
        return getattr(_RNG_TLS, "rng", None) or _DEFAULT_RNG

    def __getattr__(self, name):
        return getattr(self._current(), name)

    def __repr__(self):
        pinned = getattr(_RNG_TLS, "rng", None) is not None
        return f"<generator.rng {'pinned' if pinned else 'default'}>"


rng = _RngProxy()


class fixed_rng:
    """Context manager pinning the *calling thread's* RNG to a seeded
    stream for deterministic simulation (reference seed 45100,
    test.clj:44-48). Reentrant — nesting saves and restores the outer
    pin — and thread-safe: concurrent workers each pin their own
    stream (the search driver runs hundreds of parallel `simulate()`
    calls; see jepsen_tpu/search/driver.py)."""

    def __init__(self, seed: int = 45100):
        self.seed = seed

    def __enter__(self):
        self._saved = getattr(_RNG_TLS, "rng", None)
        r = random.Random(self.seed)
        _RNG_TLS.rng = r
        return r

    def __exit__(self, *exc):
        _RNG_TLS.rng = self._saved
        return False


def secs_to_nanos(s: float) -> int:
    return int(s * 1_000_000_000)


# ---------------------------------------------------------------------------
# Context
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Context:
    """time: ns; free_threads: ordered tuple of idle threads; workers:
    thread -> process currently assigned."""
    time: int
    free_threads: tuple
    workers: dict

    # Direct construction instead of dataclasses.replace: these run in
    # the interpreter's per-op hot path (>20k ops/s parity target,
    # `generator.clj:66-70`), and replace() re-walks the signature.

    def _share_workers_cache(self, c: "Context") -> "Context":
        # the pred -> filtered-workers memo depends only on `workers`,
        # so every transition that keeps the same workers dict (time,
        # busy, free) carries it forward — across a whole run the
        # filter is computed once per pred per workers generation, not
        # once per event
        try:
            object.__setattr__(c, "_workers_cache", self._workers_cache)
        except AttributeError:
            pass
        return c

    def with_time(self, t: int) -> "Context":
        return self._share_workers_cache(
            Context(t, self.free_threads, self.workers))

    def busy(self, thread) -> "Context":
        return self._share_workers_cache(Context(
            self.time,
            tuple(t for t in self.free_threads if t != thread),
            self.workers))

    def free(self, thread) -> "Context":
        if thread in self.free_threads:
            return self
        return self._share_workers_cache(Context(
            self.time, self.free_threads + (thread,), self.workers))

    def with_workers(self, workers: dict) -> "Context":
        # deliberately does NOT share the memo: workers changed
        return Context(self.time, self.free_threads, workers)

    def restrict(self, key, pred) -> "Context":
        """A view containing only threads satisfying pred. The workers
        filtering is memoized per pred and survives time/busy/free
        transitions (thread-routing combinators re-restrict evolving
        contexts on every event); only the free-thread filter — a
        handful of pred calls — runs per restriction. The restricted
        context gets a fresh memo of its own: its workers are a
        subset, so inherited entries would be wrong for nested
        restrictions."""
        try:
            cache = self._workers_cache
        except AttributeError:
            cache = {}
            object.__setattr__(self, "_workers_cache", cache)
        w = cache.get(key)
        if w is None:
            w = {t: p for t, p in self.workers.items() if pred(t)}
            cache[key] = w
        return Context(self.time,
                       tuple(t for t in self.free_threads if pred(t)),
                       w)


def context(test: dict) -> Context:
    """Initial context for a test map: `concurrency` client threads plus
    the nemesis, all free (`generator.clj:453-464`)."""
    threads = (NEMESIS,) + tuple(range(test.get("concurrency", 1)))
    return Context(0, threads, {t: t for t in threads})


def free_processes(ctx: Context) -> list:
    return [ctx.workers[t] for t in ctx.free_threads]


def some_free_process(ctx: Context):
    """A uniformly random free process — random, not first-fit, so quick
    threads can't starve the others (`generator.clj:440-450`)."""
    n = len(ctx.free_threads)
    if n == 0:
        return None
    return ctx.workers[ctx.free_threads[rng.randrange(n)]]


def all_processes(ctx: Context) -> list:
    return list(ctx.workers.values())


def all_threads(ctx: Context) -> list:
    return list(ctx.workers.keys())


def process_to_thread(ctx: Context, process):
    for t, p in ctx.workers.items():
        if p == process:
            return t
    return None


def thread_to_process(ctx: Context, thread):
    return ctx.workers.get(thread)


def next_process(ctx: Context, thread):
    """The replacement process for a crashed one: old process + number of
    numeric processes in the *global* context (`generator.clj:519-527`)."""
    if thread == NEMESIS:
        return thread
    numeric = [p for p in all_processes(ctx) if isinstance(p, int)]
    return ctx.workers[thread] + len(numeric)


def fill_in_op(op: dict, ctx: Context):
    """Fill :type/:process/:time from the context; PENDING if no process
    is free (`generator.clj:531-543`)."""
    p = some_free_process(ctx)
    if p is None:
        return PENDING
    out = dict(op)
    out.setdefault("time", ctx.time)
    out.setdefault("process", p)
    out.setdefault("type", "invoke")
    return out


# ---------------------------------------------------------------------------
# Protocol + lifting
# ---------------------------------------------------------------------------

class Gen:
    """The generator protocol (`generator.clj:382-390`)."""

    def op(self, test: dict, ctx: Context):
        """-> (op, gen') | (PENDING, gen') | None when exhausted."""
        raise NotImplementedError

    def update(self, test: dict, ctx: Context, event: dict) -> "Gen":
        return self


_UNPULLED = object()


class IterGen(Gen):
    """Lifts a Python iterator into a generator — the analog of the
    reference's lazy-seq generators (`generator.clj:545-590` seqs),
    enabling infinite op streams like the set workload's unique-add
    sequence. The head pull is memoized so repeated op() calls on the
    same value are idempotent; each emitted op hands back a fresh
    wrapper around the shared iterator tail."""

    def __init__(self, it):
        self.it = it
        self._head = _UNPULLED

    def _pull(self):
        if self._head is _UNPULLED:
            try:
                self._head = next(self.it)
            except StopIteration:
                self._head = None
        return self._head

    def op(self, test, ctx):
        while True:
            head = self._pull()
            if head is None:
                return None
            res = op(head, test, ctx)
            if res is None:
                # an exhausted sub-generator head: re-pull for the next
                # element (iteratively — a long run of empty heads must
                # not recurse)
                self._head = _UNPULLED
                continue
            o, g1 = res
            if o is PENDING:
                # memoize the (possibly wrapped/advanced) head so no
                # pulled element is lost when the interpreter re-asks
                self._head = g1
                return (o, self)
            tail = IterGen(self.it)
            return (o, [g1, tail] if g1 is not None else tail)

    def update(self, test, ctx, event):
        if self._head not in (_UNPULLED, None):
            self._head = update(self._head, test, ctx, event)
        return self


def op(gen, test: dict, ctx: Context):
    """Ask any liftable generator for its next operation."""
    while True:
        if gen is None:
            return None
        if isinstance(gen, Gen):
            return gen.op(test, ctx)
        if isinstance(gen, dict):
            o = fill_in_op(gen, ctx)
            return (o, gen if o is PENDING else None)
        if callable(gen):
            x = _call_fn_gen(gen, test, ctx)
            if x is None:
                return None
            if type(x) is dict:
                # fast path for the ubiquitous fn-gen -> op-dict case:
                # inline the [dict, fn] list+dict dispatch this would
                # otherwise recurse through (the >20k ops/s parity
                # target lives here, `generator.clj:66-70`)
                o = fill_in_op(x, ctx)
                return (o, [x, gen]) if o is PENDING else (o, gen)
            return op([x, gen], test, ctx)
        if isinstance(gen, (list, tuple)):
            if not gen:
                return None
            res = op(gen[0], test, ctx)
            if res is None:
                gen = list(gen[1:])
                continue
            o, g1 = res
            rest = list(gen[1:])
            return (o, [g1] + rest if rest else g1)
        if hasattr(gen, "__next__"):
            gen = IterGen(gen)
            continue
        raise TypeError(f"not a generator: {gen!r}")


def update(gen, test: dict, ctx: Context, event: dict):
    """Propagate a history event into a generator."""
    if gen is None:
        return None
    if isinstance(gen, Gen):
        return gen.update(test, ctx, event)
    if isinstance(gen, dict) or callable(gen):
        return gen
    if isinstance(gen, (list, tuple)):
        if not gen:
            return None
        return [update(gen[0], test, ctx, event)] + list(gen[1:])
    if hasattr(gen, "__next__"):
        return update(IterGen(gen), test, ctx, event)
    raise TypeError(f"not a generator: {gen!r}")


def _fn_gen_arity(f: Callable) -> int:
    """Required positional arity, memoized on the function object —
    signature inspection per emitted op dominates the hot loop."""
    n = getattr(f, "__gen_arity__", None)
    if n is None:
        try:
            sig = inspect.signature(f)
            n = len([p for p in sig.parameters.values()
                     if p.default is inspect.Parameter.empty
                     and p.kind in (p.POSITIONAL_ONLY,
                                    p.POSITIONAL_OR_KEYWORD)])
        except (TypeError, ValueError):
            n = 0
        try:
            f.__gen_arity__ = n
        except (AttributeError, TypeError):
            pass
    return n


def _call_fn_gen(f: Callable, test: dict, ctx: Context):
    return f(test, ctx) if _fn_gen_arity(f) >= 2 else f()


# ---------------------------------------------------------------------------
# Wrappers: validate / friendly exceptions / trace
# ---------------------------------------------------------------------------

class InvalidOp(Exception):
    def __init__(self, problems, res, ctx):
        self.problems, self.res, self.ctx = problems, res, ctx
        super().__init__(
            "generator produced an invalid (op, gen') pair: "
            + "; ".join(problems) + f" — {res!r}")


@dataclasses.dataclass(frozen=True)
class Validate(Gen):
    """Asserts emitted ops are well-formed and their process is actually
    free (`generator.clj:622-676`)."""
    gen: Any

    def op(self, test, ctx):
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        problems = []
        if not (isinstance(res, tuple) and len(res) == 2):
            problems.append("should return a pair of (op, gen')")
        else:
            o = res[0]
            if o is not PENDING:
                if not isinstance(o, dict):
                    problems.append("op should be PENDING or a dict")
                else:
                    if o.get("type") not in ("invoke", "info", "sleep",
                                             "log"):
                        problems.append(
                            ":type should be invoke, info, sleep or log")
                    if not isinstance(o.get("time"), int):
                        problems.append(":time should be an integer")
                    if o.get("process") is None:
                        problems.append("no :process")
                    elif o["process"] not in free_processes(ctx):
                        problems.append(
                            f"process {o['process']!r} is not free")
        if problems:
            raise InvalidOp(problems, res, ctx)
        return res[0], Validate(res[1])

    def update(self, test, ctx, event):
        return Validate(update(self.gen, test, ctx, event))


def validate(gen):
    return Validate(gen)


class GenException(Exception):
    def __init__(self, where, gen, ctx):
        super().__init__(
            f"generator raised during {where}; generator: {gen!r}")
        self.ctx = ctx


@dataclasses.dataclass(frozen=True)
class FriendlyExceptions(Gen):
    """Wraps underlying exceptions with the generator and context
    (`generator.clj:713-757`)."""
    gen: Any

    def op(self, test, ctx):
        try:
            res = op(self.gen, test, ctx)
        except GenException:
            raise
        except Exception as e:
            raise GenException("op", self.gen, ctx) from e
        if res is None:
            return None
        return res[0], FriendlyExceptions(res[1])

    def update(self, test, ctx, event):
        try:
            return FriendlyExceptions(update(self.gen, test, ctx, event))
        except GenException:
            raise
        except Exception as e:
            raise GenException("update", self.gen, ctx) from e


def friendly_exceptions(gen):
    return FriendlyExceptions(gen)


@dataclasses.dataclass(frozen=True)
class Trace(Gen):
    """Logs every op/update crossing this generator (`generator.clj:758`)."""
    k: Any
    gen: Any

    def op(self, test, ctx):
        res = op(self.gen, test, ctx)
        LOG.info("%s op %r", self.k, None if res is None else res[0])
        if res is None:
            return None
        return res[0], Trace(self.k, res[1])

    def update(self, test, ctx, event):
        LOG.info("%s update %r", self.k, event)
        return Trace(self.k, update(self.gen, test, ctx, event))


def trace(k, gen):
    return Trace(k, gen)


# ---------------------------------------------------------------------------
# Transforms: map / f-map / filter / on-update
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Map(Gen):
    f: Callable
    gen: Any

    def op(self, test, ctx):
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        o, g1 = res
        return (o if o is PENDING else self.f(o)), Map(self.f, g1)

    def update(self, test, ctx, event):
        return Map(self.f, update(self.gen, test, ctx, event))


def map(f: Callable, gen):  # noqa: A001 — mirrors the reference name
    """Transform every op with f; PENDING/None pass through untouched
    (`generator.clj:782`)."""
    return Map(f, gen)


def f_map(fmap, gen):
    """Rewrite op :f fields through a mapping — the composed-nemesis
    helper (`generator.clj:790`)."""
    lookup = fmap.get if isinstance(fmap, dict) else fmap

    def transform(o):
        o = dict(o)
        o["f"] = lookup(o["f"])
        return o
    return Map(transform, gen)


@dataclasses.dataclass(frozen=True)
class Filter(Gen):
    f: Callable
    gen: Any

    def op(self, test, ctx):
        g = self.gen
        while True:
            res = op(g, test, ctx)
            if res is None:
                return None
            o, g1 = res
            if o is PENDING or self.f(o):
                return o, Filter(self.f, g1)
            g = g1

    def update(self, test, ctx, event):
        return Filter(self.f, update(self.gen, test, ctx, event))


def filter(f: Callable, gen):  # noqa: A001
    """Only ops satisfying f pass; PENDING bypasses (`generator.clj:812`)."""
    return Filter(f, gen)


@dataclasses.dataclass(frozen=True)
class IgnoreUpdates(Gen):
    gen: Any

    def op(self, test, ctx):
        return op(self.gen, test, ctx)

    def update(self, test, ctx, event):
        return self


def ignore_updates(gen):
    return IgnoreUpdates(gen)


@dataclasses.dataclass(frozen=True)
class OnUpdate(Gen):
    """Calls (f self test ctx event) on update; f returns the replacement
    generator (`generator.clj:836`)."""
    f: Callable
    gen: Any

    def op(self, test, ctx):
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        return res[0], OnUpdate(self.f, res[1])

    def update(self, test, ctx, event):
        return self.f(self, test, ctx, event)


def on_update(f, gen):
    return OnUpdate(f, gen)


# ---------------------------------------------------------------------------
# Thread routing: on-threads / clients / nemesis / reserve / each-thread
# ---------------------------------------------------------------------------

def _restrict_ctx(pred: Callable, ctx: Context) -> Context:
    # the pred object itself is the key (identity equality for
    # functions) — keeping a reference also rules out id() reuse
    return ctx.restrict(pred, pred)


@dataclasses.dataclass(frozen=True)
class OnThreads(Gen):
    """Restricts a generator to threads satisfying pred; the inner
    generator only ever sees those threads (`generator.clj:875`)."""
    pred: Callable
    gen: Any

    def op(self, test, ctx):
        res = op(self.gen, test, _restrict_ctx(self.pred, ctx))
        if res is None:
            return None
        return res[0], OnThreads(self.pred, res[1])

    def update(self, test, ctx, event):
        t = process_to_thread(ctx, event.get("process"))
        if t is not None and self.pred(t):
            return OnThreads(
                self.pred,
                update(self.gen, test, _restrict_ctx(self.pred, ctx),
                       event))
        return self


def on_threads(pred, gen):
    if isinstance(pred, (set, frozenset)):
        s = pred
        pred = lambda t: t in s  # noqa: E731
    return OnThreads(pred, gen)


on = on_threads


def clients(client_gen, nemesis_gen=None):
    """Route client threads to client_gen (and, two-arity, the nemesis to
    nemesis_gen) (`generator.clj:1093`)."""
    c = on_threads(lambda t: t != NEMESIS, client_gen)
    if nemesis_gen is None:
        return c
    return any(c, nemesis(nemesis_gen))


def nemesis(nemesis_gen, client_gen=None):
    n = on_threads(lambda t: t == NEMESIS, nemesis_gen)
    if client_gen is None:
        return n
    return any(n, clients(client_gen))


def _soonest(m1: Optional[dict], m2: Optional[dict]) -> Optional[dict]:
    """Pick whichever op-map happens sooner; ties break randomly by weight
    (`soonest-op-map`, `generator.clj:887-927`)."""
    if m1 is None:
        return m2
    if m2 is None:
        return m1
    if m1["op"] is PENDING:
        return m2
    if m2["op"] is PENDING:
        return m1
    t1, t2 = m1["op"]["time"], m2["op"]["time"]
    if t1 == t2:
        w1 = m1.get("weight", 1)
        w2 = m2.get("weight", 1)
        winner = m1 if rng.randrange(w1 + w2) < w1 else m2
        winner = dict(winner)
        winner["weight"] = w1 + w2
        return winner
    return m1 if t1 < t2 else m2


@dataclasses.dataclass(frozen=True)
class Any(Gen):
    """Ops from whichever generator is soonest; updates go to all
    (`generator.clj:946`)."""
    gens: tuple

    def op(self, test, ctx):
        best = None
        for i, g in enumerate(self.gens):
            res = op(g, test, ctx)
            if res is not None:
                best = _soonest(best, {"op": res[0], "gen": res[1], "i": i})
        if best is None:
            return None
        gens = builtins.list(self.gens)
        gens[best["i"]] = best["gen"]
        return best["op"], Any(tuple(gens))

    def update(self, test, ctx, event):
        return Any(tuple(update(g, test, ctx, event) for g in self.gens))


def any(*gens):  # noqa: A001
    if len(gens) == 0:
        return None
    if len(gens) == 1:
        return gens[0]
    return Any(tuple(gens))


@dataclasses.dataclass(frozen=True)
class EachThread(Gen):
    """An independent copy of the generator per thread; each copy sees a
    single-thread context (`generator.clj:1001`)."""
    fresh: Any
    gens: tuple  # ((thread, gen), ...) — tuple for hashability

    def _gen_for(self, thread):
        for t, g in self.gens:
            if t == thread:
                return g
        return self.fresh

    def _with(self, thread, g):
        pairs = [(t, x) for t, x in self.gens if t != thread]
        return EachThread(self.fresh, tuple(pairs + [(thread, g)]))

    def op(self, test, ctx):
        best = None
        for thread in ctx.free_threads:
            sub = Context(ctx.time, (thread,),
                          {thread: ctx.workers[thread]})
            res = op(self._gen_for(thread), test, sub)
            if res is not None:
                best = _soonest(best, {"op": res[0], "gen": res[1],
                                       "thread": thread})
        if best is not None:
            return best["op"], self._with(best["thread"], best["gen"])
        if len(ctx.free_threads) != len(ctx.workers):
            return PENDING, self  # busy threads may still want ops
        return None  # every thread exhausted

    def update(self, test, ctx, event):
        thread = process_to_thread(ctx, event.get("process"))
        if thread is None:
            return self
        sub = Context(ctx.time,
                      tuple(t for t in ctx.free_threads if t == thread),
                      {thread: ctx.workers[thread]})
        return self._with(
            thread, update(self._gen_for(thread), test, sub, event))


def each_thread(gen):
    return EachThread(gen, ())


@dataclasses.dataclass(frozen=True)
class Reserve(Gen):
    """Dedicated thread ranges per generator, remainder to a default
    (`generator.clj:1056`). Ranges are *positional* within the current
    context's ordered thread list (integer threads in order, then the
    nemesis), so reserve composes with thread-restricting wrappers like
    on_threads and independent's concurrent groups."""
    counts: tuple     # threads per reserved range
    gens: tuple       # len(counts)+1; last is the default

    @staticmethod
    def _ordered_threads(ctx: Context) -> builtins.list:
        ints = sorted(t for t in ctx.workers if isinstance(t, int))
        rest = [t for t in ctx.workers if not isinstance(t, int)]
        return ints + rest

    def _range_sets(self, ctx: Context) -> builtins.list:
        """Per-range thread sets for this context, plus the remainder."""
        ordered = self._ordered_threads(ctx)
        sets = []
        n = 0
        for count in self.counts:
            sets.append(frozenset(ordered[n:n + count]))
            n += count
        sets.append(frozenset(ordered[n:]))
        return sets

    def op(self, test, ctx):
        best = None
        for i, threads in enumerate(self._range_sets(ctx)):
            # the frozenset itself is the memo key: a fresh lambda per
            # call would defeat (and unboundedly grow) the cache
            sub = ctx.restrict(threads, lambda t, s=threads: t in s)
            res = op(self.gens[i], test, sub)
            if res is not None:
                best = _soonest(best, {"op": res[0], "gen": res[1],
                                       "i": i, "weight": len(threads)})
        if best is None:
            return None
        gens = builtins.list(self.gens)
        gens[best["i"]] = best["gen"]
        return best["op"], Reserve(self.counts, tuple(gens))

    def update(self, test, ctx, event):
        thread = process_to_thread(ctx, event.get("process"))
        sets = self._range_sets(ctx)
        i = len(self.counts)
        for j, threads in enumerate(sets[:-1]):
            if thread in threads:
                i = j
                break
        gens = builtins.list(self.gens)
        gens[i] = update(gens[i], test, ctx, event)
        return Reserve(self.counts, tuple(gens))


def reserve(*args):
    """reserve(5, write_gen, 10, cas_gen, read_gen): first 5 threads run
    write_gen, next 10 cas_gen, the rest read_gen."""
    assert len(args) % 2 == 1, "reserve needs a trailing default generator"
    *pairs, default = args
    counts, gens = [], []
    for count, gen in zip(pairs[0::2], pairs[1::2]):
        counts.append(count)
        gens.append(gen)
    return Reserve(tuple(counts), tuple(gens) + (default,))


# ---------------------------------------------------------------------------
# Mixing and sequencing
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Mix(Gen):
    """Uniform random mixture; behaves as a sequence of one-shot randomly
    selected generators. Ignores updates (`generator.clj:1140`)."""
    i: int
    gens: tuple

    def op(self, test, ctx):
        i, gens = self.i, builtins.list(self.gens)
        while gens:
            res = op(gens[i], test, ctx)
            if res is not None:
                gens[i] = res[1]
                return res[0], Mix(rng.randrange(len(gens)), tuple(gens))
            del gens[i]
            if not gens:
                return None
            i = rng.randrange(len(gens))
        return None

    def update(self, test, ctx, event):
        return self


def mix(gens):
    gens = builtins.list(gens)
    if not gens:
        return None
    return Mix(rng.randrange(len(gens)), tuple(gens))


@dataclasses.dataclass(frozen=True)
class Limit(Gen):
    remaining: int
    gen: Any

    def op(self, test, ctx):
        if self.remaining <= 0:
            return None
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        n = self.remaining if res[0] is PENDING else self.remaining - 1
        return res[0], Limit(n, res[1])

    def update(self, test, ctx, event):
        return Limit(self.remaining, update(self.gen, test, ctx, event))


def limit(n: int, gen):
    return Limit(n, gen)


def once(gen):
    return Limit(1, gen)


class Derefer(Gen):
    """Defer building a generator until it is first asked for an op —
    the reference's `gen/derefer` over a delay (`aerospike
    set.clj:63-72` uses it for final reads over keys only known at
    runtime). The built generator is memoized on this node (a delay
    caches its value), so a discarded poll re-polls the same state and
    nothing is lost; each emitted op hands the advanced tail to a
    fresh Derefer."""

    def __init__(self, build: Callable):
        self.build = build
        self._built = _UNPULLED

    def op(self, test, ctx):
        if self._built is _UNPULLED:
            self._built = self.build(test, ctx)
        res = op(self._built, test, ctx)
        if res is None:
            return None
        o, g1 = res
        nxt = Derefer(self.build)
        nxt._built = g1
        return o, nxt

    def update(self, test, ctx, event):
        if self._built is not _UNPULLED:
            self._built = update(self._built, test, ctx, event)
        return self


def derefer(build: Callable) -> Derefer:
    """build(test, ctx) -> generator (or None), called at most once."""
    return Derefer(build)


def log(msg):
    """A one-shot op that just logs a message (`generator.clj:1177`)."""
    return {"type": "log", "value": msg}


@dataclasses.dataclass(frozen=True)
class Repeat(Gen):
    """Re-emits from an unchanging generator; remaining < 0 means forever
    (`generator.clj:1196`)."""
    remaining: int
    gen: Any

    def op(self, test, ctx):
        if self.remaining == 0:
            return None
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        n = self.remaining if res[0] is PENDING else self.remaining - 1
        return res[0], Repeat(n, self.gen)

    def update(self, test, ctx, event):
        return Repeat(self.remaining, update(self.gen, test, ctx, event))


def repeat(*args):
    """repeat(gen) forever, or repeat(n, gen) n times."""
    if len(args) == 1:
        return Repeat(-1, args[0])
    n, gen = args
    assert n >= 0
    return Repeat(n, gen)


@dataclasses.dataclass(frozen=True)
class Cycle(Gen):
    remaining: int
    original: Any
    gen: Any

    def op(self, test, ctx):
        remaining, gen = self.remaining, self.gen
        while remaining != 0:
            res = op(gen, test, ctx)
            if res is not None:
                return res[0], Cycle(remaining, self.original, res[1])
            remaining -= 1
            gen = self.original
        return None

    def update(self, test, ctx, event):
        return Cycle(self.remaining, self.original,
                     update(self.gen, test, ctx, event))


def cycle(*args):
    """cycle(gen) restarts gen forever when it exhausts; cycle(n, gen)
    runs it n times (`generator.clj:1228`)."""
    if len(args) == 1:
        return Cycle(-1, args[0], args[0])
    n, gen = args
    return Cycle(n, gen, gen)


# ---------------------------------------------------------------------------
# Bounding: process-limit / time-limit
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ProcessLimit(Gen):
    """Emits ops for at most n distinct processes, counting every process
    that *could* run — prevents end-of-test trickle (`generator.clj:1253`)."""
    n: int
    procs: frozenset
    gen: Any

    def op(self, test, ctx):
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        o, g1 = res
        if o is PENDING:
            return o, ProcessLimit(self.n, self.procs, g1)
        procs = self.procs | frozenset(all_processes(ctx))
        if len(procs) > self.n:
            return None
        return o, ProcessLimit(self.n, procs, g1)

    def update(self, test, ctx, event):
        return ProcessLimit(self.n, self.procs,
                            update(self.gen, test, ctx, event))


def process_limit(n: int, gen):
    return ProcessLimit(n, frozenset(), gen)


@dataclasses.dataclass(frozen=True)
class TimeLimit(Gen):
    """Emits for `limit` ns after its first op (`generator.clj:1286`)."""
    limit: int
    cutoff: Optional[int]
    gen: Any

    def op(self, test, ctx):
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        o, g1 = res
        if o is PENDING:
            return o, TimeLimit(self.limit, self.cutoff, g1)
        cutoff = self.cutoff if self.cutoff is not None \
            else o["time"] + self.limit
        if o["time"] >= cutoff:
            return None
        return o, TimeLimit(self.limit, cutoff, g1)

    def update(self, test, ctx, event):
        return TimeLimit(self.limit, self.cutoff,
                         update(self.gen, test, ctx, event))


def time_limit(dt_secs: float, gen):
    return TimeLimit(secs_to_nanos(dt_secs), None, gen)


# ---------------------------------------------------------------------------
# Timing: stagger / delay / sleep
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Stagger(Gen):
    """Schedules ops at uniformly random intervals in [0, dt); dt is
    2x the requested mean so the rate averages out. Applies globally, not
    per-thread (`generator.clj:1315-1340`)."""
    dt: int
    next_time: Optional[int]
    gen: Any

    def op(self, test, ctx):
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        o, g1 = res
        if o is PENDING:
            return o, self
        next_time = self.next_time if self.next_time is not None \
            else ctx.time
        if next_time <= o["time"]:
            return o, Stagger(self.dt, o["time"] + _rand_nanos(self.dt),
                              g1)
        o = dict(o)
        o["time"] = next_time
        return o, Stagger(self.dt, next_time + _rand_nanos(self.dt), g1)

    def update(self, test, ctx, event):
        return Stagger(self.dt, self.next_time,
                       update(self.gen, test, ctx, event))


def _rand_nanos(dt: int) -> int:
    return int(rng.random() * dt)


def stagger(dt_secs: float, gen):
    return Stagger(secs_to_nanos(2 * dt_secs), None, gen)


@dataclasses.dataclass(frozen=True)
class Delay(Gen):
    """Ops exactly dt apart (catching up if behind) (`generator.clj:1385`)."""
    dt: int
    next_time: Optional[int]
    gen: Any

    def op(self, test, ctx):
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        o, g1 = res
        if o is PENDING:
            return o, Delay(self.dt, self.next_time, g1)
        next_time = self.next_time if self.next_time is not None \
            else o["time"]
        if o["time"] < next_time:
            o = dict(o)
            o["time"] = next_time
        return o, Delay(self.dt, o["time"] + self.dt, g1)

    def update(self, test, ctx, event):
        return Delay(self.dt, self.next_time,
                     update(self.gen, test, ctx, event))


def delay(dt_secs: float, gen):
    return Delay(secs_to_nanos(dt_secs), None, gen)


def sleep(dt_secs: float):
    """One op telling its process to do nothing for dt seconds
    (`generator.clj:1397`)."""
    return {"type": "sleep", "value": dt_secs}


# ---------------------------------------------------------------------------
# Phasing: synchronize / phases / then
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Synchronize(Gen):
    """PENDING until every worker is free, then becomes the generator
    (`generator.clj:1420`)."""
    gen: Any

    def op(self, test, ctx):
        if len(ctx.free_threads) == len(ctx.workers) and \
                set(ctx.free_threads) == set(ctx.workers):
            return op(self.gen, test, ctx)
        return PENDING, self

    def update(self, test, ctx, event):
        return Synchronize(update(self.gen, test, ctx, event))


def synchronize(gen):
    return Synchronize(gen)


def phases(*gens):
    return [synchronize(g) for g in gens]


def then(a, b):
    """b, then (everyone idle), then a — argument order reads well in
    pipelines (`generator.clj:1432`)."""
    return [b, synchronize(a)]


# ---------------------------------------------------------------------------
# until-ok / flip-flop / cycle-times
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class UntilOk(Gen):
    """Yields ops until one of them completes :ok (`generator.clj:1469`)."""
    gen: Any
    done: bool
    active: frozenset

    def op(self, test, ctx):
        if self.done:
            return None
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        o, g1 = res
        if o is PENDING:
            return o, UntilOk(g1, self.done, self.active)
        return o, UntilOk(g1, self.done, self.active | {o["process"]})

    def update(self, test, ctx, event):
        g1 = update(self.gen, test, ctx, event)
        p = event.get("process")
        if p in self.active:
            t = event.get("type")
            if t == "ok":
                return UntilOk(g1, True, self.active - {p})
            if t in ("info", "fail"):
                return UntilOk(g1, self.done, self.active - {p})
        return UntilOk(g1, self.done, self.active)


def until_ok(gen):
    return UntilOk(gen, False, frozenset())


@dataclasses.dataclass(frozen=True)
class FlipFlop(Gen):
    """A, then B, then A... stops when either exhausts; ignores updates
    (`generator.clj:1485`)."""
    gens: tuple
    i: int

    def op(self, test, ctx):
        res = op(self.gens[self.i], test, ctx)
        if res is None:
            return None
        gens = builtins.list(self.gens)
        gens[self.i] = res[1]
        nxt = self.i if res[0] is PENDING else (self.i + 1) % len(gens)
        return res[0], FlipFlop(tuple(gens), nxt)

    def update(self, test, ctx, event):
        return self


def flip_flop(a, b):
    return FlipFlop((a, b), 0)


@dataclasses.dataclass(frozen=True)
class CycleTimes(Gen):
    """Rotates between generators on a fixed schedule of windows,
    preserving each generator's state across cycles
    (`generator.clj:1557-1581`)."""
    period: int
    t0: Optional[int]
    intervals: tuple
    cutoffs: tuple     # cumulative interval sums (includes the last)
    gens: tuple

    def op(self, test, ctx):
        now = ctx.time
        t0 = self.t0 if self.t0 is not None else now
        in_period = (now - t0) % self.period
        cycle_start = now - in_period
        i = 0
        while i < len(self.cutoffs) - 1 and in_period >= self.cutoffs[i]:
            i += 1
        t = cycle_start + sum(self.intervals[:i])
        gens = builtins.list(self.gens)
        for _ in range(2 * len(gens)):  # bounded walk over the windows
            t_end = t + self.intervals[i]
            res = op(gens[i], test, ctx.with_time(max(now, t)))
            if res is None:
                return None
            o, g1 = res
            gens[i] = g1
            if o is PENDING:
                return PENDING, CycleTimes(self.period, t0,
                                           self.intervals, self.cutoffs,
                                           tuple(gens))
            if o["time"] < t_end:
                return o, CycleTimes(self.period, t0, self.intervals,
                                     self.cutoffs, tuple(gens))
            i = (i + 1) % len(gens)
            t = t_end
        return PENDING, CycleTimes(self.period, t0, self.intervals,
                                   self.cutoffs, tuple(gens))

    def update(self, test, ctx, event):
        return CycleTimes(self.period, self.t0, self.intervals,
                          self.cutoffs,
                          tuple(update(g, test, ctx, event)
                                for g in self.gens))


def cycle_times(*specs):
    """cycle_times(5, write_gen, 10, read_gen): writes for 5 s, reads for
    10 s, repeating. State persists across cycles."""
    if not specs:
        return None
    assert len(specs) % 2 == 0
    intervals = tuple(secs_to_nanos(s) for s in specs[0::2])
    gens = tuple(specs[1::2])
    cutoffs = []
    acc = 0
    for iv in intervals:
        acc += iv
        cutoffs.append(acc)
    return CycleTimes(sum(intervals), None, intervals, tuple(cutoffs),
                      gens)


def concat(*gens):
    """Sequence of generators as one (`generator.clj:777`)."""
    return builtins.list(gens)
