"""Deterministic in-memory execution of generators, for tests.

Mirrors the reference's simulator (`jepsen/src/jepsen/generator/test.clj:
50-182`): run a generator against a synthetic executor function
`complete(ctx, invoke) -> completion op`, with the module RNG pinned to
seed 45100 so op streams are exactly reproducible. The harnesses:

  quick        — every op succeeds instantly (zero latency)
  perfect      — every op succeeds in 10 ns
  perfect_info — every op crashes :info in 10 ns
  imperfect    — each thread cycles fail -> info -> ok, 10 ns each
"""

from __future__ import annotations

from typing import Callable, Optional

from . import (NEMESIS, PENDING, Context, context, fixed_rng,
               next_process, process_to_thread, validate)
from . import op as gen_op
from . import update as gen_update

DEFAULT_TEST: dict = {}
RAND_SEED = 45100
PERFECT_LATENCY = 10  # ns


def n_plus_nemesis_context(n: int) -> Context:
    return context({"concurrency": n})


def default_context() -> Context:
    return n_plus_nemesis_context(2)


def invocations(history: list) -> list:
    return [o for o in history if o.get("type") == "invoke"]


def simulate(ctx_or_gen, gen_or_complete, complete: Optional[Callable]
             = None, seed: int = RAND_SEED, test: dict = DEFAULT_TEST,
             max_ops: Optional[int] = None) -> list:
    """simulate([ctx,] gen, complete_fn) -> full history.

    Single-threaded discrete-event loop: take the generator's next
    invocation if it precedes every in-flight completion; otherwise apply
    the earliest completion first (freeing its thread, retiring crashed
    processes). Deterministic under the fixed seed. `test` is the test
    map handed to fn-generators; defaults to {} but suite-level
    simulations pass the real test map so generators that read test keys
    (nodes, concurrency, workload opts) behave as they would live.
    `max_ops` bounds the history: a generator whose state machine needs
    live client/nemesis side effects to advance (which a simulation
    cannot provide) would otherwise spin at a frozen virtual time.
    """
    if complete is None:
        ctx, gen, complete = default_context(), ctx_or_gen, gen_or_complete
    else:
        ctx, gen = ctx_or_gen, gen_or_complete

    with fixed_rng(seed):
        ops: list = []
        in_flight: list = []  # completions, kept sorted by time
        gen = validate(gen)
        def _finish():
            # in-flight sleeps/wakes stay out of the history, same as
            # the completion branch below and the interpreter's
            # goes_in_history()
            ops.extend(o for o in in_flight
                       if o.get("type") not in ("sleep", "log"))
            return ops

        while True:
            if max_ops is not None and len(ops) >= max_ops:
                return _finish()
            res = gen_op(gen, test, ctx)
            if res is None:
                return _finish()
            invoke, gen1 = res
            if invoke is not PENDING and (
                    not in_flight
                    or invoke["time"] <= in_flight[0]["time"]):
                # invocation precedes every in-flight completion
                thread = process_to_thread(ctx, invoke["process"])
                ctx = ctx.with_time(max(ctx.time, invoke["time"]))
                ctx = ctx.busy(thread)
                gen = gen_update(gen1, test, ctx, invoke)
                if invoke.get("type") == "sleep":
                    # mirror the interpreter (`interpreter.py:141-143`):
                    # the thread wakes value seconds later; sleeps stay
                    # out of the history
                    comp = dict(invoke)
                    comp["time"] = invoke["time"] + int(
                        invoke["value"] * 1e9)
                elif invoke.get("type") == "log":
                    comp = dict(invoke)
                else:
                    comp = complete(ctx, invoke)
                    ops.append(invoke)
                in_flight.append(comp)
                in_flight.sort(key=lambda o: o["time"])
            else:
                # must complete something first
                assert in_flight, \
                    "generator pending and nothing in flight"
                comp = in_flight.pop(0)
                thread = process_to_thread(ctx, comp["process"])
                ctx = ctx.with_time(max(ctx.time, comp["time"]))
                ctx = ctx.free(thread)
                gen = gen_update(gen, test, ctx, comp)
                if thread != NEMESIS and comp.get("type") == "info":
                    workers = dict(ctx.workers)
                    workers[thread] = next_process(ctx, thread)
                    ctx = ctx.with_workers(workers)
                if comp.get("type") not in ("sleep", "log"):
                    ops.append(comp)


def _ok(ctx, invoke):
    out = dict(invoke)
    out["type"] = "ok"
    return out


def quick_ops(ctx_or_gen, gen=None, test: dict = DEFAULT_TEST,
              max_ops: Optional[int] = None) -> list:
    if gen is None:
        ctx_or_gen, gen = default_context(), ctx_or_gen
    return simulate(ctx_or_gen, gen, _ok, test=test, max_ops=max_ops)


def quick(ctx_or_gen, gen=None) -> list:
    return invocations(quick_ops(ctx_or_gen, gen)
                       if gen is not None else quick_ops(ctx_or_gen))


def _latency(type_: str):
    def complete(ctx, invoke):
        out = dict(invoke)
        out["type"] = type_
        out["time"] = invoke["time"] + PERFECT_LATENCY
        return out
    return complete


def perfect_star(ctx_or_gen, gen=None) -> list:
    if gen is None:
        ctx_or_gen, gen = default_context(), ctx_or_gen
    return simulate(ctx_or_gen, gen, _latency("ok"))


def perfect(ctx_or_gen, gen=None) -> list:
    return invocations(perfect_star(ctx_or_gen, gen)
                       if gen is not None else perfect_star(ctx_or_gen))


def perfect_info(ctx_or_gen, gen=None) -> list:
    if gen is None:
        ctx_or_gen, gen = default_context(), ctx_or_gen
    return invocations(simulate(ctx_or_gen, gen, _latency("info")))


def imperfect(ctx_or_gen, gen=None) -> list:
    """Threads cycle fail -> info -> ok; returns the full history."""
    if gen is None:
        ctx_or_gen, gen = default_context(), ctx_or_gen
    state: dict = {}
    nxt = {None: "fail", "fail": "info", "info": "ok", "ok": "fail"}

    def complete(ctx, invoke):
        t = process_to_thread(ctx, invoke["process"])
        state[t] = nxt[state.get(t)]
        out = dict(invoke)
        out["type"] = state[t]
        out["time"] = invoke["time"] + PERFECT_LATENCY
        return out

    return simulate(ctx_or_gen, gen, complete)
