"""The interpreter: turns a pure generator into a real concurrent history
(reference `jepsen/src/jepsen/generator/interpreter.clj`).

One worker thread per logical thread (concurrency clients + the nemesis),
each fed through a 1-slot queue; a single-threaded scheduler loop owns the
context and the generator, polls completions at microsecond granularity
(`max-pending-interval` 1000 us, `interpreter.clj:166-170`), asks the
generator for ops, dispatches them, and journals invocations and
completions into the history.

Worker behavior (`interpreter.clj:99-164`):
  * any Throwable from a client invoke becomes an :info op (the op is
    indeterminate — it may or may not have taken effect),
  * crashed (non-nemesis) processes are retired and replaced with fresh
    process ids (`:233-236`),
  * crashed clients are closed and reopened for the new process, unless
    the client is `reusable` (`ClientWorker`, `:33-67`),
  * :sleep and :log ops are handled in the worker and kept out of the
    history (`goes-in-history?`, `:171-178`).

Run survivability (beyond the reference):
  * every history op is appended to a write-ahead journal
    (store.Journal) as it happens, so a crashed or SIGKILL'd run
    leaves a replayable prefix on disk,
  * a test-level 'op-timeout' (seconds; per-op 'deadline' override)
    bounds every invoke: an overdue op gets a synthetic :info
    completion, its process is retired exactly like a crash, the
    wedged worker thread is abandoned and replaced, and the late real
    completion — should the abandoned worker ever answer — is
    discarded. A hung client can therefore never wedge the run.

Online verification (checker/streaming.py): when the test carries an
'online-checker', every history op is offered to it live — via the
journal's subscribe feed when a journal exists (op flow rides the WAL
append), directly from the recording hook otherwise — and the
scheduler polls should_abort() before asking the generator for more
work: a confirmed mid-run violation stops new ops, drains the
outstanding ones (op-timeouts still bound them), and returns the
history checked so far, saving the rest of the cluster time.
"""

from __future__ import annotations

import logging
import queue
import threading
import time as _time
from typing import Optional

from .. import client as jclient
from .. import store
from ..history import History
from ..util import relative_time_nanos, secs_to_nanos
from . import (NEMESIS, PENDING, context, friendly_exceptions,
               next_process, process_to_thread, validate)
from . import op as gen_op
from . import update as gen_update

LOG = logging.getLogger("jepsen_tpu.interpreter")

MAX_PENDING_INTERVAL_US = 1000


class Worker:
    """Stateful per-thread executor; all calls come from one thread
    (`interpreter.clj:19-31`)."""

    def open(self, test: dict, wid) -> "Worker":
        return self

    def invoke(self, test: dict, op: dict) -> dict:
        raise NotImplementedError

    def close(self, test: dict) -> None:
        pass


class ClientWorker(Worker):
    """Owns the client for one thread; reopens it per fresh process unless
    the client is reusable (`interpreter.clj:33-67`)."""

    def __init__(self, node: str):
        self.node = node
        self.process = None
        self.client: Optional[jclient.Client] = None

    def invoke(self, test, op):
        while True:
            if self.process == op["process"] and self.client is not None:
                return self.client.invoke(test, op)
            if self.client is not None and \
                    jclient.is_reusable(self.client, test):
                self.process = op["process"]
                continue
            # new process, new client
            self.close(test)
            try:
                self.client = jclient.validate(test["client"]).open(
                    test, self.node)
                self.process = op["process"]
            except Exception as e:
                LOG.warning("error opening client: %s", e)
                self.client = None
                out = dict(op)
                out["type"] = "fail"
                out["error"] = ["no-client", str(e)]
                return out

    def close(self, test):
        if self.client is not None:
            try:
                self.client.close(test)
            finally:
                self.client = None


class NemesisWorker(Worker):
    """Validates completions so a misbehaving nemesis crashes its own op
    (becoming :info) instead of wedging the scheduler."""

    def invoke(self, test, op):
        from .. import nemesis as jnemesis
        return jnemesis.Validate(test["nemesis"]).invoke(test, op)


class RetiredNemesisWorker(Worker):
    """Seated when a nemesis invoke exceeds its deadline. There is only
    ONE nemesis object and it is single-threaded by contract — the
    wedged thread still owns it, so unlike a client (which gets a fresh
    connection) the nemesis cannot be reopened. Subsequent nemesis ops
    complete as :info without touching it: the run keeps terminating,
    fault injection honestly stops."""

    def invoke(self, test, op):
        out = dict(op)
        out["type"] = "info"
        out["error"] = ("nemesis-retired: a prior nemesis op exceeded "
                        "its deadline")
        return out


class ClientNemesisWorker(Worker):
    """Spawns ClientWorkers for integer ids (round-robin over nodes) and a
    NemesisWorker for the nemesis (`interpreter.clj:77-95`)."""

    def open(self, test, wid):
        if isinstance(wid, int):
            nodes = test.get("nodes") or ["local"]
            return ClientWorker(nodes[wid % len(nodes)]).open(test, wid)
        return NemesisWorker().open(test, wid)


def goes_in_history(op: dict) -> bool:
    return op.get("type") not in ("sleep", "log")


class _WorkerThread:
    """Completions are tagged with the emitting _WorkerThread so the
    scheduler can tell a live worker's answer from the late answer of
    an abandoned (timed-out) one and discard the latter."""

    def __init__(self, test: dict, out: queue.Queue, worker: Worker, wid):
        self.id = wid
        self.inbox: queue.Queue = queue.Queue(1)
        self.test = test
        self.out = out
        self.worker = worker
        self.abandoned = False
        self.thread = threading.Thread(
            target=self._run, name=f"jepsen-worker-{wid}", daemon=True)
        self.thread.start()

    def _run(self):
        test = self.test
        worker = self.worker.open(test, self.id)
        try:
            while True:
                op = self.inbox.get()
                t = op.get("type")
                if t == "exit":
                    return
                try:
                    if t == "sleep":
                        _time.sleep(op["value"])
                        self.out.put((self, op))
                    elif t == "log":
                        LOG.info("%s", op["value"])
                        self.out.put((self, op))
                    else:
                        self.out.put((self, worker.invoke(test, op)))
                except BaseException as e:
                    LOG.warning("process %r crashed: %s",
                                op.get("process"), e)
                    out = dict(op)
                    out["type"] = "info"
                    out["error"] = f"indeterminate: {e}"
                    self.out.put((self, out))
        finally:
            worker.close(test)


def _op_deadline(test: dict, op: dict, now: int):
    """(absolute-deadline-ns, timeout-s) for an op dispatched at `now`,
    or None when it is unbounded. The per-op 'deadline' key (seconds
    from dispatch) overrides the test-level 'op-timeout'; an explicit
    'deadline': None exempts one op (a deliberately long nemesis
    transition) from the test-level bound. Anchored at dispatch time,
    not the generator-scheduled op['time'], so scheduler lag never
    eats into the client's budget. :sleep/:log ops complete
    deterministically and are never deadlined."""
    if op.get("type") in ("sleep", "log"):
        return None
    t = op.get("deadline", test.get("op-timeout"))
    if t is None:
        return None
    return now + secs_to_nanos(t), t


def run(test: dict) -> History:
    """Evaluate all ops from test['generator'], applying them with
    test['client'] / test['nemesis']. Returns the history
    (`interpreter.clj:181-310`). History ops are journaled to
    journal.jsonl as they happen (when the test has a store identity),
    and in-flight ops are bounded by 'op-timeout' / per-op 'deadline'
    so a wedged client can't hang the run — see the module docstring."""
    ctx = context(test)
    completions: queue.Queue = queue.Queue()
    workers = {t: _WorkerThread(test, completions, ClientNemesisWorker(), t)
               for t in ctx.workers}
    gen = validate(friendly_exceptions(test.get("generator")))
    outstanding = 0
    poll_timeout_us = 0
    history: list = []
    # thread -> (op, absolute-deadline-ns, timeout-s); only ops that
    # actually carry a deadline are tracked, so runs without
    # 'op-timeout' pay nothing on the hot path
    deadlines: dict = {}
    op_timeout = test.get("op-timeout")
    journal = store.open_journal(test)
    online = test.get("online-checker")
    hook = None
    if online is not None:
        if journal is not None:
            # live ops ride the WAL append path (Journal.subscribe) —
            # one feed, shared with the crash-survivability journal
            journal.subscribe(online.offer)
        else:
            hook = online.offer
    aborted = False

    def record(o: dict) -> None:
        history.append(o)
        if journal is not None:
            journal.append(o)
        elif hook is not None:
            hook(o)

    def deadline_capped(us: int, now: int) -> int:
        # never sleep past the nearest in-flight deadline
        if not deadlines:
            return us
        nearest = min(dl for _, dl, _ in deadlines.values())
        return max(1, min(us, (nearest - now) // 1000))

    def settle(thread, op2: dict, now: int) -> dict:
        """The one completion transition, shared by real completions
        and synthetic op-timeout :infos so the two can never diverge:
        free the thread, update the generator, retire the process on
        :info, journal, decrement outstanding."""
        nonlocal ctx, gen, outstanding
        if deadlines:
            deadlines.pop(thread, None)
        op2 = dict(op2)
        op2["time"] = now
        ctx = ctx.with_time(now).free(thread)
        # update sees the free thread but the *old* process so
        # thread->process still resolves this event
        gen = gen_update(gen, test, ctx, op2)
        if thread != NEMESIS and op2.get("type") == "info":
            workers_map = dict(ctx.workers)
            workers_map[thread] = next_process(ctx, thread)
            ctx = ctx.with_workers(workers_map)
        if goes_in_history(op2):
            record(op2)
        outstanding -= 1
        return op2

    try:
        while True:
            # Completions first: they're latency-sensitive — waiting
            # introduces false concurrency.
            try:
                if poll_timeout_us > 0:
                    src, op2 = completions.get(
                        timeout=poll_timeout_us / 1e6)
                else:
                    src, op2 = completions.get_nowait()
            except queue.Empty:
                src = op2 = None

            if op2 is not None:
                if src.abandoned or src is not workers.get(src.id):
                    # a timed-out worker eventually answered: its op was
                    # already journaled as :info — the late result must
                    # be discarded, not double-completed
                    LOG.info("discarding late completion from retired "
                             "worker %r: %r", src.id, op2.get("f"))
                    poll_timeout_us = 0
                    continue
                settle(process_to_thread(ctx, op2["process"]), op2,
                       relative_time_nanos())
                poll_timeout_us = 0
                continue

            # Overdue ops — checked only once the completion queue is
            # drained, so an answer that beat its deadline is never
            # discarded in favor of a synthetic timeout. A wedged
            # worker still can't stall the run: an empty poll lands
            # here within MAX_PENDING_INTERVAL_US.
            if deadlines:
                now = relative_time_nanos()
                overdue = [(t, o, ts) for t, (o, dl, ts)
                           in deadlines.items() if now >= dl]
                if overdue:
                    for thread, op1, timeout_s in overdue:
                        LOG.warning(
                            "process %r exceeded its %.3gs op deadline; "
                            "recording :info and retiring worker %r",
                            op1.get("process"), timeout_s, thread)
                        settle(thread,
                               {**op1, "type": "info",
                                "error": ["op-timeout", timeout_s]},
                               now)
                        # abandon the wedged worker (its late answer is
                        # discarded above) and seat a replacement; if it
                        # ever unwedges, the queued exit lets it close.
                        # Clients reopen fresh for the new process; the
                        # single shared nemesis can't, so its
                        # replacement answers :info without touching it
                        old = workers[thread]
                        old.abandoned = True
                        # displace any undelivered op first (the worker
                        # may have wedged before dequeuing it) so the
                        # exit sentinel always lands and close() runs
                        try:
                            old.inbox.get_nowait()
                        except queue.Empty:
                            pass
                        try:
                            old.inbox.put_nowait({"type": "exit"})
                        except queue.Full:
                            pass
                        replacement = (RetiredNemesisWorker()
                                       if thread == NEMESIS
                                       else ClientNemesisWorker())
                        workers[thread] = _WorkerThread(
                            test, completions, replacement, thread)
                    poll_timeout_us = 0
                    continue

            now = relative_time_nanos()
            ctx = ctx.with_time(now)
            if online is not None and not aborted \
                    and online.should_abort():
                aborted = True
                LOG.warning(
                    "online checker confirmed a violation; aborting "
                    "the run early (%d ops outstanding will drain)",
                    outstanding)
            res = None if aborted else gen_op(gen, test, ctx)
            if res is None:
                if outstanding > 0:
                    poll_timeout_us = MAX_PENDING_INTERVAL_US
                    continue
                for w in workers.values():
                    w.inbox.put({"type": "exit"})
                for w in workers.values():
                    w.thread.join()
                return History(history)

            op, gen1 = res
            if op is PENDING:
                # keep the un-advanced generator, as the reference does
                # (interpreter.clj:263-265)
                poll_timeout_us = MAX_PENDING_INTERVAL_US
                continue
            if now < op["time"]:
                # not yet time for this op; sleep-poll until then
                poll_timeout_us = deadline_capped(
                    max(1, (op["time"] - now) // 1000), now)
                continue
            thread = process_to_thread(ctx, op["process"])
            workers[thread].inbox.put(op)
            ctx = ctx.with_time(op["time"]).busy(thread)
            gen = gen_update(gen1, test, ctx, op)
            if goes_in_history(op):
                record(op)
            if op_timeout is not None or "deadline" in op:
                dl = _op_deadline(test, op, now)
                if dl is not None:
                    deadlines[thread] = (op, dl[0], dl[1])
            outstanding += 1
            poll_timeout_us = 0
    except BaseException:
        LOG.info("shutting down workers after abnormal exit")
        for w in workers.values():
            # the 1-slot inbox may still hold an undelivered op; displace
            # it so the exit sentinel always lands
            try:
                w.inbox.get_nowait()
            except queue.Empty:
                pass
            try:
                w.inbox.put_nowait({"type": "exit"})
            except queue.Full:
                pass
        for w in workers.values():
            w.thread.join(timeout=5)
        raise
    finally:
        # flush + close the write-ahead journal on every exit path: the
        # on-disk prefix is the run's crash-surviving record
        if journal is not None:
            journal.close()
