"""The interpreter: turns a pure generator into a real concurrent history
(reference `jepsen/src/jepsen/generator/interpreter.clj`).

One worker thread per logical thread (concurrency clients + the nemesis),
each fed through a 1-slot queue; a single-threaded scheduler loop owns the
context and the generator, polls completions at microsecond granularity
(`max-pending-interval` 1000 us, `interpreter.clj:166-170`), asks the
generator for ops, dispatches them, and journals invocations and
completions into the history.

Worker behavior (`interpreter.clj:99-164`):
  * any Throwable from a client invoke becomes an :info op (the op is
    indeterminate — it may or may not have taken effect),
  * crashed (non-nemesis) processes are retired and replaced with fresh
    process ids (`:233-236`),
  * crashed clients are closed and reopened for the new process, unless
    the client is `reusable` (`ClientWorker`, `:33-67`),
  * :sleep and :log ops are handled in the worker and kept out of the
    history (`goes-in-history?`, `:171-178`).
"""

from __future__ import annotations

import logging
import queue
import threading
import time as _time
from typing import Optional

from .. import client as jclient
from ..history import History
from ..util import relative_time_nanos
from . import (NEMESIS, PENDING, context, friendly_exceptions,
               next_process, process_to_thread, validate)
from . import op as gen_op
from . import update as gen_update

LOG = logging.getLogger("jepsen_tpu.interpreter")

MAX_PENDING_INTERVAL_US = 1000


class Worker:
    """Stateful per-thread executor; all calls come from one thread
    (`interpreter.clj:19-31`)."""

    def open(self, test: dict, wid) -> "Worker":
        return self

    def invoke(self, test: dict, op: dict) -> dict:
        raise NotImplementedError

    def close(self, test: dict) -> None:
        pass


class ClientWorker(Worker):
    """Owns the client for one thread; reopens it per fresh process unless
    the client is reusable (`interpreter.clj:33-67`)."""

    def __init__(self, node: str):
        self.node = node
        self.process = None
        self.client: Optional[jclient.Client] = None

    def invoke(self, test, op):
        while True:
            if self.process == op["process"] and self.client is not None:
                return self.client.invoke(test, op)
            if self.client is not None and \
                    jclient.is_reusable(self.client, test):
                self.process = op["process"]
                continue
            # new process, new client
            self.close(test)
            try:
                self.client = jclient.validate(test["client"]).open(
                    test, self.node)
                self.process = op["process"]
            except Exception as e:
                LOG.warning("error opening client: %s", e)
                self.client = None
                out = dict(op)
                out["type"] = "fail"
                out["error"] = ["no-client", str(e)]
                return out

    def close(self, test):
        if self.client is not None:
            try:
                self.client.close(test)
            finally:
                self.client = None


class NemesisWorker(Worker):
    """Validates completions so a misbehaving nemesis crashes its own op
    (becoming :info) instead of wedging the scheduler."""

    def invoke(self, test, op):
        from .. import nemesis as jnemesis
        return jnemesis.Validate(test["nemesis"]).invoke(test, op)


class ClientNemesisWorker(Worker):
    """Spawns ClientWorkers for integer ids (round-robin over nodes) and a
    NemesisWorker for the nemesis (`interpreter.clj:77-95`)."""

    def open(self, test, wid):
        if isinstance(wid, int):
            nodes = test.get("nodes") or ["local"]
            return ClientWorker(nodes[wid % len(nodes)]).open(test, wid)
        return NemesisWorker().open(test, wid)


def goes_in_history(op: dict) -> bool:
    return op.get("type") not in ("sleep", "log")


class _WorkerThread:
    def __init__(self, test: dict, out: queue.Queue, worker: Worker, wid):
        self.id = wid
        self.inbox: queue.Queue = queue.Queue(1)
        self.test = test
        self.out = out
        self.worker = worker
        self.thread = threading.Thread(
            target=self._run, name=f"jepsen-worker-{wid}", daemon=True)
        self.thread.start()

    def _run(self):
        test = self.test
        worker = self.worker.open(test, self.id)
        try:
            while True:
                op = self.inbox.get()
                t = op.get("type")
                if t == "exit":
                    return
                try:
                    if t == "sleep":
                        _time.sleep(op["value"])
                        self.out.put(op)
                    elif t == "log":
                        LOG.info("%s", op["value"])
                        self.out.put(op)
                    else:
                        self.out.put(worker.invoke(test, op))
                except BaseException as e:
                    LOG.warning("process %r crashed: %s",
                                op.get("process"), e)
                    out = dict(op)
                    out["type"] = "info"
                    out["error"] = f"indeterminate: {e}"
                    self.out.put(out)
        finally:
            worker.close(test)


def run(test: dict) -> History:
    """Evaluate all ops from test['generator'], applying them with
    test['client'] / test['nemesis']. Returns the history
    (`interpreter.clj:181-310`)."""
    ctx = context(test)
    completions: queue.Queue = queue.Queue()
    workers = [_WorkerThread(test, completions, ClientNemesisWorker(), t)
               for t in ctx.workers]
    inboxes = {w.id: w.inbox for w in workers}
    gen = validate(friendly_exceptions(test.get("generator")))
    outstanding = 0
    poll_timeout_us = 0
    history: list = []

    try:
        while True:
            # Completions first: they're latency-sensitive — waiting
            # introduces false concurrency.
            try:
                if poll_timeout_us > 0:
                    op2 = completions.get(timeout=poll_timeout_us / 1e6)
                else:
                    op2 = completions.get_nowait()
            except queue.Empty:
                op2 = None

            if op2 is not None:
                thread = process_to_thread(ctx, op2["process"])
                now = relative_time_nanos()
                op2 = dict(op2)
                op2["time"] = now
                ctx = ctx.with_time(now).free(thread)
                # update sees the free thread but the *old* process so
                # thread->process still resolves this event
                gen = gen_update(gen, test, ctx, op2)
                if thread != NEMESIS and op2.get("type") == "info":
                    workers_map = dict(ctx.workers)
                    workers_map[thread] = next_process(ctx, thread)
                    ctx = ctx.with_workers(workers_map)
                if goes_in_history(op2):
                    history.append(op2)
                outstanding -= 1
                poll_timeout_us = 0
                continue

            now = relative_time_nanos()
            ctx = ctx.with_time(now)
            res = gen_op(gen, test, ctx)
            if res is None:
                if outstanding > 0:
                    poll_timeout_us = MAX_PENDING_INTERVAL_US
                    continue
                for w in workers:
                    w.inbox.put({"type": "exit"})
                for w in workers:
                    w.thread.join()
                return History(history)

            op, gen1 = res
            if op is PENDING:
                # keep the un-advanced generator, as the reference does
                # (interpreter.clj:263-265)
                poll_timeout_us = MAX_PENDING_INTERVAL_US
                continue
            if now < op["time"]:
                # not yet time for this op; sleep-poll until then
                poll_timeout_us = max(1, (op["time"] - now) // 1000)
                continue
            thread = process_to_thread(ctx, op["process"])
            inboxes[thread].put(op)
            ctx = ctx.with_time(op["time"]).busy(thread)
            gen = gen_update(gen1, test, ctx, op)
            if goes_in_history(op):
                history.append(op)
            outstanding += 1
            poll_timeout_us = 0
    except BaseException:
        LOG.info("shutting down workers after abnormal exit")
        for w in workers:
            # the 1-slot inbox may still hold an undelivered op; displace
            # it so the exit sentinel always lands
            try:
                w.inbox.get_nowait()
            except queue.Empty:
                pass
            try:
                w.inbox.put_nowait({"type": "exit"})
            except queue.Full:
                pass
        for w in workers:
            w.thread.join(timeout=5)
        raise
