"""Key-sharded ("independent") tests: lift a test over a single piece of
state into a test over many independent pieces of state, checked separately.

Reference: `jepsen/src/jepsen/independent.clj`. Linearizability search is
exponential in history length, so instead of one long history over one key,
run many short histories over independent keys — op values become `(k, v)`
tuples, generators stamp keys onto a base generator's values, and the
checker splits the history per key and checks each subhistory.

The TPU twist (SURVEY.md §2.4): per-key subhistories are exactly the
batchable axis. When the subchecker is a device-model linearizability
checker, all keys are encoded into one stacked array batch and checked in a
single vmapped kernel call (`checker/wgl.py: analysis_tpu_batch`), sharded
over the device mesh — instead of the reference's `bounded-pmap` over JVM
threads (`independent.clj:266+`).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Iterable

from . import generator as gen
from .checker import Checker, check_safe, coerce, merge_valid
from .generator import Gen, PENDING
from .history import History, history as as_history
from .util import bounded_pmap


class KV(tuple):
    """A `(key, value)` tuple distinguishable from plain pairs
    (reference `independent.clj:21-29` Tuple type)."""

    __slots__ = ()

    def __new__(cls, k, v):
        return super().__new__(cls, (k, v))

    @property
    def key(self):
        return self[0]

    @property
    def value(self):
        return self[1]

    def __repr__(self):
        return f"KV({self[0]!r}, {self[1]!r})"


def ktuple(k, v) -> KV:
    """Construct an independent key/value pair."""
    return KV(k, v)


def is_tuple(x) -> bool:
    return isinstance(x, KV)


def tuple_key(op: dict):
    """The key of an op whose value is a KV, else None."""
    v = op.get("value")
    return v.key if isinstance(v, KV) else None


def tuple_value(op: dict):
    v = op.get("value")
    return v.value if isinstance(v, KV) else None


def _wrap(k) -> Callable[[dict], dict]:
    def f(op: dict) -> dict:
        op = dict(op)
        op["value"] = KV(k, op.get("value"))
        return op
    return f


def tuple_gen(k, g):
    """Wrap a generator so every op's value becomes (k, v)."""
    return gen.map(_wrap(k), g)


# ---------------------------------------------------------------------------
# Generators
# ---------------------------------------------------------------------------

class _KeyStream:
    """Deterministic, memoizing view of a (possibly infinite) key sequence.

    Generator state stays pure — cursors are plain ints held in generator
    records — while realized keys are cached here. Realizing key i is
    deterministic, so sharing the memo across generator copies is safe.
    """

    def __init__(self, keys: Iterable):
        self._it = iter(keys)
        self._memo: list = []
        self._done = False

    def get(self, i: int):
        """The i-th key, or None when the stream is exhausted before i."""
        while len(self._memo) <= i and not self._done:
            try:
                self._memo.append(next(self._it))
            except StopIteration:
                self._done = True
        return self._memo[i] if i < len(self._memo) else None


@dataclasses.dataclass(frozen=True)
class SequentialGenerator(Gen):
    """One key at a time: runs `fgen(k)` (with values wrapped in (k, v))
    for each key in sequence (`independent.clj:31-47`)."""
    keys: Any           # _KeyStream
    fgen: Callable
    i: int              # cursor into keys
    current: Any        # active generator or None (not yet built)
    started: bool

    def _ensure(self):
        if self.started:
            return self
        k = self.keys.get(self.i)
        if k is None:
            return None
        return dataclasses.replace(
            self, current=tuple_gen(k, self.fgen(k)), started=True)

    def op(self, test, ctx):
        me = self._ensure()
        while me is not None:
            res = gen.op(me.current, test, ctx)
            if res is not None:
                return res[0], dataclasses.replace(me, current=res[1])
            me = dataclasses.replace(me, i=me.i + 1, started=False)
            me = me._ensure()
        return None

    def update(self, test, ctx, event):
        me = self._ensure()
        if me is None:
            return self
        return dataclasses.replace(
            me, current=gen.update(me.current, test, ctx, event))


def sequential_generator(keys: Iterable, fgen: Callable) -> Gen:
    """For each key k in sequence, runs fgen(k) with values wrapped as
    (k, v) tuples."""
    return SequentialGenerator(_KeyStream(keys), fgen, 0, None, False)


@dataclasses.dataclass(frozen=True)
class ConcurrentGenerator(Gen):
    """Partitions client threads into groups of n; each group concurrently
    works through the shared key sequence, running an independent
    `fgen(k)` per key (`independent.clj:103-239`).

    State per group: (next-key-cursor-claim handled via `cursor`, the
    group's active key index, and its active generator). Groups claim key
    indices from a shared monotone cursor so no two groups run the same
    key.
    """
    n: int              # threads per group
    keys: Any           # _KeyStream
    fgen: Callable
    cursor: int         # next unclaimed key index
    groups: tuple       # ((group_id, key_index, gen) ...), active groups

    def _group_of(self, thread) -> int | None:
        if not isinstance(thread, int):
            return None  # nemesis never participates
        return thread // self.n

    def _group_pred(self, gid: int) -> Callable:
        lo, hi = gid * self.n, (gid + 1) * self.n
        return lambda t: isinstance(t, int) and lo <= t < hi

    def _group_state(self, gid: int):
        for g, ki, gg in self.groups:
            if g == gid:
                return ki, gg
        return None

    def _with_group(self, gid: int, ki, g, cursor=None):
        groups = tuple((gg, kk, xx) for gg, kk, xx in self.groups
                       if gg != gid)
        if g is not None:
            groups = groups + ((gid, ki, g),)
        return dataclasses.replace(
            self, groups=groups,
            cursor=self.cursor if cursor is None else cursor)

    def op(self, test, ctx):
        client_threads = sorted(t for t in ctx.workers if isinstance(t, int))
        if not client_threads:
            return None
        if len(client_threads) % self.n != 0:
            raise ValueError(
                f"concurrent_generator requires the client thread count "
                f"({len(client_threads)}) to be divisible by n={self.n}")
        gids = sorted({t // self.n for t in client_threads})
        me = self
        best = None
        exhausted = 0
        for gid in gids:
            sub = gen.Context(
                ctx.time,
                tuple(t for t in ctx.free_threads
                      if me._group_pred(gid)(t)),
                {t: p for t, p in ctx.workers.items()
                 if me._group_pred(gid)(t)})
            # Claim keys until this group has a generator that yields —
            # empty per-key generators must not end the group while the
            # key stream has more keys.
            res = None
            ki = None
            while True:
                st = me._group_state(gid)
                if st is None:
                    k = me.keys.get(me.cursor)
                    if k is None:
                        exhausted += 1
                        break
                    me = me._with_group(gid, me.cursor,
                                        tuple_gen(k, me.fgen(k)),
                                        cursor=me.cursor + 1)
                    continue
                ki, g = st
                res = gen.op(g, test, sub)
                if res is None:
                    me = me._with_group(gid, None, None)  # key done
                    continue
                break
            if res is None:
                continue
            o, g1 = res
            cand = {"op": o, "gen": me._with_group(gid, ki, g1,
                                                   cursor=me.cursor),
                    "weight": self.n}
            best = gen._soonest(best, cand)
        if best is not None:
            # each candidate's generator snapshot carries the shared
            # cursor/groups state as of its build; losing candidates'
            # claims are deterministically redone on the next call
            return best["op"], best["gen"]
        if exhausted == len(gids):
            return None
        return PENDING, me

    def update(self, test, ctx, event):
        gid = self._group_of(
            gen.process_to_thread(ctx, event.get("process")))
        if gid is None:
            return self
        st = self._group_state(gid)
        if st is None:
            return self
        ki, g = st
        sub = gen.Context(
            ctx.time,
            tuple(t for t in ctx.free_threads if self._group_pred(gid)(t)),
            {t: p for t, p in ctx.workers.items()
             if self._group_pred(gid)(t)})
        return self._with_group(gid, ki, gen.update(g, test, sub, event))


def concurrent_generator(n: int, keys: Iterable, fgen: Callable) -> Gen:
    """n threads per key; groups of threads run independent keys
    concurrently, pulling fresh keys as theirs exhaust. Client thread
    count must be divisible by n."""
    return ConcurrentGenerator(n, _KeyStream(keys), fgen, 0, ())


# ---------------------------------------------------------------------------
# History splitting
# ---------------------------------------------------------------------------

def history_keys(hist) -> list:
    """Every key present in the history, in order of first appearance
    (`independent.clj:240`)."""
    seen = []
    seen_set = set()
    for o in as_history(hist):
        v = o.get("value")
        if isinstance(v, KV) and v.key not in seen_set:
            seen_set.add(v.key)
            seen.append(v.key)
    return seen


def subhistory(k, hist) -> History:
    """The subhistory for key k: ops with that key get their value
    unwrapped; non-client ops (nemesis) pass through; other clients' ops
    are dropped (`independent.clj:252`)."""
    out = []
    for o in as_history(hist):
        v = o.get("value")
        if isinstance(v, KV):
            if v.key == k:
                o = dict(o)
                o["value"] = v.value
                out.append(o)
        elif not isinstance(o.get("process"), int):
            out.append(o)  # nemesis ops belong to every subhistory
    return History(out)


# ---------------------------------------------------------------------------
# Checker
# ---------------------------------------------------------------------------

class IndependentChecker(Checker):
    """Applies a subchecker to each key's subhistory; a key's failure
    fails the whole test (`independent.clj:266+`).

    Device-model linearizability subcheckers take the batched TPU path:
    one vmapped kernel call over all keys instead of per-key host checks.

    strict_device=True turns a failed device batch into a raised error
    instead of a silent host fallback — use in tests/CI so a broken
    kernel can't hide behind the (correct but slow) host oracle.
    """

    def __init__(self, subchecker, strict_device: bool = False):
        self.subchecker = coerce(subchecker)
        self.strict_device = strict_device

    def _batched_tpu(self, test, hist, opts, ks):
        """Batched per-key device check, or None if not applicable."""
        from .checker.linear import Linearizable
        c = self.subchecker
        if not isinstance(c, Linearizable):
            return None
        if c.model is None or c.model.device_model is None:
            return None
        if c.algorithm not in ("auto", "tpu", "linear", "wgl",
                               "competition", "tpu-wgl"):
            return None
        from .checker.wgl import analysis_tpu_batch
        subs = [subhistory(k, hist) for k in ks]
        try:
            return dict(zip(ks, analysis_tpu_batch(c.model, subs,
                                                   **c.opts)))
        except Exception:
            if self.strict_device:
                raise
            import logging
            logging.getLogger(__name__).warning(
                "batched device check failed; falling back to per-key "
                "host checks (pass strict_device=True to raise instead)",
                exc_info=True)
            return None

    def check(self, test, hist, opts):
        hist = as_history(hist).index()
        ks = history_keys(hist)
        results = self._batched_tpu(test, hist, opts, ks)
        if results is None:
            def one(k):
                sub_opts = dict(opts)
                sub_opts["history-key"] = k
                return k, check_safe(self.subchecker, test,
                                     subhistory(k, hist), sub_opts)
            results = dict(bounded_pmap(one, ks, max_workers=8))
        valids = {k: (r or {}).get("valid?", True)
                  for k, r in results.items()}
        failures = [k for k, v in valids.items() if v is False]
        return {
            "valid?": merge_valid(valids.values()) if valids else True,
            "results": results,
            "failures": failures,
        }


def checker(subchecker, strict_device: bool = False) -> Checker:
    return IndependentChecker(subchecker, strict_device=strict_device)
