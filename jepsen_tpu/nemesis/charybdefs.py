"""CharybdeFS driver: filesystem fault injection via a FUSE passthrough.

Reference: `charybdefs/src/jepsen/charybdefs.clj` — builds thrift from
source (Ubuntu lacks the C++ library; versions can't be mixed, :7-38),
clones + cmake-builds scylladb/charybdefs, mounts the fault-injecting
filesystem at /faulty backed by /real (:40-65), and drives fault recipes
break-all / break-one-percent / clear (:67-85). DBs under test point
their data dirs at /faulty; faults then surface as EIO etc.
"""

from __future__ import annotations

import logging

from .. import control as c
from ..control import util as cu
from ..os_ import debian
from . import Nemesis

log = logging.getLogger(__name__)

THRIFT_URL = "http://www-eu.apache.org/dist/thrift/0.10.0/" \
             "thrift-0.10.0.tar.gz"
THRIFT_DIR = "/opt/thrift"
CHARYBDEFS_REPO = "https://github.com/scylladb/charybdefs.git"
CHARYBDEFS_DIR = "/opt/charybdefs"


def install_thrift() -> None:
    """Build thrift (compiler + C++ + python libs) from source
    (`charybdefs.clj:7-38`)."""
    if cu.exists("/usr/bin/thrift"):
        return
    with c.su():
        debian.install(["automake", "bison", "flex", "g++", "git",
                        "libboost-all-dev", "libevent-dev", "libssl-dev",
                        "libtool", "make", "pkg-config",
                        "python-setuptools", "libglib2.0-dev"])
        log.info("Building thrift (this takes several minutes)")
        cu.install_archive(THRIFT_URL, THRIFT_DIR)
        with c.cd(THRIFT_DIR):
            c.exec_("./configure", "--prefix=/usr")
            c.exec_("make", "-j4")
            c.exec_("make", "install")
        with c.cd(f"{THRIFT_DIR}/lib/py"):
            c.exec_("python", "setup.py", "install")


def install() -> None:
    """Ensure CharybdeFS is built and mounted at /faulty (backed by
    /real) on the current node (`charybdefs.clj:40-65`)."""
    install_thrift()
    bin = f"{CHARYBDEFS_DIR}/charybdefs"
    if not cu.exists(bin):
        with c.su():
            debian.install(["build-essential", "cmake", "libfuse-dev",
                            "fuse"])
            c.exec_("mkdir", "-p", CHARYBDEFS_DIR)
            c.exec_("chmod", "777", CHARYBDEFS_DIR)
        c.exec_("git", "clone", "--depth", 1, CHARYBDEFS_REPO,
                CHARYBDEFS_DIR)
        with c.cd(CHARYBDEFS_DIR):
            c.exec_("thrift", "-r", "--gen", "cpp", "server.thrift")
            c.exec_("cmake", "CMakeLists.txt")
            c.exec_("make")
    with c.su():
        c.exec_("modprobe", "fuse")
        c.exec_("umount", "/faulty", c.lit("||"), "/bin/true")
        c.exec_("mkdir", "-p", "/real", "/faulty")
        c.exec_(bin, "/faulty",
                "-oallow_other,modules=subdir,subdir=/real")
        c.exec_("chmod", "777", "/real", "/faulty")


def _cookbook(flag: str) -> None:
    with c.cd(f"{CHARYBDEFS_DIR}/cookbook"):
        c.exec_("./recipes", flag)


def break_all() -> None:
    """All fs operations fail with EIO (`charybdefs.clj:72-75`)."""
    _cookbook("--io-error")


def break_one_percent() -> None:
    """1% of disk operations fail (`charybdefs.clj:77-80`)."""
    _cookbook("--probability")


def clear() -> None:
    """Clear a previous failure injection (`charybdefs.clj:82-85`)."""
    _cookbook("--clear")


class CharybdeFSNemesis(Nemesis):
    """Nemesis driving the recipes: ops {"f": "break-all" |
    "break-one-percent" | "clear-fs-faults", "value": node-list|None}."""

    def fs(self):
        return {"break-all", "break-one-percent", "clear-fs-faults"}

    def setup(self, test):
        c.on_nodes(test, lambda t, n: install())
        return self

    def invoke(self, test, op):
        action = {"break-all": break_all,
                  "break-one-percent": break_one_percent,
                  "clear-fs-faults": clear}[op["f"]]
        res = c.on_nodes(test, lambda t, n: action(),
                         nodes=op.get("value"))
        return {**op, "value": res}

    def teardown(self, test):
        try:
            c.on_nodes(test, lambda t, n: clear())
        except Exception:  # noqa: BLE001 — teardown is best-effort
            pass


def nemesis() -> CharybdeFSNemesis:
    return CharybdeFSNemesis()
