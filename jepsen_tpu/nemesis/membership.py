"""Membership nemesis: grow/shrink-cluster state machine.

Reference: `jepsen/src/jepsen/nemesis/membership.clj` (view-merging loop
refreshing each node's view every 5 s, pending-op resolution to a fixed
point, nemesis + generator pair) and `membership/state.clj` (the State
protocol users implement per-database).

The cluster state is {"node-views": {node: view}, "view": merged,
"pending": set of (op, op') pairs} plus whatever the State carries.
"""

from __future__ import annotations

import logging
import threading
from typing import Any

from .. import generator as gen
from . import Nemesis

log = logging.getLogger(__name__)

NODE_VIEW_INTERVAL = 5  # seconds between node-view refreshes (`:59-61`)


class State:
    """Per-database membership state machine (`membership/state.clj`).

    Implementations are immutable-style: methods return new states (or
    None where documented)."""

    def setup(self, test: dict) -> "State":
        """One-time initialization; returns a new state."""
        return self

    def node_view(self, test: dict, node: str) -> Any:
        """The cluster view from one node; None = unknown (ignored)."""
        return None

    def merge_views(self, test: dict) -> Any:
        """Derive the authoritative view from self.node_views."""
        return None

    def fs(self) -> set:
        """All op :f's this state machine can generate."""
        return set()

    def op(self, test: dict):
        """An op we could perform next, or "pending" if none available."""
        return "pending"

    def invoke(self, test: dict, op: dict) -> dict:
        """Apply a generated op; returns the completion."""
        return dict(op)

    def resolve(self, test: dict) -> "State":
        """Evolve toward a fixed point; called repeatedly."""
        return self

    def resolve_op(self, test: dict, op_pair: tuple) -> "State | None":
        """If (op, op') has resolved, return a new state; else None."""
        return None

    def teardown(self, test: dict) -> None:
        pass


def _op_key(op_pair) -> str:
    import json

    return json.dumps(op_pair, sort_keys=True, default=str)


class _Shared:
    """The mutable cell the nemesis, generator, and view threads share
    (the reference's state atom)."""

    def __init__(self, state: State):
        self.lock = threading.RLock()
        self.state = state
        self.node_views: dict = {}
        self.view: Any = None
        self.pending: dict[str, tuple] = {}  # key -> (op, op')


def _resolve(shared: _Shared, test: dict, opts: dict) -> None:
    """state.resolve + resolve-op over pending until fixed point
    (`membership.clj:79-107`). Caller holds the lock."""
    for _ in range(100):  # fixed-point iteration, bounded
        before_state = shared.state
        before_pending = dict(shared.pending)
        shared.state = shared.state.resolve(test) or shared.state
        for key, pair in list(shared.pending.items()):
            state2 = shared.state.resolve_op(test, pair)
            if state2 is not None:
                if opts.get("log-resolve-op"):
                    log.info("Resolved pending membership operation: %s",
                             pair)
                shared.state = state2
                shared.pending.pop(key, None)
        if shared.state is before_state and \
                shared.pending == before_pending:
            return


class MembershipNemesis(Nemesis):
    """Drives a State machine; keeps per-node views fresh from
    background threads (`membership.clj:159-210`)."""

    def __init__(self, state: State, opts: dict | None = None):
        self.shared = _Shared(state)
        self.opts = opts or {}
        self._running = threading.Event()
        self._threads: list[threading.Thread] = []

    def fs(self):
        return self.shared.state.fs()

    def _update_node_view(self, test, node):
        """(`membership.clj:109-140`)"""
        from .. import control as c

        with c.on(node):
            nv = self.shared.state.node_view(test, node)
        if nv is None:
            return
        with self.shared.lock:
            old = self.shared.node_views.get(node)
            if self.opts.get("log-node-views") and nv != old:
                log.info("New view from %s: %s", node, nv)
            self.shared.node_views[node] = nv
            # expose node_views on the state so merge_views can see them
            self.shared.state.node_views = dict(self.shared.node_views)
            view = self.shared.state.merge_views(test)
            changed = view != self.shared.view
            self.shared.view = view
            self.shared.state.view = view
            _resolve(self.shared, test, self.opts)
            if changed and self.opts.get("log-view"):
                log.info("New membership view from %s: %s", node, view)

    def _view_loop(self, test, node):
        while self._running.is_set():
            try:
                self._update_node_view(test, node)
            except Exception as e:  # noqa: BLE001 — keep refreshing
                log.warning("Node view updater caught %s; will retry", e)
            self._running.wait(0)  # yield
            for _ in range(NODE_VIEW_INTERVAL * 10):
                if not self._running.is_set():
                    return
                threading.Event().wait(0.1)

    def setup(self, test):
        with self.shared.lock:
            self.shared.state.node_views = {}
            self.shared.state.view = None
            self.shared.state = self.shared.state.setup(test) or \
                self.shared.state
        self._running.set()
        for node in test["nodes"]:
            t = threading.Thread(target=self._view_loop,
                                 args=(test, node), daemon=True,
                                 name=f"membership-view-{node}")
            t.start()
            self._threads.append(t)
        return self

    def invoke(self, test, op):
        op2 = self.shared.state.invoke(test, op)
        with self.shared.lock:
            pair = (op, op2)
            self.shared.pending[_op_key(pair)] = pair
            _resolve(self.shared, test, self.opts)
        return op2

    def teardown(self, test):
        self._running.clear()
        for t in self._threads:
            t.join(timeout=1.0)
        self.shared.state.teardown(test)


class MembershipGenerator(gen.Gen):
    """Asks the shared state machine for the next legal op
    (`membership.clj:212-222`)."""

    def __init__(self, shared: _Shared):
        self.shared = shared

    def op(self, test, ctx):
        with self.shared.lock:
            o = self.shared.state.op(test)
        if o is None:
            return None
        if o == "pending" or o is gen.PENDING:
            return gen.PENDING, self
        return gen.fill_in_op(dict(o), ctx), self

    def update(self, test, ctx, event):
        return self


def package(opts: dict) -> dict | None:
    """Build {"state", "nemesis", "generator"} when faults include
    "membership" (`membership.clj:224-255`). opts["membership"]["state"]
    is the user's State machine."""
    if "membership" not in set(opts.get("faults") or ()):
        return None
    mopts = opts.get("membership") or {}
    nem = MembershipNemesis(
        mopts["state"],
        {k: mopts.get(k) for k in
         ("log-node-views", "log-view", "log-resolve", "log-resolve-op")})
    g = gen.stagger(opts.get("interval", 10),
                    MembershipGenerator(nem.shared))
    return {"state": nem.shared, "nemesis": nem, "generator": g}
