"""Nemesis protocol: fault injection driven by generator ops (reference
`jepsen/src/jepsen/nemesis.clj:11-16`).

A nemesis receives :info ops from the generator's nemesis thread and
performs faults against the cluster. The full built-in nemesis stack
(partitioners, grudges, clock skew, kill/pause) lives in sibling modules;
this module holds the protocol, the noop nemesis, validation, and
composition.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable


class Nemesis:
    def setup(self, test: dict) -> "Nemesis":
        return self

    def invoke(self, test: dict, op: dict) -> dict:
        """Apply a fault op; returns the completion op."""
        raise NotImplementedError

    def teardown(self, test: dict) -> None:
        pass


class Noop(Nemesis):
    """Does nothing (`nemesis.clj:92-99`)."""

    def invoke(self, test, op):
        return dict(op)


noop = Noop()


class InvalidCompletion(Exception):
    def __init__(self, op, op2, problems):
        self.op, self.op2, self.problems = op, op2, problems
        super().__init__(
            "nemesis returned an invalid completion: "
            + "; ".join(problems) + f" — invoke {op!r}, completion {op2!r}")


class Validate(Nemesis):
    """Asserts nemesis completions are well-formed (`nemesis.clj:49-90`)."""

    def __init__(self, nemesis: Nemesis):
        self.nemesis = nemesis

    def setup(self, test):
        res = self.nemesis.setup(test)
        if not isinstance(res, Nemesis):
            raise TypeError(f"nemesis setup returned non-nemesis {res!r}")
        return Validate(res)

    def invoke(self, test, op):
        op2 = self.nemesis.invoke(test, op)
        problems = []
        if not isinstance(op2, dict):
            problems.append("should be a dict")
        else:
            if op2.get("process") != op.get("process"):
                problems.append(":process should be the same")
            if op2.get("f") != op.get("f"):
                problems.append(":f should be the same")
        if problems:
            raise InvalidCompletion(op, op2, problems)
        return op2

    def teardown(self, test):
        self.nemesis.teardown(test)


def validate(n: Nemesis) -> Nemesis:
    return Validate(n)


class Compose(Nemesis):
    """Routes ops to sub-nemeses by :f through per-nemesis f-sets or
    f-mapping dicts (`nemesis.clj:384-428`)."""

    def __init__(self, nemeses):
        """nemeses: pairs of (fs, nemesis) where fs is either a set of :f
        values this nemesis handles, or a dict mapping outer :f -> inner
        :f (the op is rewritten on the way in and back on the way out).
        Accepts a dict {frozenset: nemesis} or, since dicts can't be dict
        keys, a list of (fs_or_fmap, nemesis) pairs."""
        pairs = nemeses.items() if isinstance(nemeses, dict) else nemeses
        self.nemeses = tuple((fs, n) for fs, n in pairs)

    def setup(self, test):
        return Compose([(fs, n.setup(test)) for fs, n in self.nemeses])

    def invoke(self, test, op):
        f = op.get("f")
        for fs, n in self.nemeses:
            if isinstance(fs, dict):
                if f in fs:
                    inner = dict(op)
                    inner["f"] = fs[f]
                    out = n.invoke(test, inner)
                    out = dict(out)
                    out["f"] = f
                    return out
            elif f in fs:
                return n.invoke(test, op)
        raise ValueError(f"no nemesis handles f={f!r}")

    def teardown(self, test):
        for _, n in self.nemeses:
            n.teardown(test)


def compose(nemeses) -> Nemesis:
    return Compose(nemeses)


class FnNemesis(Nemesis):
    """Lift a function (test, op) -> op' into a nemesis."""

    def __init__(self, f: Callable[[dict, dict], dict],
                 setup_fn: Callable[[dict], None] | None = None,
                 teardown_fn: Callable[[dict], None] | None = None):
        self.f = f
        self.setup_fn = setup_fn
        self.teardown_fn = teardown_fn

    def setup(self, test):
        if self.setup_fn:
            self.setup_fn(test)
        return self

    def invoke(self, test, op):
        return self.f(test, op)

    def teardown(self, test):
        if self.teardown_fn:
            self.teardown_fn(test)
