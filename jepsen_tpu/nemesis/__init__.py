"""Nemesis protocol: fault injection driven by generator ops (reference
`jepsen/src/jepsen/nemesis.clj:11-16`).

A nemesis receives :info ops from the generator's nemesis thread and
performs faults against the cluster. The full built-in nemesis stack
(partitioners, grudges, clock skew, kill/pause) lives in sibling modules;
this module holds the protocol, the noop nemesis, validation, and
composition.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable


class Nemesis:
    def setup(self, test: dict) -> "Nemesis":
        return self

    def invoke(self, test: dict, op: dict) -> dict:
        """Apply a fault op; returns the completion op."""
        raise NotImplementedError

    def teardown(self, test: dict) -> None:
        pass


class Noop(Nemesis):
    """Does nothing (`nemesis.clj:92-99`)."""

    def invoke(self, test, op):
        return dict(op)


noop = Noop()


class Validate(Nemesis):
    """Asserts nemesis completions are well-formed (`nemesis.clj:49-90`)."""

    def __init__(self, nemesis: Nemesis):
        self.nemesis = nemesis

    def setup(self, test):
        res = self.nemesis.setup(test)
        if not isinstance(res, Nemesis):
            raise TypeError(f"nemesis setup returned non-nemesis {res!r}")
        return Validate(res)

    def invoke(self, test, op):
        op2 = self.nemesis.invoke(test, op)
        if not isinstance(op2, dict):
            raise TypeError(
                f"nemesis completion should be a dict, got {op2!r}")
        return op2

    def teardown(self, test):
        self.nemesis.teardown(test)


def validate(n: Nemesis) -> Nemesis:
    return Validate(n)


class Compose(Nemesis):
    """Routes ops to sub-nemeses by :f through per-nemesis f-sets or
    f-mapping dicts (`nemesis.clj:384-428`)."""

    def __init__(self, nemeses: dict):
        """nemeses: {fs: nemesis} where fs is a frozenset of :f values, or
        a dict mapping outer :f -> inner :f."""
        self.nemeses = dict(nemeses)

    def setup(self, test):
        return Compose({fs: n.setup(test)
                        for fs, n in self.nemeses.items()})

    def invoke(self, test, op):
        f = op.get("f")
        for fs, n in self.nemeses.items():
            if isinstance(fs, dict):
                if f in fs:
                    inner = dict(op)
                    inner["f"] = fs[f]
                    out = n.invoke(test, inner)
                    out = dict(out)
                    out["f"] = f
                    return out
            elif f in fs:
                return n.invoke(test, op)
        raise ValueError(f"no nemesis handles f={f!r}")

    def teardown(self, test):
        for n in self.nemeses.values():
            n.teardown(test)


def compose(nemeses: dict) -> Nemesis:
    return Compose(nemeses)


class FnNemesis(Nemesis):
    """Lift a function (test, op) -> op' into a nemesis."""

    def __init__(self, f: Callable[[dict, dict], dict],
                 setup_fn: Callable[[dict], None] | None = None,
                 teardown_fn: Callable[[dict], None] | None = None):
        self.f = f
        self.setup_fn = setup_fn
        self.teardown_fn = teardown_fn

    def setup(self, test):
        if self.setup_fn:
            self.setup_fn(test)
        return self

    def invoke(self, test, op):
        return self.f(test, op)

    def teardown(self, test):
        if self.teardown_fn:
            self.teardown_fn(test)
