"""Nemesis protocol: fault injection driven by generator ops (reference
`jepsen/src/jepsen/nemesis.clj:11-16`).

A nemesis receives :info ops from the generator's nemesis thread and
performs faults against the cluster. The full built-in nemesis stack
(partitioners, grudges, clock skew, kill/pause) lives in sibling modules;
this module holds the protocol, the noop nemesis, validation, and
composition.
"""

from __future__ import annotations

from typing import Callable


class Nemesis:
    def setup(self, test: dict) -> "Nemesis":
        return self

    def invoke(self, test: dict, op: dict) -> dict:
        """Apply a fault op; returns the completion op."""
        raise NotImplementedError

    def teardown(self, test: dict) -> None:
        pass

    def fs(self) -> set | None:
        """Reflection: the :f values this nemesis handles, or None if
        unknown (`nemesis.clj:18-21`). Enables collection-style compose
        and f_map."""
        return None


class Noop(Nemesis):
    """Does nothing (`nemesis.clj:92-99`)."""

    def invoke(self, test, op):
        return dict(op)

    def fs(self):
        return set()


noop = Noop()


class InvalidCompletion(Exception):
    def __init__(self, op, op2, problems):
        self.op, self.op2, self.problems = op, op2, problems
        super().__init__(
            "nemesis returned an invalid completion: "
            + "; ".join(problems) + f" — invoke {op!r}, completion {op2!r}")


class Validate(Nemesis):
    """Asserts nemesis completions are well-formed (`nemesis.clj:49-90`)."""

    def __init__(self, nemesis: Nemesis):
        self.nemesis = nemesis

    def setup(self, test):
        res = self.nemesis.setup(test)
        if not isinstance(res, Nemesis):
            raise TypeError(f"nemesis setup returned non-nemesis {res!r}")
        return Validate(res)

    def invoke(self, test, op):
        op2 = self.nemesis.invoke(test, op)
        problems = []
        if not isinstance(op2, dict):
            problems.append("should be a dict")
        else:
            if op2.get("process") != op.get("process"):
                problems.append(":process should be the same")
            if op2.get("f") != op.get("f"):
                problems.append(":f should be the same")
        if problems:
            raise InvalidCompletion(op, op2, problems)
        return op2

    def teardown(self, test):
        self.nemesis.teardown(test)


def validate(n: Nemesis) -> Nemesis:
    return Validate(n)


class Compose(Nemesis):
    """Routes ops to sub-nemeses by :f through per-nemesis f-sets or
    f-mapping dicts (`nemesis.clj:384-428`)."""

    def __init__(self, nemeses):
        """nemeses: pairs of (fs, nemesis) where fs is either a set of :f
        values this nemesis handles, or a dict mapping outer :f -> inner
        :f (the op is rewritten on the way in and back on the way out).
        Accepts a dict {frozenset: nemesis} or, since dicts can't be dict
        keys, a list of (fs_or_fmap, nemesis) pairs."""
        pairs = nemeses.items() if isinstance(nemeses, dict) else nemeses
        self.nemeses = tuple((fs, n) for fs, n in pairs)

    def setup(self, test):
        return Compose([(fs, n.setup(test)) for fs, n in self.nemeses])

    def invoke(self, test, op):
        f = op.get("f")
        for fs, n in self.nemeses:
            if isinstance(fs, dict):
                if f in fs:
                    inner = dict(op)
                    inner["f"] = fs[f]
                    out = n.invoke(test, inner)
                    out = dict(out)
                    out["f"] = f
                    return out
            elif f in fs:
                return n.invoke(test, op)
        raise ValueError(f"no nemesis handles f={f!r}")

    def teardown(self, test):
        for _, n in self.nemeses:
            n.teardown(test)

    def fs(self):
        """Union of routed f-spaces: dict f-maps contribute their outer
        keys, sets their members (`nemesis.clj:373-382`)."""
        out = set()
        for fs, _ in self.nemeses:
            out |= set(fs.keys()) if isinstance(fs, dict) else set(fs)
        return out


def compose(nemeses) -> Nemesis:
    """Combine nemeses into one, routing by :f. Accepts {fs: nemesis} /
    [(fs, nemesis)] pairs, or a plain collection of nemeses whose fs()
    reflection determines routing (`nemesis.clj:384-428`)."""
    if isinstance(nemeses, dict):
        return Compose(nemeses)
    nemeses = list(nemeses)
    if nemeses and all(isinstance(n, Nemesis) for n in nemeses):
        pairs, seen = [], {}
        for n in nemeses:
            fs = n.fs()
            if fs is None:
                raise ValueError(
                    f"{n!r} doesn't support fs() reflection; compose it "
                    "with explicit (fs, nemesis) pairs instead")
            for f in fs:
                if f in seen:
                    raise ValueError(
                        f"nemeses {n!r} and {seen[f]!r} are mutually "
                        f"incompatible; both use f={f!r}")
                seen[f] = n
            pairs.append((fs, n))
        return Compose(pairs)
    return Compose(nemeses)


class FMap(Nemesis):
    """Remaps the :f values a nemesis accepts: ops arrive with f=lift(f0),
    are unlifted for the inner nemesis, and completions are re-lifted —
    the mirror of generator f_map so the two compose
    (`nemesis.clj:285-327`)."""

    def __init__(self, lift: Callable, nem: Nemesis,
                 unlift: dict | None = None):
        self.lift = lift
        self.nem = nem
        fs = nem.fs()
        if fs is None and unlift is None:
            raise ValueError(
                f"{nem!r} doesn't support fs() reflection; f_map needs it")
        self.unlift = unlift if unlift is not None else \
            {lift(f): f for f in fs}

    def setup(self, test):
        return FMap(self.lift, self.nem.setup(test), self.unlift)

    def invoke(self, test, op):
        inner = dict(op)
        inner["f"] = self.unlift[op["f"]]
        out = dict(self.nem.invoke(test, inner))
        out["f"] = op["f"]
        return out

    def teardown(self, test):
        self.nem.teardown(test)

    def fs(self):
        return set(self.unlift.keys())


def f_map(lift: Callable, nem: Nemesis) -> FMap:
    return FMap(lift, nem)


class TimeoutNemesis(Nemesis):
    """Times out unreliable nemesis invocations; timed-out ops get
    :value :timeout (`nemesis.clj:92-106`)."""

    def __init__(self, timeout_ms: float, nem: Nemesis):
        self.timeout_ms = timeout_ms
        self.nem = nem

    def setup(self, test):
        from ..util import timeout as _timeout

        return TimeoutNemesis(
            self.timeout_ms,
            _timeout(self.timeout_ms / 1000,
                     lambda: self.nem.setup(test)))

    def invoke(self, test, op):
        from ..util import timeout as _timeout

        return _timeout(self.timeout_ms / 1000,
                        lambda: self.nem.invoke(test, op),
                        default={**op, "value": "timeout"})

    def teardown(self, test):
        self.nem.teardown(test)

    def fs(self):
        return self.nem.fs()


def timeout(timeout_ms: float, nem: Nemesis) -> TimeoutNemesis:
    return TimeoutNemesis(timeout_ms, nem)


# -- clock, process, and file faults ---------------------------------------

def set_time(t: float) -> None:
    """Set the current node's wall clock, POSIX seconds
    (`nemesis.clj:430-433`)."""
    from .. import control as c

    with c.su():
        c.exec_("date", "+%s", "-s", f"@{int(t)}")


class ClockScrambler(Nemesis):
    """Randomizes node clocks within a ±dt-second window
    (`nemesis.clj:435-450`)."""

    def __init__(self, dt: float):
        self.dt = dt

    def fs(self):
        return {"scramble-clock"}

    def invoke(self, test, op):
        import random as _random
        import time as _time

        from .. import control as c

        dt = self.dt

        def f(t, node):
            set_time(_time.time() + _random.randint(-int(dt), int(dt)))

        value = c.on_nodes(test, f)
        return {**op, "value": value}

    def teardown(self, test):
        import time as _time

        from .. import control as c

        c.on_nodes(test, lambda t, n: set_time(_time.time()))


def clock_scrambler(dt: float) -> ClockScrambler:
    return ClockScrambler(dt)


class NodeStartStopper(Nemesis):
    """:start runs start_fn on targeted nodes; :stop undoes it on the
    same nodes (`nemesis.clj:452-495`). Targeter takes (test, nodes) or
    (nodes); returning None skips. Values become the op's :value, e.g.
    {"n1": ["killed", "java"]}."""

    def __init__(self, targeter, start_fn, stop_fn):
        self.targeter = targeter
        self.start_fn = start_fn
        self.stop_fn = stop_fn
        self._nodes = None
        import threading

        self._lock = threading.Lock()

    def fs(self):
        return {"start", "stop"}

    def invoke(self, test, op):
        from .. import control as c

        with self._lock:
            f = op.get("f")
            if f == "start":
                try:
                    ns = self.targeter(test, list(test["nodes"]))
                except TypeError:
                    ns = self.targeter(list(test["nodes"]))
                if ns is None:
                    value = "no-target"
                elif self._nodes is not None:
                    value = f"nemesis already disrupting {self._nodes!r}"
                else:
                    ns = ns if isinstance(ns, (list, tuple, set)) else [ns]
                    self._nodes = list(ns)
                    value = c.on_many(
                        ns, lambda: self.start_fn(test, c.var("host")))
            elif f == "stop":
                if self._nodes is None:
                    value = "not-started"
                else:
                    value = c.on_many(
                        self._nodes,
                        lambda: self.stop_fn(test, c.var("host")))
                    self._nodes = None
            else:
                raise ValueError(f"can't handle f={f!r}")
            return {**op, "type": "info", "value": value}


def node_start_stopper(targeter, start_fn, stop_fn) -> NodeStartStopper:
    return NodeStartStopper(targeter, start_fn, stop_fn)


def hammer_time(process: str, targeter=None) -> NodeStartStopper:
    """SIGSTOP a process on :start, SIGCONT on :stop
    (`nemesis.clj:497-511`)."""
    import random as _random

    from .. import control as c

    if targeter is None:
        targeter = lambda nodes: _random.choice(nodes)

    def start(test, node):
        with c.su():
            c.exec_("killall", "-s", "STOP", process)
        return ["paused", process]

    def stop(test, node):
        with c.su():
            c.exec_("killall", "-s", "CONT", process)
        return ["resumed", process]

    return NodeStartStopper(targeter, start, stop)


class TruncateFile(Nemesis):
    """{:f :truncate :value {node: {"file": ..., "drop": bytes}}} drops
    the last bytes from files (`nemesis.clj:513-539`)."""

    def fs(self):
        return {"truncate"}

    def invoke(self, test, op):
        from .. import control as c

        assert op.get("f") == "truncate"
        plan = op.get("value") or {}

        def f(t, node):
            spec = plan[node]
            assert isinstance(spec["file"], str)
            assert isinstance(spec["drop"], int)
            with c.su():
                c.exec_("truncate", "-c", "-s", f"-{spec['drop']}",
                        spec["file"])

        c.on_nodes(test, f, nodes=list(plan.keys()))
        return dict(op)


def truncate_file() -> TruncateFile:
    return TruncateFile()


class FnNemesis(Nemesis):
    """Lift a function (test, op) -> op' into a nemesis."""

    def __init__(self, f: Callable[[dict, dict], dict],
                 setup_fn: Callable[[dict], None] | None = None,
                 teardown_fn: Callable[[dict], None] | None = None):
        self.f = f
        self.setup_fn = setup_fn
        self.teardown_fn = teardown_fn

    def setup(self, test):
        if self.setup_fn:
            self.setup_fn(test)
        return self

    def invoke(self, test, op):
        return self.f(test, op)

    def teardown(self, test):
        if self.teardown_fn:
            self.teardown_fn(test)
