"""Nemesis packages: a composable algebra of faults + their generators.

Reference: `jepsen/src/jepsen/nemesis/combined.clj` — a *package* is
{"nemesis", "generator", "final-generator", "perf"}; node-spec DSL
(:38-68), db kill/pause package (:70-160), partition-spec grudges +
package (:162-246), clock package (:248-280), f-map lifting (:282-303),
and composition (:305-374).
"""

from __future__ import annotations

import random
from typing import Iterable

from .. import db as db_
from .. import generator as gen
from ..util import majority
from . import Nemesis, compose as n_compose, f_map as n_f_map, noop as n_noop
from . import partition as part
from . import time as nt

DEFAULT_INTERVAL = 10  # seconds between nemesis ops (`combined.clj:27-29`)

noop = {"generator": None, "final-generator": None, "nemesis": n_noop,
        "perf": set()}


def minority_third(n: int) -> int:
    """Up to, but not including, one third of n (reference
    `util/minority-third`)."""
    return max(0, (n - 1) // 3) if n % 3 == 0 else (n - 1) // 3


def random_nonempty_subset(nodes, rng=None):
    r = rng or random
    return r.sample(list(nodes), r.randint(1, len(nodes)))


def db_nodes(test: dict, db, node_spec):
    """Resolve a node spec to nodes (`combined.clj:38-61`):
    None | "one" | "minority" | "majority" | "minority-third" |
    "primaries" | "all" | explicit list."""
    nodes = list(test["nodes"])
    if node_spec is None:
        return random_nonempty_subset(nodes)
    if node_spec == "one":
        return [random.choice(nodes)]
    if node_spec == "minority":
        random.shuffle(nodes)
        return nodes[:majority(len(nodes)) - 1]
    if node_spec == "majority":
        random.shuffle(nodes)
        return nodes[:majority(len(nodes))]
    if node_spec == "minority-third":
        random.shuffle(nodes)
        return nodes[:minority_third(len(nodes))]
    if node_spec == "primaries":
        return random_nonempty_subset(db.primaries(test))
    if node_spec == "all":
        return nodes
    return list(node_spec)


def node_specs(db) -> list:
    """All node specs valid for this DB (`combined.clj:63-68`)."""
    specs = [None, "one", "minority-third", "minority", "majority", "all"]
    if db_.supports(db, "primary"):
        specs.append("primaries")
    return specs


class DBNemesis(Nemesis):
    """start/kill/pause/resume against node specs (`combined.clj:70-98`)."""

    def __init__(self, db):
        self.db = db

    def fs(self):
        return {"start", "kill", "pause", "resume"}

    def invoke(self, test, op):
        from .. import control as c

        f = {"start": lambda t, n: self.db.start(t, n),
             "kill": lambda t, n: self.db.kill(t, n),
             "pause": lambda t, n: self.db.pause(t, n),
             "resume": lambda t, n: self.db.resume(t, n)}[op["f"]]
        nodes = db_nodes(test, self.db, op.get("value"))
        res = c.on_nodes(test, f, nodes=nodes)
        return {**op, "value": res}


def db_generators(opts: dict) -> dict:
    """:generator/:final-generator for kill/pause flip-flops, driven by
    which capability protocols the DB implements
    (`combined.clj:100-139`)."""
    db = opts["db"]
    faults = opts["faults"]
    kill = db_.supports(db, "process") and "kill" in faults
    pause = db_.supports(db, "pause") and "pause" in faults
    kill_targets = opts.get("kill", {}).get("targets") or node_specs(db)
    pause_targets = opts.get("pause", {}).get("targets") or node_specs(db)

    start = {"type": "info", "f": "start", "value": "all"}
    resume = {"type": "info", "f": "resume", "value": "all"}

    def kill_op(test, ctx):
        return {"type": "info", "f": "kill",
                "value": random.choice(kill_targets)}

    def pause_op(test, ctx):
        return {"type": "info", "f": "pause",
                "value": random.choice(pause_targets)}

    modes, final = [], []
    if pause:
        modes.append(gen.flip_flop(pause_op, gen.repeat(resume)))
        final.append(resume)
    if kill:
        modes.append(gen.flip_flop(kill_op, gen.repeat(start)))
        final.append(start)
    return {"generator": gen.mix(modes) if modes else None,
            "final-generator": final or None}


def db_package(opts: dict) -> dict:
    """Kill/pause package (`combined.clj:141-160`)."""
    needed = bool({"kill", "pause"} & set(opts["faults"]))
    gens = db_generators(opts)
    g = gens["generator"]
    if g is not None:
        g = gen.stagger(opts.get("interval", DEFAULT_INTERVAL), g)
    return {"generator": g if needed else None,
            "final-generator": gens["final-generator"] if needed else None,
            "nemesis": DBNemesis(opts["db"]),
            "perf": {("kill", frozenset({"kill"}), frozenset({"start"}),
                      "#E9A4A0"),
                     ("pause", frozenset({"pause"}),
                      frozenset({"resume"}), "#A0B1E9")}}


def grudge(test: dict, db, part_spec):
    """Compute a grudge from a partition spec (`combined.clj:162-188`):
    "one" | "majority" | "majorities-ring" | "minority-third" |
    "primaries" | explicit grudge dict."""
    nodes = list(test["nodes"])
    if part_spec == "one":
        return part.complete_grudge(part.split_one(nodes))
    if part_spec == "majority":
        random.shuffle(nodes)
        return part.complete_grudge(part.bisect(nodes))
    if part_spec == "majorities-ring":
        return part.majorities_ring(nodes)
    if part_spec == "minority-third":
        random.shuffle(nodes)
        k = minority_third(len(nodes))
        return part.complete_grudge([nodes[:k], nodes[k:]])
    if part_spec == "primaries":
        primaries = db.primaries(test)
        chosen = random_nonempty_subset(primaries)
        rest = [n for n in nodes if n not in set(primaries)]
        return part.complete_grudge([rest] + [[p] for p in chosen])
    return part_spec


def partition_specs(db) -> list:
    specs = ["one", "minority-third", "majority", "majorities-ring"]
    if db_.supports(db, "primary"):
        specs.append("primaries")
    return specs


class PartitionNemesis(Nemesis):
    """Partitioner lifted to partition specs (`combined.clj:196-224`)."""

    def __init__(self, db, p: Nemesis | None = None):
        self.db = db
        self.p = p or part.partitioner()

    def fs(self):
        return {"start-partition", "stop-partition"}

    def setup(self, test):
        return PartitionNemesis(self.db, self.p.setup(test))

    def invoke(self, test, op):
        if op["f"] == "start-partition":
            g = grudge(test, self.db, op.get("value"))
            out = self.p.invoke(test, {**op, "f": "start", "value": g})
        elif op["f"] == "stop-partition":
            out = self.p.invoke(test, {**op, "f": "stop"})
        else:
            raise ValueError(f"can't handle f={op['f']!r}")
        return {**out, "f": op["f"]}

    def teardown(self, test):
        self.p.teardown(test)


def partition_package(opts: dict) -> dict:
    """Partition package (`combined.clj:226-246`)."""
    needed = "partition" in set(opts["faults"])
    db = opts["db"]
    targets = opts.get("partition", {}).get("targets") or \
        partition_specs(db)

    def start(test, ctx):
        return {"type": "info", "f": "start-partition",
                "value": random.choice(targets)}

    stop = {"type": "info", "f": "stop-partition", "value": None}
    g = gen.stagger(opts.get("interval", DEFAULT_INTERVAL),
                    gen.flip_flop(start, gen.repeat(stop)))
    return {"generator": g if needed else None,
            "final-generator": stop if needed else None,
            "nemesis": PartitionNemesis(db),
            "perf": {("partition", frozenset({"start-partition"}),
                      frozenset({"stop-partition"}), "#E9DCA0")}}


def clock_package(opts: dict) -> dict:
    """Clock-skew package (`combined.clj:248-280`)."""
    needed = "clock" in set(opts["faults"])
    db = opts["db"]
    nemesis = n_compose([({"reset-clock": "reset",
                           "check-clock-offsets": "check-offsets",
                           "strobe-clock": "strobe",
                           "bump-clock": "bump"}, nt.clock_nemesis())])
    target_specs = opts.get("clock", {}).get("targets") or node_specs(db)

    def targets(test):
        return db_nodes(test, db,
                        random.choice(target_specs) if target_specs
                        else None)

    lift = {"reset": "reset-clock",
            "check-offsets": "check-clock-offsets",
            "strobe": "strobe-clock",
            "bump": "bump-clock"}
    clock_gen = gen.phases(
        {"type": "info", "f": "check-offsets"},
        gen.mix([nt.reset_gen_select(targets),
                 nt.bump_gen_select(targets),
                 nt.strobe_gen_select(targets)]))
    g = gen.stagger(opts.get("interval", DEFAULT_INTERVAL),
                    gen.f_map(lift, clock_gen))
    return {"generator": g if needed else None,
            "final-generator": ({"type": "info", "f": "reset-clock"}
                                if needed else None),
            "nemesis": nemesis,
            "perf": {("clock", frozenset({"bump-clock"}),
                      frozenset({"reset-clock"}), "#A0E9E3")}}


def f_map(lift, pkg: dict) -> dict:
    """Lift a whole package's f-space (`combined.clj:294-303`)."""
    perf = set()
    for name, start, stop, color in pkg["perf"]:
        perf.add((lift(name), frozenset(lift(f) for f in start),
                  frozenset(lift(f) for f in stop), color))
    return {
        "generator": gen.f_map(lift, pkg["generator"])
        if pkg["generator"] is not None else None,
        "final-generator": gen.f_map(lift, pkg["final-generator"])
        if pkg["final-generator"] is not None else None,
        "nemesis": n_f_map(lift, pkg["nemesis"]),
        "perf": perf,
    }


def compose_packages(packages: Iterable[dict]) -> dict:
    """Combine packages: generators via gen.any, final generators
    sequentially, nemeses by f-routing (`combined.clj:305-316`)."""
    packages = list(packages)
    if not packages:
        return noop
    if len(packages) == 1:
        return packages[0]
    gens = [p["generator"] for p in packages
            if p["generator"] is not None]
    finals = [p["final-generator"] for p in packages
              if p["final-generator"] is not None]
    perf = set()
    for p in packages:
        perf |= p["perf"]
    return {"generator": gen.any(*gens) if gens else None,
            "final-generator": finals or None,
            "nemesis": n_compose([p["nemesis"] for p in packages]),
            "perf": perf}


def nemesis_packages(opts: dict) -> list[dict]:
    """The individual packages, pre-composition (`combined.clj:318-326`)."""
    opts = {**opts, "faults": set(opts.get("faults")
                                  or ["partition", "kill", "pause",
                                      "clock"])}
    return [partition_package(opts), clock_package(opts),
            db_package(opts)]


def nemesis_package(opts: dict) -> dict:
    """The kitchen-sink package: partitions + clock skew + kill/pause,
    each fault type gated by opts["faults"] (`combined.clj:328-374`).

    Mandatory: opts["db"]. Optional: "interval" (s), "faults" (list),
    "partition"/"kill"/"pause"/"clock" each {"targets": [...]}.
    """
    return compose_packages(nemesis_packages(opts))
