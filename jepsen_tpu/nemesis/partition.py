"""Network-partition nemeses and grudge calculus.

Reference: `jepsen/src/jepsen/nemesis.clj` — `bisect` (:108-111),
`split-one` (:113-118), `complete-grudge` (:120-132), `invert-grudge`
(:134-142), `bridge` (:144-155), `partitioner` (:157-183), the packaged
partitioners (:185-200, :277-281), and the majorities-ring grudges:
exact for ≤5 nodes (:202-216), stochastic for larger clusters
(:218-258).

A *grudge* maps each node to the set of nodes whose traffic it drops.
"""

from __future__ import annotations

import random
from typing import Callable, Iterable

from .. import net
from ..util import majority
from . import Nemesis


def bisect(coll: list) -> tuple[list, list]:
    """Cut a sequence in half; smaller half first (`nemesis.clj:108-111`)."""
    mid = len(coll) // 2
    return list(coll[:mid]), list(coll[mid:])


def split_one(coll: list, loner=None) -> tuple[list, list]:
    """Split one node (random unless given) from the rest
    (`nemesis.clj:113-118`)."""
    if loner is None:
        loner = random.choice(list(coll))
    return [loner], [x for x in coll if x != loner]


def complete_grudge(components: Iterable[Iterable]) -> dict:
    """Grudge in which no node can talk outside its component
    (`nemesis.clj:120-132`)."""
    comps = [set(c) for c in components]
    universe = set().union(*comps) if comps else set()
    grudge = {}
    for comp in comps:
        for node in comp:
            grudge[node] = universe - comp
    return grudge


def invert_grudge(nodes: Iterable, conns: dict) -> dict:
    """From {node: set-of-connected} to {node: set-to-DROP}
    (`nemesis.clj:134-142`)."""
    ns = set(nodes)
    return {a: ns - conns.get(a, set()) - {a} for a in sorted(ns)}


def bridge(nodes: list) -> dict:
    """Cut the network in half but keep one bridge node connected to both
    sides (`nemesis.clj:144-155`)."""
    components = bisect(list(nodes))
    bridge_node = components[1][0]
    grudge = complete_grudge(components)
    grudge.pop(bridge_node, None)
    return {k: v - {bridge_node} for k, v in grudge.items()}


def majorities_ring_perfect(nodes: list,
                            rng: random.Random | None = None) -> dict:
    """Exact ring of overlapping majorities for ≤5 nodes
    (`nemesis.clj:202-216`)."""
    r = rng or random
    U = set(nodes)
    n = len(nodes)
    m = majority(n)
    ring = list(nodes)
    r.shuffle(ring)
    grudge = {}
    for i in range(n):
        maj = [ring[(i + j) % n] for j in range(m)]
        holder = maj[len(maj) // 2]
        grudge[holder] = U - set(maj)
    return grudge


def majorities_ring_stochastic(nodes: list,
                               rng: random.Random | None = None) -> dict:
    """Incremental least-connected matching until every node sees a
    majority (`nemesis.clj:218-258`)."""
    r = rng or random
    n = len(nodes)
    m = majority(n)
    conns: dict = {a: {a} for a in nodes}
    while True:
        # shuffled, degree-ordered [degree, node]
        by_degree: dict[int, list] = {}
        for node, cs in conns.items():
            by_degree.setdefault(len(cs), []).append(node)
        dns = []
        for d in sorted(by_degree):
            group = by_degree[d]
            r.shuffle(group)
            dns.extend((d, x) for x in group)
        a_degree, a = dns[0]
        if m <= a_degree:
            return invert_grudge(nodes, conns)
        b = next(node for d, node in dns if node not in conns[a])
        conns[a].add(b)
        conns[b].add(a)


def majorities_ring(nodes: list, rng: random.Random | None = None) -> dict:
    """Every node sees a majority; no two see the same one
    (`nemesis.clj:260-275`)."""
    if len(nodes) <= 5:
        return majorities_ring_perfect(nodes, rng)
    return majorities_ring_stochastic(nodes, rng)


class Partitioner(Nemesis):
    """:start cuts links per (grudge nodes) or the op's :value grudge;
    :stop heals (`nemesis.clj:157-183`)."""

    def __init__(self, grudge: Callable[[list], dict] | None = None):
        self.grudge = grudge

    def fs(self):
        return {"start-partition", "stop-partition", "start", "stop"}

    def setup(self, test):
        test["net"].heal(test)
        return self

    def invoke(self, test, op):
        f = op.get("f")
        if f in ("start", "start-partition"):
            grudge = op.get("value")
            if grudge is None:
                if self.grudge is None:
                    raise ValueError(
                        f"op {op!r} needs a grudge :value, and this "
                        "partitioner has no grudge function")
                grudge = self.grudge(list(test["nodes"]))
            net.drop_all(test, grudge)
            return {**op, "value": ["isolated", grudge]}
        if f in ("stop", "stop-partition"):
            test["net"].heal(test)
            return {**op, "value": "network-healed"}
        raise ValueError(f"partitioner can't handle f={f!r}")

    def teardown(self, test):
        test["net"].heal(test)


def partitioner(grudge=None) -> Partitioner:
    return Partitioner(grudge)


def partition_halves() -> Partitioner:
    """First half vs second half (`nemesis.clj:185-190`)."""
    return Partitioner(lambda nodes: complete_grudge(bisect(nodes)))


def partition_random_halves() -> Partitioner:
    """Random halves (`nemesis.clj:192-195`)."""
    def g(nodes):
        ns = list(nodes)
        random.shuffle(ns)
        return complete_grudge(bisect(ns))
    return Partitioner(g)


def partition_random_node() -> Partitioner:
    """Isolate one random node (`nemesis.clj:197-200`)."""
    return Partitioner(lambda nodes: complete_grudge(split_one(nodes)))


def partition_majorities_ring() -> Partitioner:
    """Overlapping-majorities ring (`nemesis.clj:277-281`)."""
    return Partitioner(majorities_ring)
