"""Clock nemesis: wall-clock faults driven by on-node native tools.

Reference: `jepsen/src/jepsen/nemesis/time.clj` — uploads C sources and
compiles them on each DB node (:20-61 `compile!`/`install!`), then drives
them: ops `:reset` (ntpdate), `:bump` (one-shot jump), `:strobe`
(oscillation), `:check-offsets` (:98-146 `clock-nemesis`); randomized
skew generators ±2²–2¹⁸ ms (:148-205). The native tools themselves are
C++ ports in `jepsen_tpu/native/` (see each file's header).
"""

from __future__ import annotations

import os
import random
import time as _time

from .. import control as c
from ..control import util as cu
from ..control.core import RemoteError
from . import Nemesis

DIR = "/opt/jepsen"

NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")

TOOLS = {"bump-time": "bump_time.cpp",
         "strobe-time": "strobe_time.cpp",
         # phase-locked variant (the reference's abandoned
         # strobe-time-experiment.c, finished): flips align to absolute
         # monotonic ticks instead of drifting relative sleeps
         "strobe-time-experiment": "strobe_time_experiment.cpp",
         "adj-time": "adj_time.cpp"}


def compile_tool(source_path: str, bin: str) -> str:
    """Upload a C++ source and compile it to /opt/jepsen/<bin> on the
    current node, if not already present (`nemesis/time.clj:20-39`)."""
    with c.su():
        if not cu.exists(f"{DIR}/{bin}"):
            c.exec_("mkdir", "-p", DIR)
            c.exec_("chmod", "a+rwx", DIR)
            c.upload(source_path, f"{DIR}/{bin}.cpp")
            with c.cd(DIR):
                c.exec_("g++", "-O2", "-std=c++17", "-o", bin,
                        f"{bin}.cpp")
    return bin


def compile_tools() -> None:
    for bin, src in TOOLS.items():
        compile_tool(os.path.join(NATIVE_DIR, src), bin)


def install() -> None:
    """Upload + compile the clock tools, installing a compiler on demand
    (`nemesis/time.clj:52-61`)."""
    try:
        compile_tools()
    except RemoteError:
        from ..os_ import centos, debian

        try:
            debian.install(["build-essential"])
        except RemoteError:
            centos.install(["gcc-c++"])
        compile_tools()


def current_offset() -> float:
    """This node's clock offset from the control node, seconds
    (`nemesis/time.clj:69-78`)."""
    remote = float(c.exec_("date", "+%s.%N"))
    return remote - _time.time()


def reset_time() -> None:
    """Reset the current node's clock via NTP (`nemesis/time.clj:80-84`)."""
    with c.su():
        c.exec_("ntpdate", "-p", 1, "-b", "time.google.com")


def reset_time_all(test: dict) -> None:
    c.on_nodes(test, lambda t, n: reset_time())


def bump_time(delta_ms: float) -> float:
    """Jump this node's clock by delta ms; returns the resulting offset
    in seconds (`nemesis/time.clj:86-90`)."""
    with c.su():
        t = float(c.exec_(f"{DIR}/bump-time", delta_ms))
    return t - _time.time()


def strobe_time(delta_ms: float, period_ms: float,
                duration_s: float, phase_locked: bool = False) -> None:
    """Oscillate this node's clock (`nemesis/time.clj:92-96`).
    phase_locked uses the tick-anchored experiment variant, whose flip
    edges don't drift with per-iteration overhead."""
    tool = "strobe-time-experiment" if phase_locked else "strobe-time"
    with c.su():
        c.exec_(f"{DIR}/{tool}", delta_ms, period_ms, duration_s)


class ClockNemesis(Nemesis):
    """Ops (`nemesis/time.clj:98-146`):
      {"f": "reset",  "value": [node, ...]}
      {"f": "strobe", "value": {node: {"delta": ms, "period": ms,
                                       "duration": s}}}
      {"f": "bump",   "value": {node: delta-ms}}
      {"f": "check-offsets"}
    Completions carry {"clock-offsets": {node: seconds}}."""

    def fs(self):
        return {"reset", "strobe", "bump", "check-offsets"}

    def setup(self, test):
        def prep(t, node):
            install()
            try:
                with c.su():
                    c.exec_("service", "ntpd", "stop")
            except RemoteError:
                pass
            reset_time()

        c.on_nodes(test, prep)
        return self

    def invoke(self, test, op):
        f = op.get("f")
        if f == "reset":
            res = c.on_nodes(
                test, lambda t, n: (reset_time(), current_offset())[1],
                nodes=op.get("value"))
        elif f == "check-offsets":
            res = c.on_nodes(test, lambda t, n: current_offset())
        elif f == "strobe":
            m = op["value"]

            def go(t, node):
                s = m[node]
                strobe_time(s["delta"], s["period"], s["duration"],
                            phase_locked=bool(s.get("phase-locked")))
                return current_offset()

            res = c.on_nodes(test, go, nodes=list(m.keys()))
        elif f == "bump":
            m = op["value"]
            res = c.on_nodes(test, lambda t, n: bump_time(m[n]),
                             nodes=list(m.keys()))
        else:
            raise ValueError(f"clock nemesis can't handle f={f!r}")
        return {**op, "clock-offsets": res}

    def teardown(self, test):
        reset_time_all(test)


def clock_nemesis() -> ClockNemesis:
    return ClockNemesis()


# -- randomized skew generators (`nemesis/time.clj:148-205`) ---------------

def random_nonempty_subset(nodes, rng=None):
    r = rng or random
    n = r.randint(1, len(nodes))
    return r.sample(list(nodes), n)


def reset_gen_select(select):
    """Reset generator targeting select(test) nodes
    (`nemesis/time.clj:148-154`). Fn-generators take (test, ctx)."""
    def gen(test, ctx):
        return {"type": "info", "f": "reset", "value": select(test)}
    return gen


def reset_gen(test, ctx):
    """Reset clocks on a random nonempty node subset
    (`nemesis/time.clj:156-159`)."""
    return {"type": "info", "f": "reset",
            "value": random_nonempty_subset(test["nodes"])}


def _exp_ms(rng=None):
    """±2²–2¹⁸ ms, exponentially distributed (`nemesis/time.clj:161-173`)."""
    r = rng or random
    return int(r.choice([-1, 1]) * 2 ** (2 + r.random() * 16))


def bump_gen_select(select):
    def gen(test, ctx):
        return {"type": "info", "f": "bump",
                "value": {n: _exp_ms() for n in select(test)}}
    return gen


def bump_gen(test, ctx):
    return bump_gen_select(
        lambda t: random_nonempty_subset(t["nodes"]))(test, ctx)


def strobe_gen_select(select):
    """Strobes of 4 ms–262 s delta, 1 ms–1 s period, 0–32 s duration
    (`nemesis/time.clj:179-192`)."""
    def gen(test, ctx):
        return {"type": "info", "f": "strobe",
                "value": {n: {"delta": int(2 ** (2 + random.random() * 16)),
                              "period": int(2 ** (random.random() * 10)),
                              "duration": random.random() * 32}
                          for n in select(test)}}
    return gen


def strobe_gen(test, ctx):
    return strobe_gen_select(
        lambda t: random_nonempty_subset(t["nodes"]))(test, ctx)


def clock_gen():
    """A random schedule of clock-skew ops, starting with a
    check-offsets to establish a baseline (`nemesis/time.clj:199-205`)."""
    from .. import generator as gen

    return gen.phases(
        {"type": "info", "f": "check-offsets"},
        gen.mix([reset_gen, bump_gen, strobe_gen]))
