"""Operation and history model — the foundation every layer shares.

An *operation* is a plain dict (mirroring the reference's Clojure maps; see
`jepsen/src/jepsen/generator.clj` docstring for the op shape):

    {'type': 'invoke'|'ok'|'fail'|'info',
     'f': <workload-specific function, e.g. 'read'|'write'|'cas'>,
     'value': <payload>,
     'process': int | 'nemesis',
     'time': int nanoseconds, relative to test start,
     'index': int, position in the history (assigned by `index()`)}

A *history* is the ordered journal of invocations and completions recorded by
the interpreter (reference: `jepsen/src/jepsen/generator/interpreter.clj:
181-310` journals a transient vector; `jepsen/src/jepsen/core.clj:228`
indexes it with knossos.history before checking).

This module also defines the *device encoding*: a history lowered to a
structure-of-arrays of fixed-width integers, one row per logical operation
(invoke paired with its completion), ready to ship to TPU as JAX arrays.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Iterable, Iterator, Sequence

import numpy as np

# Op types
INVOKE = "invoke"
OK = "ok"
FAIL = "fail"
INFO = "info"

NEMESIS = "nemesis"

# Sentinel for "no value" in integer device encodings. Register-family
# workloads use non-negative small ints; -1 is reserved.
NIL = -1


# device unordered-queue multiset layout: 4-bit per-value counts
UQ_VALUES = 7
UQ_COUNT_MAX = 15


class DeviceEncodingError(ValueError):
    """The history (or model state) exceeds a device encoding's
    capacity — checkers catch this and fall back to the host model.
    Deliberately distinct from plain ValueError so configuration
    errors (e.g. forcing an ineligible engine) still surface."""


def op(type: str, f: Any, value: Any = None, process: Any = None,
       time: int | None = None, **extra: Any) -> dict:
    """Build an op map."""
    o = {"type": type, "f": f, "value": value, "process": process,
         "time": time}
    if extra:
        o.update(extra)
    return o


def invoke_op(process: Any, f: Any, value: Any = None, **extra: Any) -> dict:
    return op(INVOKE, f, value, process, **extra)


def is_invoke(o: dict) -> bool:
    return o["type"] == INVOKE


def is_ok(o: dict) -> bool:
    return o["type"] == OK


def is_fail(o: dict) -> bool:
    return o["type"] == FAIL


def is_info(o: dict) -> bool:
    return o["type"] == INFO


def is_completion(o: dict) -> bool:
    return o["type"] in (OK, FAIL, INFO)


def is_client_op(o: dict) -> bool:
    """Client ops have integer processes; the nemesis uses 'nemesis'."""
    return isinstance(o["process"], int)


def completion_of(invocation: dict, completion_type: str = OK,
                  **overrides: Any) -> dict:
    """Build the completion op for an invocation (same process/f, new type)."""
    o = dict(invocation)
    o["type"] = completion_type
    o.update(overrides)
    return o


class History(Sequence):
    """An immutable-by-convention ordered journal of ops.

    Thin wrapper over a list of op dicts with the derived structure every
    checker needs: indexing, invoke/completion pairing, filtering.
    """

    __slots__ = ("ops", "_pair_index", "_indexed")

    def __init__(self, ops: Iterable[dict]):
        self.ops = list(ops)
        self._pair_index: dict[int, int] | None = None
        self._indexed = False

    # -- Sequence interface -------------------------------------------------
    def __len__(self) -> int:
        return len(self.ops)

    def __getitem__(self, i):
        if isinstance(i, slice):
            return History(self.ops[i])
        return self.ops[i]

    def __iter__(self) -> Iterator[dict]:
        return iter(self.ops)

    def __eq__(self, other) -> bool:
        if isinstance(other, History):
            return self.ops == other.ops
        if isinstance(other, list):
            return self.ops == other
        return NotImplemented

    def __repr__(self) -> str:
        return f"History({len(self.ops)} ops)"

    # -- Derived structure --------------------------------------------------
    def index(self) -> "History":
        """Return a history whose ops carry an :index field equal to their
        position (reference: knossos history/index via core.clj:228). Ops
        that already have correct indices are reused; a fully-indexed
        history returns itself (re-indexing a 100k-op history costs
        half a second of pure dict traffic)."""
        if self._indexed:
            return self   # verified (or built) by an earlier call
        if all(o.get("index") == i for i, o in enumerate(self.ops)):
            self._indexed = True
            return self
        out = []
        for i, o in enumerate(self.ops):
            if o.get("index") != i:
                o = dict(o)
                o["index"] = i
            out.append(o)
        h = History(out)
        h._indexed = True
        return h

    def pair_index(self) -> dict[int, int]:
        """Map from op position -> position of its partner (invoke <->
        completion), for client ops. Pending invocations (no completion) and
        nemesis ops are absent. Requires ops in journal order."""
        if self._pair_index is not None:
            return self._pair_index
        pairs: dict[int, int] = {}
        open_by_process: dict[Any, int] = {}
        for i, o in enumerate(self.ops):
            p = o["process"]
            if not isinstance(p, int):
                continue  # nemesis ops don't pair
            if is_invoke(o):
                open_by_process[p] = i
            else:
                j = open_by_process.pop(p, None)
                if j is not None:
                    pairs[i] = j
                    pairs[j] = i
        self._pair_index = pairs
        return pairs

    def completion(self, i: int) -> dict | None:
        """The completion op for the invocation at position i, or None."""
        j = self.pair_index().get(i)
        return self.ops[j] if j is not None else None

    def invocation(self, i: int) -> dict | None:
        j = self.pair_index().get(i)
        return self.ops[j] if j is not None else None

    # -- Filters ------------------------------------------------------------
    def filter(self, pred: Callable[[dict], bool]) -> "History":
        return History(o for o in self.ops if pred(o))

    def invocations(self) -> "History":
        return self.filter(is_invoke)

    def completions(self) -> "History":
        return self.filter(is_completion)

    def oks(self) -> "History":
        return self.filter(is_ok)

    def fails(self) -> "History":
        return self.filter(is_fail)

    def infos(self) -> "History":
        return self.filter(is_info)

    def client_ops(self) -> "History":
        return self.filter(is_client_op)

    def filter_f(self, f: Any) -> "History":
        fs = f if isinstance(f, (set, frozenset, tuple, list)) else (f,)
        fs = set(fs)
        return self.filter(lambda o: o["f"] in fs)

    def pending(self) -> "History":
        """Client invocations with no completion — the open tail a
        crash, SIGKILL, or op-timeout leaves behind. A salvaged journal
        ends with these; checkers treat them as indeterminate, so the
        prefix stays checkable (cf. P-compositional checking)."""
        pairs = self.pair_index()
        return History(o for i, o in enumerate(self.ops)
                       if is_invoke(o) and isinstance(o["process"], int)
                       and i not in pairs)

    def without_failures(self) -> "History":
        """Drop :fail completions and their invocations — failed ops are
        known to have not taken effect (knossos semantics)."""
        pairs = self.pair_index()
        drop = set()
        for i, o in enumerate(self.ops):
            if is_fail(o):
                drop.add(i)
                j = pairs.get(i)
                if j is not None:
                    drop.add(j)
        return History(o for i, o in enumerate(self.ops) if i not in drop)


def history(ops: Iterable[dict] | History) -> History:
    if isinstance(ops, History):
        return ops
    return History(ops)


# ---------------------------------------------------------------------------
# Device encoding: operations as structure-of-arrays
# ---------------------------------------------------------------------------

# Function codes for the register family (read/write/cas). Other workloads
# register their own codes; these cover the knossos-model kernels.
F_READ = 0
F_WRITE = 1
F_CAS = 2

# Outcome kinds for paired operations.
KIND_OK = 0      # completed :ok — must linearize with recorded result
KIND_INFO = 1    # crashed :info — may linearize (successfully) or never


@dataclasses.dataclass
class OpArray:
    """A history lowered to one row per *logical operation* (invoke paired
    with completion), sorted by invocation order.

    Fields (all numpy, length n):
      f        int32 — function code (F_READ/F_WRITE/F_CAS/...)
      a        int32 — 1st argument (write value, cas old, read-observed)
      b        int32 — 2nd argument (cas new), NIL otherwise
      kind     int32 — KIND_OK | KIND_INFO
      inv      int32 — invocation's rank within the client-op stream this
                       array was built from (ordering only)
      ret      int32 — completion's rank in that same stream, or
                       PENDING_RET (int32 max) for pending/:info ops
      process  int32 — process id (client ops only)
      index    int32 — invocation's :index in the source history (equals
                       inv-rank only if the history was pre-filtered);
                       use this to point back at real ops

    Failed ops are excluded (they did not take effect); crashed reads are
    excluded (a pending read constrains nothing). See checker/wgl.py for the
    soundness argument.
    """
    f: np.ndarray
    a: np.ndarray
    b: np.ndarray
    kind: np.ndarray
    inv: np.ndarray
    ret: np.ndarray
    process: np.ndarray
    index: np.ndarray

    def __len__(self) -> int:
        return len(self.f)

    @property
    def n_ok(self) -> int:
        return int((self.kind == KIND_OK).sum())


# int32 max: ships to TPU unharmed (x64 is typically disabled, and TPUs
# have no native int64 — an int64 sentinel like 2**62 would silently wrap).
PENDING_RET = np.int32(2**31 - 1)


def default_register_codec(o: dict) -> tuple[int, int, int]:
    """Value codec for read/write/cas register ops.

    read:  value is the observed register value (or None on invoke)
    write: value is the written value
    cas:   value is a (old, new) pair
    """
    f = o["f"]
    v = o["value"]
    if f in ("read", "r", F_READ):
        return F_READ, NIL if v is None else int(v), NIL
    if f in ("write", "w", F_WRITE):
        return F_WRITE, int(v), NIL
    if f in ("cas", F_CAS):
        old, new = v
        return F_CAS, int(old), int(new)
    raise DeviceEncodingError(f"unknown register op f={f!r}")


def encode_ops(h: History,
               codec: Callable[[dict], tuple[int, int, int]]
               = default_register_codec,
               drop_pending: frozenset | None = None) -> OpArray:
    """Lower a history to an OpArray for the device checkers.

    Pairing/semantics follow knossos: each client invoke pairs with the next
    completion from the same process; :fail pairs are dropped; :info ops are
    pending forever (ret = PENDING_RET); the *completion's* value is
    authoritative for :ok ops (a read's observed value arrives on the :ok
    op).

    drop_pending: f-codes whose pending (crashed) ops constrain nothing and
    may be elided. This is codec-specific — f-code meanings differ per codec
    (mutex 'acquire' is 0 too) — so the default only drops reads for the
    default register codec and nothing otherwise; keeping a droppable
    pending op is always sound, just slower.
    """
    if drop_pending is None:
        drop_pending = (frozenset({F_READ})
                        if codec is default_register_codec else frozenset())
    if h.ops and "index" not in h.ops[0]:
        h = h.index()
    h = h.client_ops()
    pairs = h.pair_index()
    rows = []
    for i, o in enumerate(h.ops):
        if not is_invoke(o):
            continue
        j = pairs.get(i)
        comp = h.ops[j] if j is not None else None
        if comp is not None and is_fail(comp):
            continue  # did not take effect
        if comp is None or is_info(comp):
            # Pending forever. Ops whose f is in drop_pending constrain
            # nothing when pending (e.g. reads) and are elided.
            f, a, b = codec(o)
            if f in drop_pending:
                continue
            rows.append((f, a, b, KIND_INFO, i, PENDING_RET,
                         o["process"], o.get("index", i)))
        else:
            f, a, b = codec(comp)  # completion value is authoritative
            rows.append((f, a, b, KIND_OK, i, j,
                         o["process"], o.get("index", i)))
    if rows:
        cols = list(zip(*rows))
    else:
        cols = [[] for _ in range(8)]
    return OpArray(
        f=np.asarray(cols[0], np.int32),
        a=np.asarray(cols[1], np.int32),
        b=np.asarray(cols[2], np.int32),
        kind=np.asarray(cols[3], np.int32),
        inv=np.asarray(cols[4], np.int32),
        ret=np.asarray(cols[5], np.int32),
        process=np.asarray(cols[6], np.int32),
        index=np.asarray(cols[7], np.int32),
    )
