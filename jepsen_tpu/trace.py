"""Distributed tracing for tests and clients (the reference's
OpenCensus→Jaeger wiring, `dgraph/src/jepsen/dgraph/trace.clj:1-73`,
re-designed dependency-free).

The reference builds spans with the OpenCensus tracer and exports them
to a Jaeger collector. Here a tracer is a contextvar-scoped span stack:
`span("name")` opens a scoped span (the `with-trace` macro), `annotate`
/ `attribute` decorate the current span (`trace.clj:59-73`), and
`context()` returns the {span-id, trace-id} map workloads attach to
checker violations (`trace.clj:51-57`, used by `bank.clj:160-166`).

Finished spans are recorded Jaeger-JSON-shaped and exported either to
an in-memory buffer (always), a JSONL file (endpoint = a filesystem
path), or an HTTP collector (endpoint = http(s) URL, posted
best-effort in Jaeger's /api/traces JSON format). Sampling follows the
reference: enabled iff an endpoint is configured (`trace.clj:9-14`).
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import random
import threading
import time
import urllib.request
from typing import Any

_stack: contextvars.ContextVar[tuple] = contextvars.ContextVar(
    "trace_stack", default=())


class Span:
    __slots__ = ("name", "trace_id", "span_id", "parent_id", "start_us",
                 "duration_us", "tags", "logs")

    def __init__(self, name: str, trace_id: str, parent_id: str | None):
        self.name = name
        self.trace_id = trace_id
        self.span_id = f"{random.getrandbits(64):016x}"
        self.parent_id = parent_id
        self.start_us = int(time.time() * 1e6)
        self.duration_us = 0
        self.tags: dict[str, str] = {}
        self.logs: list[dict] = []

    def to_jaeger(self) -> dict:
        """One span in Jaeger JSON shape."""
        return {
            "traceID": self.trace_id,
            "spanID": self.span_id,
            "parentSpanID": self.parent_id or "",
            "operationName": self.name,
            "startTime": self.start_us,
            "duration": self.duration_us,
            "tags": [{"key": k, "type": "string", "value": v}
                     for k, v in self.tags.items()],
            "logs": self.logs,
            "process": {"serviceName": "jepsen"},
        }


class Tracer:
    """Sampler + exporter. `endpoint=None` disables sampling — spans
    become no-ops, mirroring `Samplers/neverSample`
    (`trace.clj:9-14`)."""

    def __init__(self, endpoint: str | None = None,
                 buffer_limit: int = 100_000):
        self.endpoint = endpoint
        self.enabled = endpoint is not None
        self.buffer: list[dict] = []
        self.buffer_limit = buffer_limit
        self.lock = threading.Lock()
        self._file = None
        if self.enabled and not str(endpoint).startswith(
                ("http://", "https://")):
            self._file = open(endpoint, "a", encoding="utf8")  # noqa: SIM115 — long-lived exporter

    # -- span lifecycle ------------------------------------------------------

    @contextlib.contextmanager
    def span(self, name: str):
        """Scoped span (the `with-trace` macro, `trace.clj:40-49`)."""
        if not self.enabled:
            yield None
            return
        stack = _stack.get()
        parent = stack[-1] if stack else None
        trace_id = parent.trace_id if parent \
            else f"{random.getrandbits(128):032x}"
        sp = Span(name, trace_id, parent.span_id if parent else None)
        token = _stack.set(stack + (sp,))
        t0 = time.monotonic()
        try:
            yield sp
        finally:
            sp.duration_us = int((time.monotonic() - t0) * 1e6)
            _stack.reset(token)
            self._record(sp)

    def current(self) -> Span | None:
        stack = _stack.get()
        return stack[-1] if stack else None

    def context(self) -> dict:
        """{span-id, trace-id} of the current span (`trace.clj:51-57`)."""
        sp = self.current()
        if sp is None:
            return {"span-id": None, "trace-id": None}
        return {"span-id": sp.span_id, "trace-id": sp.trace_id}

    def annotate(self, message: str) -> None:
        """`trace.clj:59-63`."""
        sp = self.current()
        if sp is not None:
            sp.logs.append({"timestamp": int(time.time() * 1e6),
                            "fields": [{"key": "message",
                                        "value": str(message)}]})

    def attribute(self, k: str, v: Any) -> None:
        """Keys and values are coerced to strings, as opencensus
        requires (`trace.clj:65-73`)."""
        sp = self.current()
        if sp is not None:
            sp.tags[str(k)] = str(v)

    # -- export --------------------------------------------------------------

    def _record(self, sp: Span) -> None:
        doc = sp.to_jaeger()
        with self.lock:
            if len(self.buffer) < self.buffer_limit:
                self.buffer.append(doc)
            if self._file is not None:
                self._file.write(json.dumps(doc) + "\n")
                self._file.flush()
        if self._file is None and self.enabled:
            self._post([doc])

    def _post(self, docs: list[dict]) -> None:
        """Best-effort POST to a Jaeger-style HTTP collector."""
        try:
            body = json.dumps({"data": [{
                "traceID": docs[0]["traceID"], "spans": docs}]}).encode()
            req = urllib.request.Request(
                self.endpoint, data=body, method="POST",
                headers={"Content-Type": "application/json"})
            urllib.request.urlopen(req, timeout=1.0).close()
        except OSError:
            pass   # tracing must never fail an op

    def spans(self, name: str | None = None) -> list[dict]:
        with self.lock:
            if name is None:
                return list(self.buffer)
            return [s for s in self.buffer if s["operationName"] == name]

    def close(self) -> None:
        with self.lock:
            if self._file is not None:
                self._file.close()
                self._file = None


# -- module-level default tracer (what suites import) ------------------------

_default = Tracer(None)


def tracing(endpoint: str | None) -> dict:
    """Install the default tracer for an endpoint; returns the config
    map stored on the test (`trace.clj:34-38`)."""
    global _default
    _default.close()
    _default = Tracer(endpoint)
    return {"endpoint": endpoint, "config": _default.enabled,
            "exporter": _default}


def tracer() -> Tracer:
    return _default


def span(name: str):
    return _default.span(name)


def context() -> dict:
    return _default.context()


def annotate(message: str) -> None:
    _default.annotate(message)


def attribute(k: str, v: Any) -> None:
    _default.attribute(k, v)
