"""Distributed tracing for tests and clients (the reference's
OpenCensus→Jaeger wiring, `dgraph/src/jepsen/dgraph/trace.clj:1-73`,
re-designed dependency-free).

The reference builds spans with the OpenCensus tracer and exports them
to a Jaeger collector. Here a tracer is a contextvar-scoped span stack:
`span("name")` opens a scoped span (the `with-trace` macro), `annotate`
/ `attribute` decorate the current span (`trace.clj:59-73`), and
`context()` returns the {span-id, trace-id} map workloads attach to
checker violations (`trace.clj:51-57`, used by `bank.clj:160-166`).

Finished spans are recorded Jaeger-JSON-shaped and exported either to
an in-memory buffer (always), a JSONL file (endpoint = a filesystem
path), or an HTTP collector (endpoint = http(s) URL, posted
best-effort in Jaeger's /api/traces JSON format). Sampling follows the
reference: enabled iff an endpoint is configured (`trace.clj:9-14`).

HTTP export is asynchronous: finished spans land in a bounded queue
drained in batches by a daemon flusher thread, so a slow or
unreachable collector can never stall span creation on the hot path
(each span used to pay a synchronous POST with a 1 s timeout — on the
chunk-dispatch path that froze the checking pipeline). `close()`
performs a final flush; a full queue drops the oldest spans and counts
them in `jepsen_tpu_trace_dropped_total`.

Cross-thread spans: `span(name, parent=ctx)` (and the manual
`start_span`/`finish_span` pair for long-lived spans) accept an
explicit `{"trace-id": ..., "span-id": ...}` parent context, so one
trace id can thread run -> stream -> chunk -> recovery-retry across
the checker's worker threads (`checker/streaming.py` stamps the
resulting trace id on stream verdicts)."""

from __future__ import annotations

import atexit
import collections
import contextlib
import contextvars
import json
import random
import threading
import time
import urllib.request
from typing import Any

from . import telemetry

_stack: contextvars.ContextVar[tuple] = contextvars.ContextVar(
    "trace_stack", default=())

# export tuning: the queue bounds memory under a dead collector; the
# flusher posts at most BATCH spans per request
EXPORT_QUEUE_LIMIT = 4096
EXPORT_BATCH = 256
EXPORT_TIMEOUT_S = 1.0

_M_SPANS = telemetry.counter(
    "jepsen_tpu_trace_spans_total",
    "Finished spans recorded by the tracer")
_M_DROPPED = telemetry.counter(
    "jepsen_tpu_trace_dropped_total",
    "Spans dropped because the HTTP export queue was full")
_M_FLUSH = telemetry.histogram(
    "jepsen_tpu_trace_flush_seconds",
    "HTTP collector POST latency per span batch")


class Span:
    __slots__ = ("name", "trace_id", "span_id", "parent_id", "start_us",
                 "duration_us", "tags", "logs", "_t0")

    def __init__(self, name: str, trace_id: str, parent_id: str | None):
        self.name = name
        self.trace_id = trace_id
        self.span_id = f"{random.getrandbits(64):016x}"
        self.parent_id = parent_id
        self.start_us = int(time.time() * 1e6)
        self.duration_us = 0
        self.tags: dict[str, str] = {}
        self.logs: list[dict] = []
        self._t0 = time.monotonic()

    def context(self) -> dict:
        return {"span-id": self.span_id, "trace-id": self.trace_id}

    def to_jaeger(self) -> dict:
        """One span in Jaeger JSON shape."""
        return {
            "traceID": self.trace_id,
            "spanID": self.span_id,
            "parentSpanID": self.parent_id or "",
            "operationName": self.name,
            "startTime": self.start_us,
            "duration": self.duration_us,
            "tags": [{"key": k, "type": "string", "value": v}
                     for k, v in self.tags.items()],
            "logs": self.logs,
            "process": {"serviceName": "jepsen"},
        }


def _new_trace_id() -> str:
    return f"{random.getrandbits(128):032x}"


class Tracer:
    """Sampler + exporter. `endpoint=None` disables sampling — spans
    become no-ops, mirroring `Samplers/neverSample`
    (`trace.clj:9-14`)."""

    def __init__(self, endpoint: str | None = None,
                 buffer_limit: int = 100_000):
        self.endpoint = endpoint
        self.enabled = endpoint is not None
        self.buffer: list[dict] = []    # guarded-by: lock
        self.buffer_limit = buffer_limit
        self.lock = threading.Lock()
        self._file = None               # guarded-by: lock
        self._http = False
        self._q: collections.deque = collections.deque()  # guarded-by: lock
        self._q_event = threading.Event()
        self._stop = threading.Event()
        self._flusher: threading.Thread | None = None
        if self.enabled:
            if str(endpoint).startswith(("http://", "https://")):
                self._http = True
                self._flusher = threading.Thread(
                    target=self._flush_loop, name="jepsen-trace-flush",
                    daemon=True)
                self._flusher.start()
            else:
                self._file = open(endpoint, "a", encoding="utf8")  # noqa: SIM115 — long-lived exporter

    # -- span lifecycle ------------------------------------------------------

    def _make_span(self, name: str, parent: dict | None) -> Span:
        if parent is not None and parent.get("trace-id"):
            return Span(name, parent["trace-id"],
                        parent.get("span-id"))
        stack = _stack.get()
        psp = stack[-1] if stack else None
        trace_id = psp.trace_id if psp else _new_trace_id()
        return Span(name, trace_id, psp.span_id if psp else None)

    @contextlib.contextmanager
    def span(self, name: str, parent: dict | None = None):
        """Scoped span (the `with-trace` macro, `trace.clj:40-49`).
        `parent` overrides the contextvar stack with an explicit
        {"trace-id", "span-id"} context — the cross-thread form."""
        if not self.enabled:
            yield None
            return
        sp = self._make_span(name, parent)
        token = _stack.set(_stack.get() + (sp,))
        try:
            yield sp
        finally:
            sp.duration_us = int((time.monotonic() - sp._t0) * 1e6)
            _stack.reset(token)
            self._record(sp)

    def start_span(self, name: str,
                   parent: dict | None = None) -> Span | None:
        """Open a long-lived span WITHOUT entering the contextvar
        stack (a stream worker owns it across many feed calls); pair
        with finish_span. None when sampling is off."""
        if not self.enabled:
            return None
        return self._make_span(name, parent)

    def finish_span(self, sp: Span | None) -> None:
        if sp is None or not self.enabled:
            return
        sp.duration_us = int((time.monotonic() - sp._t0) * 1e6)
        self._record(sp)

    def new_context(self) -> dict:
        """A fresh root trace context (no parent span) — the anchor a
        run/stream uses when nothing upstream opened a span. The null
        context when sampling is off."""
        if not self.enabled:
            return {"span-id": None, "trace-id": None}
        return {"span-id": None, "trace-id": _new_trace_id()}

    def current(self) -> Span | None:
        stack = _stack.get()
        return stack[-1] if stack else None

    def context(self) -> dict:
        """{span-id, trace-id} of the current span (`trace.clj:51-57`)."""
        sp = self.current()
        if sp is None:
            return {"span-id": None, "trace-id": None}
        return sp.context()

    def annotate(self, message: str) -> None:
        """`trace.clj:59-63`."""
        sp = self.current()
        if sp is not None:
            sp.logs.append({"timestamp": int(time.time() * 1e6),
                            "fields": [{"key": "message",
                                        "value": str(message)}]})

    def attribute(self, k: str, v: Any) -> None:
        """Keys and values are coerced to strings, as opencensus
        requires (`trace.clj:65-73`)."""
        sp = self.current()
        if sp is not None:
            sp.tags[str(k)] = str(v)

    # -- export --------------------------------------------------------------

    def _record(self, sp: Span) -> None:
        doc = sp.to_jaeger()
        _M_SPANS.inc()
        dropped = False
        with self.lock:
            if len(self.buffer) < self.buffer_limit:
                self.buffer.append(doc)
            if self._file is not None:
                self._file.write(json.dumps(doc) + "\n")
                self._file.flush()
            if self._http:
                # bounded enqueue, never a network call: the flusher
                # thread owns the POSTs (one lock acquisition covers
                # buffer + queue — this is the hot path)
                if len(self._q) >= EXPORT_QUEUE_LIMIT:
                    self._q.popleft()
                    dropped = True
                self._q.append(doc)
        if dropped:
            _M_DROPPED.inc()
        if self._http:
            self._q_event.set()

    def _drain(self, n: int) -> list[dict]:
        out: list[dict] = []
        with self.lock:
            while self._q and len(out) < n:
                out.append(self._q.popleft())
        return out

    def _flush_loop(self) -> None:
        while not self._stop.is_set():
            self._q_event.wait(0.5)
            self._q_event.clear()
            docs = self._drain(EXPORT_BATCH)
            while docs:
                self._post(docs)
                docs = self._drain(EXPORT_BATCH)

    def flush(self, max_batches: int | None = None) -> None:
        """Synchronously post everything queued (close() calls this;
        tests may too), up to max_batches POSTs (None = drain fully).
        No-op for file/disabled tracers."""
        n = 0
        docs = self._drain(EXPORT_BATCH)
        while docs:
            self._post(docs)
            n += 1
            if max_batches is not None and n >= max_batches:
                return
            docs = self._drain(EXPORT_BATCH)

    def _post(self, docs: list[dict]) -> None:
        """Best-effort POST to a Jaeger-style HTTP collector, one
        request per traceID group (Jaeger's /api/traces shape nests
        spans under their trace)."""
        groups: dict[str, list[dict]] = {}
        for d in docs:
            groups.setdefault(d["traceID"], []).append(d)
        try:
            with _M_FLUSH.time():
                body = json.dumps({"data": [
                    {"traceID": tid, "spans": spans}
                    for tid, spans in groups.items()]}).encode()
                req = urllib.request.Request(
                    self.endpoint, data=body, method="POST",
                    headers={"Content-Type": "application/json"})
                urllib.request.urlopen(req,
                                       timeout=EXPORT_TIMEOUT_S).close()
        except OSError:
            pass   # tracing must never fail an op

    def spans(self, name: str | None = None) -> list[dict]:
        with self.lock:
            if name is None:
                return list(self.buffer)
            return [s for s in self.buffer if s["operationName"] == name]

    def close(self) -> None:
        """Stop the flusher (after a final flush) and close the file
        sink. Bounded even against a wedged collector: when the
        flusher fails to join (it is stuck inside a POST), the queue
        is DROPPED (counted) instead of re-posted synchronously, and
        a clean join's residual flush is capped at two batches — so
        close() costs at most a couple of POST timeouts, never a
        queue-length hang on the drain/shutdown path."""
        self._stop.set()
        self._q_event.set()
        if self._flusher is not None:
            self._flusher.join(2 * EXPORT_TIMEOUT_S)
            wedged = self._flusher.is_alive()
            self._flusher = None
            if not wedged:
                self.flush(max_batches=2)
            # wedged: the flusher is stuck inside a POST — re-posting
            # synchronously would hang too; the drop below covers it
        with self.lock:
            if self._q:
                _M_DROPPED.inc(len(self._q))
                self._q.clear()
            if self._file is not None:
                self._file.close()
                self._file = None


# -- module-level default tracer (what suites import) ------------------------

_default = Tracer(None)


def _close_default() -> None:
    _default.close()


# the async exporter must not lose the tail at process exit: the old
# synchronous POST delivered every span before _record returned; the
# flusher needs one final bounded flush when the interpreter goes down
# (suites install tracing() and never close it themselves)
atexit.register(_close_default)


def tracing(endpoint: str | None) -> dict:
    """Install the default tracer for an endpoint; returns the config
    map stored on the test (`trace.clj:34-38`)."""
    global _default
    _default.close()
    _default = Tracer(endpoint)
    return {"endpoint": endpoint, "config": _default.enabled,
            "exporter": _default}


def tracer() -> Tracer:
    return _default


def span(name: str, parent: dict | None = None):
    return _default.span(name, parent=parent)


def context() -> dict:
    return _default.context()


def new_context() -> dict:
    return _default.new_context()


def annotate(message: str) -> None:
    _default.annotate(message)


def attribute(k: str, v: Any) -> None:
    _default.attribute(k, v)
