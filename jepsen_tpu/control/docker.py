"""Docker remote: `docker exec` / `docker cp` as the control transport.

Reference: `jepsen/src/jepsen/control/docker.clj` — an alternate Remote
for nodes that are local containers rather than SSH-able machines. The
conn spec's host is the container name/id.
"""

from __future__ import annotations

from .core import Remote, RemoteError, cli_run


class DockerRemote(Remote):
    def __init__(self, container: str | None = None, binary: str = "docker"):
        self.container = container
        self.binary = binary

    def connect(self, conn_spec: dict) -> "DockerRemote":
        return DockerRemote(conn_spec["host"], self.binary)

    def _run(self, argv, stdin=None) -> dict:
        return cli_run(argv, stdin)

    def execute(self, context: dict, action: dict) -> dict:
        argv = [self.binary, "exec", "-i", self.container,
                "/bin/sh", "-c", action["cmd"]]
        res = self._run(argv, action.get("in"))
        return {**action, **res, "host": self.container}

    def upload(self, context, local_paths, remote_path, opts=None):
        if isinstance(local_paths, (str, bytes)):
            local_paths = [local_paths]
        for p in local_paths:
            res = self._run([self.binary, "cp", str(p),
                             f"{self.container}:{remote_path}"])
            if res["exit"] != 0:
                raise RemoteError(f"docker cp to {self.container} failed: "
                                  f"{res['err']}", res)

    def download(self, context, remote_paths, local_path, opts=None):
        if isinstance(remote_paths, (str, bytes)):
            remote_paths = [remote_paths]
        for p in remote_paths:
            res = self._run([self.binary, "cp",
                             f"{self.container}:{p}", str(local_path)])
            if res["exit"] != 0:
                raise RemoteError(
                    f"docker cp from {self.container} failed: "
                    f"{res['err']}", res)


def remote() -> DockerRemote:
    return DockerRemote()
