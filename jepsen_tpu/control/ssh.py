"""OpenSSH-based remote: shells out to `ssh`/`scp` with connection
multiplexing.

Reference: the JSch default remote (`control/clj_ssh.clj`) and the SSHJ
remote (`control/sshj.clj`). Two hard-won behaviors are replicated:

* channel limiting — OpenSSH servers cap sessions per connection at 10;
  the reference derates to a fair Semaphore of **6** concurrent channels
  per connection (`control/sshj.clj:173-179`). We keep the same limit
  around concurrent `ssh -S <mux>` invocations.
* scp for bulk files — the reference shells out to `scp` because JVM SFTP
  is "orders of magnitude slower" for GB-scale files
  (`control/scp.clj:1-15`). Here scp *is* the transfer path.

A ControlMaster socket gives one authenticated TCP connection per node
(the analog of the reference's persistent JSch session) so each exec is a
cheap mux client, not a fresh handshake.
"""

from __future__ import annotations

import shutil
import subprocess
import tempfile
import threading
from typing import Sequence

from .core import Remote, RemoteError, cli_run

CONCURRENCY_LIMIT = 6  # channels per connection, `sshj.clj:173-179`


def available() -> bool:
    return shutil.which("ssh") is not None


class SSHRemote(Remote):
    def __init__(self, conn_spec: dict | None = None):
        self.spec = conn_spec or {}
        self.host = self.spec.get("host")
        self._sem = threading.Semaphore(CONCURRENCY_LIMIT)
        self._mux_dir = None

    # -- connection ---------------------------------------------------------

    def connect(self, conn_spec: dict) -> "SSHRemote":
        if not available():
            raise RemoteError("no `ssh` binary on the control node; use "
                              "the dummy/docker remote or install OpenSSH")
        r = SSHRemote(conn_spec)
        r._mux_dir = tempfile.mkdtemp(prefix="jepsen-ssh-")
        # Open the master eagerly so auth errors surface here. DEVNULL all
        # fds: with pipes, the forked ControlMaster inherits stderr and
        # subprocess.run blocks on EOF until the timeout.
        p = subprocess.run(r._ssh_argv() + ["-fN"],
                           stdin=subprocess.DEVNULL,
                           stdout=subprocess.DEVNULL,
                           stderr=subprocess.DEVNULL, timeout=30)
        check = r._run(r._base_ssh() + ["-O", "check", r._dest()], None,
                       timeout=10)
        if p.returncode != 0 or check["exit"] != 0:
            raise RemoteError(
                f"ssh connect to {r.host} failed "
                f"(exit {p.returncode}): {check['err']}",
                {"exit": -1, **check})
        return r

    def disconnect(self) -> None:
        if self._mux_dir:
            subprocess.run(self._base_ssh() + ["-O", "exit", self._dest()],
                           capture_output=True)
            shutil.rmtree(self._mux_dir, ignore_errors=True)
            self._mux_dir = None

    # -- argv construction --------------------------------------------------

    def _dest(self) -> str:
        user = self.spec.get("username")
        return f"{user}@{self.host}" if user else str(self.host)

    def _base_ssh(self) -> list[str]:
        argv = ["ssh"]
        if self._mux_dir:
            argv += ["-o", "ControlMaster=auto",
                     "-o", f"ControlPath={self._mux_dir}/mux",
                     "-o", "ControlPersist=60"]
        if not self.spec.get("strict-host-key-checking", True):
            argv += ["-o", "StrictHostKeyChecking=no",
                     "-o", "UserKnownHostsFile=/dev/null"]
        if self.spec.get("port"):
            argv += ["-p", str(self.spec["port"])]
        if self.spec.get("private-key-path"):
            argv += ["-i", str(self.spec["private-key-path"])]
        argv += ["-o", "BatchMode=yes"]
        return argv

    def _ssh_argv(self) -> list[str]:
        return self._base_ssh() + [self._dest()]

    def _scp_argv(self) -> list[str]:
        argv = ["scp", "-rq"]
        if self._mux_dir:
            argv += ["-o", f"ControlPath={self._mux_dir}/mux"]
        if not self.spec.get("strict-host-key-checking", True):
            argv += ["-o", "StrictHostKeyChecking=no",
                     "-o", "UserKnownHostsFile=/dev/null"]
        if self.spec.get("port"):
            argv += ["-P", str(self.spec["port"])]
        if self.spec.get("private-key-path"):
            argv += ["-i", str(self.spec["private-key-path"])]
        return argv

    # -- actions ------------------------------------------------------------

    def _run(self, argv: Sequence[str], stdin: str | None,
             timeout: float | None = None) -> dict:
        return cli_run(argv, stdin, timeout)

    def execute(self, context: dict, action: dict) -> dict:
        # actions arrive fully wrapped (cd+sudo) from the DSL layer
        with self._sem:
            res = self._run(self._ssh_argv() + [action["cmd"]],
                            action.get("in"),
                            timeout=action.get("timeout"))
        # OpenSSH reports its own connection/transport failures as client
        # exit 255; raise (rather than return a result) so the retry
        # wrapper reconnects and retries — a remote command's own status
        # is what execute() *returns*.
        if res["exit"] == 255:
            raise RemoteError(
                f"ssh transport failure to {self.host}: {res['err']}",
                {"exit": -1, "err": res["err"], "out": res["out"]})
        return {**action, **res, "host": self.host}

    def upload(self, context, local_paths, remote_path, opts=None):
        if isinstance(local_paths, (str, bytes)):
            local_paths = [local_paths]
        with self._sem:
            res = self._run(self._scp_argv() + [str(p) for p in local_paths]
                            + [f"{self._dest()}:{remote_path}"], None)
        if res["exit"] != 0:
            raise RemoteError(f"scp upload to {self.host} failed: "
                              f"{res['err']}", {**res, "exit": -1})

    def download(self, context, remote_paths, local_path, opts=None):
        if isinstance(remote_paths, (str, bytes)):
            remote_paths = [remote_paths]
        with self._sem:
            res = self._run(
                self._scp_argv()
                + [f"{self._dest()}:{p}" for p in remote_paths]
                + [str(local_path)], None)
        if res["exit"] != 0:
            raise RemoteError(f"scp download from {self.host} failed: "
                              f"{res['err']}", {**res, "exit": -1})


def remote() -> SSHRemote:
    return SSHRemote()
