"""Retrying remote wrapper: reconnect + bounded retries around any Remote.

Reference: `jepsen/src/jepsen/control/retry.clj` — wraps a Remote in a
stateful auto-reconnecting connection and retries failed operations
**5 times** (`retry.clj:15-30`), because transient SSH failures (EOFs,
dropped channels, slow sshds) are routine during fault injection.

Delays follow capped exponential backoff with *decorrelated jitter*
(sleep = min(cap, U(base, 3·prev))) instead of the reference's fixed
~100 ms: when a partition heals, N workers all lost their connections
at the same instant, and a fixed delay has them retrying in lockstep —
hammering the node's sshd in synchronized waves. Jitter spreads them
out; the cap bounds the worst-case wait.

Commands that fail with a *nonzero exit status* are NOT retried — that's
a real result, not transport trouble. Only transport-level exceptions
trigger reconnect+retry.

`backoff()` is also the delay schedule for the checkers' device-fault
recovery ladders (wgl/_RecoveryTrail, streaming.WglStream): a TPU that
just OOMed or dropped off the bus is the same shape of problem as a
node whose sshd is drowning — N retriers hammering it in lockstep make
it worse, decorrelated jitter spreads them out.
"""

from __future__ import annotations

import random as _random
import threading
import time
from typing import Iterator

from .core import Remote, RemoteError

RETRIES = 5
BACKOFF_S = 0.1       # base (and first) delay
BACKOFF_CAP_S = 2.0   # delays never exceed this


def backoff(base_s: float = BACKOFF_S, cap_s: float = BACKOFF_CAP_S,
            rng: _random.Random | None = None) -> Iterator[float]:
    """Infinite generator of retry delays: base first, then
    decorrelated jitter — sleep = min(cap, U(base, 3·prev)) (the AWS
    "exponential backoff and jitter" scheme). Every delay lies in
    [base, cap]. Pass a seeded rng for a deterministic schedule."""
    u = (rng or _random).uniform
    sleep = base_s
    while True:
        yield sleep
        sleep = min(cap_s, u(base_s, sleep * 3))


class RetryRemote(Remote):
    def __init__(self, inner: Remote, retries: int = RETRIES,
                 backoff_s: float = BACKOFF_S,
                 backoff_cap_s: float = BACKOFF_CAP_S,
                 rng: _random.Random | None = None):
        self.inner = inner          # unconnected prototype
        self.retries = retries
        self.backoff_s = backoff_s
        self.backoff_cap_s = backoff_cap_s
        self.rng = rng
        self.conn_spec = None
        self._conn: Remote | None = None
        self._lock = threading.Lock()

    def connect(self, conn_spec: dict) -> "RetryRemote":
        r = RetryRemote(self.inner, self.retries, self.backoff_s,
                        self.backoff_cap_s, self.rng)
        r.conn_spec = dict(conn_spec)
        r._conn = self.inner.connect(conn_spec)
        return r

    def disconnect(self) -> None:
        with self._lock:
            if self._conn is not None:
                self._conn.disconnect()
                self._conn = None

    def _reconnect(self) -> Remote:
        with self._lock:
            if self._conn is not None:
                try:
                    self._conn.disconnect()
                except Exception:
                    pass
            self._conn = self.inner.connect(self.conn_spec)
            return self._conn

    def _with_retry(self, f):
        last = None
        delays = backoff(self.backoff_s, self.backoff_cap_s, self.rng)
        for attempt in range(self.retries + 1):
            conn = self._conn
            if conn is None:
                try:
                    conn = self._reconnect()
                except Exception as e:
                    last = e
                    time.sleep(next(delays))
                    continue
            try:
                return f(conn)
            except RemoteError as e:
                # A real command result: propagate, don't retry.
                if e.exit is not None and e.exit >= 0:
                    raise
                last = e
            except Exception as e:
                last = e
            time.sleep(next(delays))
            try:
                self._reconnect()
            except Exception as e:
                last = e
        raise RemoteError(f"remote operation failed after "
                          f"{self.retries + 1} attempts: {last}",
                          getattr(last, "result", None) or {})

    def execute(self, context, action) -> dict:
        return self._with_retry(lambda c: c.execute(context, action))

    def upload(self, context, local_paths, remote_path, opts=None):
        return self._with_retry(
            lambda c: c.upload(context, local_paths, remote_path, opts))

    def download(self, context, remote_paths, local_path, opts=None):
        return self._with_retry(
            lambda c: c.download(context, remote_paths, local_path, opts))


def remote(inner: Remote, **kw) -> RetryRemote:
    return RetryRemote(inner, **kw)
