"""Dummy remote: a no-op control backend for hermetic runs.

Reference behavior: `:ssh {:dummy? true}` makes the whole control layer a
no-op (`jepsen/src/jepsen/control.clj:40`, `cli.clj:85-86` `--no-ssh`), so
a complete end-to-end test executes in one process with no cluster. This
implementation additionally journals every action (for assertions in
tests) and supports scripted responses keyed by command regex.
"""

from __future__ import annotations

import re
import threading
from typing import Callable

from .core import Remote


class DummyRemote(Remote):
    """Pretends to run everything, successfully and instantly.

    ``responses`` is an ordered mapping of command-regex → canned stdout
    (or a callable (context, action) → result-fields dict). All executed
    actions are appended to ``log`` as (host, context, action) tuples,
    shared across connect()'d copies so a test can inspect the full
    cluster-wide command stream.
    """

    def __init__(self, responses=None, log=None, files=None):
        self.responses = list((responses or {}).items())
        self.log: list = log if log is not None else []
        # remote-path → contents uploaded; shared across connections
        self.files: dict = files if files is not None else {}
        self.host = None
        self._lock = threading.Lock()

    def connect(self, conn_spec: dict) -> "DummyRemote":
        r = DummyRemote(dict(self.responses), self.log, self.files)
        r.host = conn_spec.get("host")
        return r

    def execute(self, context: dict, action: dict) -> dict:
        cmd = action.get("cmd", "")
        with self._lock:
            self.log.append((self.host, dict(context or {}), dict(action)))
        for pattern, resp in self.responses:
            if re.search(pattern, cmd):
                if isinstance(resp, Callable):
                    extra = resp(context, action)
                    return {**action, "exit": 0, "out": "", "err": "",
                            **extra}
                return {**action, "exit": 0, "out": resp, "err": ""}
        return {**action, **self._fake_fs(cmd), "err": ""}

    def _fake_fs(self, cmd: str) -> dict:
        """Minimal filesystem semantics over the shared ``files`` map,
        so existence-polling helpers (exists/stat, tmp_dir, cached_wget)
        terminate instead of seeing every path as present. Commands may
        arrive wrapped (`cd /foo; stat x`); only the last segment
        matters."""
        # commands may be cd- and sudo-wrapped:
        #   sudo -k -S -u root bash -c "cd /; stat /x"
        tail = cmd.split(";")[-1].strip().rstrip("\"'")
        m = re.fullmatch(r"(?:stat|test -[efd]) (\S+)", tail)
        if m:
            path = m.group(1)
            with self._lock:
                known = any(f == path or f.startswith(path + "/")
                            for f in self.files)
            return {"exit": 0 if known else 1, "out": ""}
        m = re.fullmatch(r"(?:mkdir -p|touch) (\S+)", tail)
        if m:
            with self._lock:
                self.files.setdefault(m.group(1), b"")
            return {"exit": 0, "out": ""}
        m = re.fullmatch(r"mv (\S+) (\S+)", tail)
        if m:
            src, dst = m.groups()
            with self._lock:
                if src in self.files:
                    self.files[dst] = self.files.pop(src)
                else:
                    self.files.setdefault(dst, b"")
            return {"exit": 0, "out": ""}
        return {"exit": 0, "out": ""}

    def upload(self, context, local_paths, remote_path, opts=None):
        if isinstance(local_paths, (str, bytes)):
            local_paths = [local_paths]
        with self._lock:
            for p in local_paths:
                try:
                    with open(p, "rb") as f:
                        self.files[str(remote_path)] = f.read()
                except OSError:
                    self.files[str(remote_path)] = None
                self.log.append((self.host, dict(context or {}),
                                 {"upload": str(p),
                                  "remote": str(remote_path)}))

    def download(self, context, remote_paths, local_path, opts=None):
        if isinstance(remote_paths, (str, bytes)):
            remote_paths = [remote_paths]
        with self._lock:
            for p in remote_paths:
                self.log.append((self.host, dict(context or {}),
                                 {"download": str(p),
                                  "local": str(local_path)}))


def remote(**kw) -> DummyRemote:
    return DummyRemote(**kw)
