"""Kubernetes remote: `kubectl exec` / `kubectl cp` as the control
transport.

Reference: `jepsen/src/jepsen/control/k8s.clj` — an alternate Remote for
nodes that are pods. The conn spec's host is the pod name; an optional
``namespace`` is threaded through.
"""

from __future__ import annotations

from .core import Remote, RemoteError, cli_run


class K8sRemote(Remote):
    def __init__(self, pod: str | None = None, namespace: str = "default",
                 binary: str = "kubectl"):
        self.pod = pod
        self.namespace = namespace
        self.binary = binary

    def connect(self, conn_spec: dict) -> "K8sRemote":
        return K8sRemote(conn_spec["host"],
                         conn_spec.get("namespace", self.namespace),
                         self.binary)

    def _run(self, argv, stdin=None) -> dict:
        return cli_run(argv, stdin)

    def execute(self, context: dict, action: dict) -> dict:
        argv = [self.binary, "-n", self.namespace, "exec", "-i", self.pod,
                "--", "/bin/sh", "-c", action["cmd"]]
        res = self._run(argv, action.get("in"))
        return {**action, **res, "host": self.pod}

    def upload(self, context, local_paths, remote_path, opts=None):
        if isinstance(local_paths, (str, bytes)):
            local_paths = [local_paths]
        for p in local_paths:
            res = self._run([self.binary, "-n", self.namespace, "cp",
                             str(p), f"{self.pod}:{remote_path}"])
            if res["exit"] != 0:
                raise RemoteError(f"kubectl cp to {self.pod} failed: "
                                  f"{res['err']}", res)

    def download(self, context, remote_paths, local_path, opts=None):
        if isinstance(remote_paths, (str, bytes)):
            remote_paths = [remote_paths]
        for p in remote_paths:
            res = self._run([self.binary, "-n", self.namespace, "cp",
                             f"{self.pod}:{p}", str(local_path)])
            if res["exit"] != 0:
                raise RemoteError(f"kubectl cp from {self.pod} failed: "
                                  f"{res['err']}", res)


def remote() -> K8sRemote:
    return K8sRemote()
