"""Remote-node scripting helpers: daemons, archives, downloads, files.

Reference: `jepsen/src/jepsen/control/util.clj` — `await-tcp-port` (:14),
`exists?`/`ls` (:38-61), `tmp-file!`/`tmp-dir!` (:63-86), `write-file!`
(:88), wget + control-node-keyed cache (:104-197), `install-archive!`
(:199-275), `grepkill!` (:286-308), `start-daemon!`/`stop-daemon!` via
start-stop-daemon (:310-384), `signal!` (:399).
"""

from __future__ import annotations

import base64
import logging
import os.path
import random

from .. import util
from . import exec_, ssh_star, var
from .core import RemoteError, env as make_env, escape, lit, \
    throw_on_nonzero_exit
from . import cd

log = logging.getLogger(__name__)

TMP_DIR_BASE = "/tmp/jepsen"
WGET_CACHE_DIR = TMP_DIR_BASE + "/wget-cache"

STD_WGET_OPTS = ["--tries", "20", "--waitretry", "60",
                 "--retry-connrefused", "--dns-timeout", "60",
                 "--connect-timeout", "60", "--read-timeout", "60"]


def meh(f):
    """Run f(), swallowing RemoteErrors (the reference's `meh`)."""
    try:
        return f()
    except RemoteError:
        return None


def await_tcp_port(port: int, retry_interval: float = 1.0,
                   timeout: float = 60.0) -> None:
    """Block until a TCP port is bound on the current node
    (`control/util.clj:14-30`)."""
    util.await_fn(lambda: exec_("nc", "-z", "localhost", port) and None,
                  retry_interval=retry_interval, timeout_secs=timeout,
                  log_message=f"Waiting for port {port} ...")


def exists(filename: str) -> bool:
    """Is a path present? (`control/util.clj:38-43`)"""
    try:
        exec_("stat", filename)
        return True
    except RemoteError:
        return False


def ls(dir: str = ".") -> list[str]:
    """Directory entries, not including . and .. (`control/util.clj:45-51`)."""
    out = exec_("ls", "-A", dir)
    return [l for l in out.split("\n") if l.strip()]


def ls_full(dir: str) -> list[str]:
    """ls with dir prepended to each entry (`control/util.clj:53-61`)."""
    if not dir.endswith("/"):
        dir = dir + "/"
    return [dir + e for e in ls(dir)]


def tmp_file() -> str:
    """A fresh random file under /tmp/jepsen (`control/util.clj:63-76`)."""
    while True:
        f = f"{TMP_DIR_BASE}/{random.randrange(2**31)}"
        if exists(f):
            continue
        try:
            exec_("touch", f)
        except RemoteError:
            exec_("mkdir", "-p", TMP_DIR_BASE)
            exec_("touch", f)
        return f


def tmp_dir() -> str:
    """A fresh random directory under /tmp/jepsen
    (`control/util.clj:78-86`)."""
    while True:
        d = f"{TMP_DIR_BASE}/{random.randrange(2**31)}"
        if exists(d):
            continue
        exec_("mkdir", "-p", d)
        return d


def write_file(content: str, file: str) -> str:
    """Write a string to a remote file via `cat > file` with the content
    on stdin — sudo- and dir-aware via ssh_star's wrapping
    (`control/util.clj:88-102`)."""
    throw_on_nonzero_exit(ssh_star({
        "cmd": f"cat > {escape(file)}", "in": content}))
    return file


def _wget_auth(user: str | None, pw: str | None) -> list[str]:
    if not user:
        return []
    if pw is None:
        raise ValueError("wget auth requires both user and pw")
    return ["--user", user, "--password", pw]


def _wget_helper(*args) -> str:
    """wget with retries on network errors (exit 4)
    (`control/util.clj:113-127`)."""
    tries = 5
    while True:
        try:
            return exec_("wget", *args)
        except RemoteError as e:
            if e.exit == 4 and tries > 0:
                tries -= 1
                continue
            raise


def wget(url: str, force: bool = False, user: str | None = None,
         pw: str | None = None) -> str:
    """Download url to the cwd; skips if present; returns the filename
    (`control/util.clj:133-156`)."""
    filename = os.path.basename(url)
    if force:
        exec_("rm", "-f", filename)
    opts = list(STD_WGET_OPTS) + _wget_auth(user, pw)
    if not exists(filename):
        _wget_helper(*opts, url)
    return filename


def cached_wget(url: str, force: bool = False, user: str | None = None,
                pw: str | None = None) -> str:
    """Download url into the wget cache, keyed by base64(url) so that
    version-in-URL-but-not-filename packages can't alias
    (`control/util.clj:167-197`)."""
    encoded = base64.b64encode(url.encode()).decode()
    dest = f"{WGET_CACHE_DIR}/{encoded}"
    # download to a .part name, rename on success: a failed download must
    # not leave a partial file that later calls mistake for a cached one
    opts = list(STD_WGET_OPTS) + ["-O", dest + ".part"]
    opts += _wget_auth(user, pw)
    if force:
        log.info("Clearing cached copy of %s", url)
        exec_("rm", "-rf", dest)
    if not exists(dest):
        log.info("Downloading %s", url)
        exec_("mkdir", "-p", WGET_CACHE_DIR)
        with cd(WGET_CACHE_DIR):
            _wget_helper(*opts, url)
            exec_("mv", dest + ".part", dest)
    return dest


def install_archive(url: str, dest: str, force: bool = False,
                    user: str | None = None, pw: str | None = None,
                    _retry: bool = True) -> str:
    """Fetch a tarball/zip (file:// or cached wget), extract, and move its
    sole top-level dir's contents (or all roots) to dest
    (`control/util.clj:199-275`)."""
    from . import expand_path

    local = url[len("file://"):] if url.startswith("file://") else None
    file = local or cached_wget(url, force=force, user=user, pw=pw)
    tmpdir = tmp_dir()
    dest = expand_path(dest)
    exec_("rm", "-rf", dest)
    parent = exec_("dirname", dest)
    exec_("mkdir", "-p", parent)
    try:
        with cd(tmpdir):
            if url.endswith(".zip"):
                exec_("unzip", file)
            else:
                exec_("tar", "--no-same-owner", "--no-same-permissions",
                      "--extract", "--file", file)
            if var("sudo") == "root":
                exec_("chown", "-R", "root:root", ".")
            roots = ls()
            assert roots, "Archive contained no files"
            if len(roots) == 1:
                exec_("mv", roots[0], dest)
            else:
                exec_("mv", tmpdir, dest)
    except RemoteError as e:
        err = e.err or ""
        corrupt = any(m in err for m in
                      ("tar: Unexpected EOF",
                       "This does not look like a tar archive",
                       "cannot find zipfile directory"))
        if corrupt and not local and _retry:
            log.info("Retrying corrupt archive download")
            exec_("rm", "-rf", file)
            return install_archive(url, dest, force=True, user=user,
                                   pw=pw, _retry=False)
        if corrupt and local:
            raise RemoteError(
                f"Local archive {local} on node {var('host')} is "
                f"corrupt: {err}", e.result)
        raise
    finally:
        meh(lambda: exec_("rm", "-rf", tmpdir))
    return dest


def ensure_user(username: str) -> str:
    """Make sure a user exists (`control/util.clj:277-284`)."""
    from . import su

    try:
        with su():
            exec_("adduser", "--disabled-password", "--gecos", lit("''"),
                  username)
    except RemoteError as e:
        if "already exists" not in (e.err or "") + str(e):
            raise
    return username


def grepkill(pattern: str, signal="9") -> None:
    """Kill processes matching a pattern. Can't pkill: sudo runs inside a
    `bash -c` wrapper whose argv would match and kill itself — so
    ps|grep|grep -v grep|awk|xargs kill (`control/util.clj:286-308`)."""
    sig = str(signal).lstrip(":").upper() if isinstance(signal, str) \
        else str(signal)
    try:
        exec_("ps", "aux", lit("|"), "grep", pattern,
              lit("|"), "grep", "-v", "grep",
              lit("|"), "awk", lit("'{print $2}'"),
              lit("|"), "xargs", "--no-run-if-empty", "kill", f"-{sig}")
    except RemoteError as e:
        if e.exit == 123 and "No such process" in (e.err or ""):
            return  # already exited
        if e.exit == 0:
            return
        raise


def start_daemon(opts: dict, bin: str, *args) -> str:
    """Start a daemon via start-stop-daemon, logging to opts["logfile"];
    returns "started" or "already-running" (`control/util.clj:310-367`).

    Options: env, background (default True), chdir, exec, logfile,
    make-pidfile (default True), match-executable (default True),
    match-process-name (default False), pidfile, process-name.
    """
    e = make_env(opts.get("env"))
    ssd: list = ["--start"]
    if opts.get("background", True):
        ssd += ["--background", "--no-close"]
    if opts.get("pidfile") and opts.get("make-pidfile", True):
        ssd += ["--make-pidfile"]
    if opts.get("match-executable", True):
        ssd += ["--exec", opts.get("exec", bin)]
    if opts.get("match-process-name", False):
        ssd += ["--name", opts.get("process-name",
                                   os.path.basename(bin))]
    if opts.get("pidfile"):
        ssd += ["--pidfile", opts["pidfile"]]
    ssd += ["--chdir", opts["chdir"], "--startas", bin, "--",
            *args, ">>", opts["logfile"], lit("2>&1")]
    log.info("Starting %s", os.path.basename(bin))
    exec_("echo", lit("`date +'%Y-%m-%d %H:%M:%S'`"),
          f"Jepsen starting {escape(e)} {bin} "
          f"{escape(list(args))}", ">>", opts["logfile"])
    try:
        exec_(*( [e] if e else [] ), "start-stop-daemon", *ssd)
        return "started"
    except RemoteError as err:
        if err.exit == 1:
            return "already-running"
        raise


def stop_daemon(pidfile: str | None, cmd: str | None = None) -> None:
    """Kill a daemon by pidfile, or by command name
    (`control/util.clj:369-384`)."""
    if cmd is not None:
        log.info("Stopping %s", cmd)
        meh(lambda: exec_("killall", "-9", "-w", cmd))
        if pidfile:
            meh(lambda: exec_("rm", "-rf", pidfile))
        return
    if pidfile and exists(pidfile):
        log.info("Stopping %s", pidfile)
        try:
            pid = int(exec_("cat", pidfile))
        except (ValueError, RemoteError):
            pid = None  # empty/vanished pidfile: best-effort teardown
        if pid is not None:
            meh(lambda: exec_("kill", "-9", pid))
        meh(lambda: exec_("rm", "-rf", pidfile))


def daemon_running(pidfile: str):
    """True if pidfile's process is alive, None if no pidfile, False if
    the process is gone (`control/util.clj:386-397`)."""
    pid = meh(lambda: exec_("cat", pidfile))
    if not pid:
        return None
    try:
        exec_("ps", "-o", "pid=", "-p", pid)
        return True
    except RemoteError:
        return False


def signal(process_name: str, sig) -> str:
    """Send a signal to a named process (`control/util.clj:399-403`)."""
    meh(lambda: exec_("pkill", "--signal", str(sig), process_name))
    return "signaled"
