"""Control DSL: scoped remote shell over polymorphic Remotes.

Reference: `jepsen/src/jepsen/control.clj` — dynamic-var-scoped remote
shell (`*host* *session* *sudo* *dir*`…, `:40-53`), `exec`/`exec*`
escape+sudo+cd pipeline (`:138-157`), `upload`/`download` (`:167-189`),
parallel fan-out `on`/`on-many`/`on-nodes` (`:272-311`), and scoping
macros `cd`/`sudo`/`su`/`with-ssh`/`with-remote` (`:203-262`).

Python rendering: the dynamic vars become a thread-local ``Env`` (worker
threads inherit nothing — each `on_nodes` branch binds its own session),
and the Clojure macros become context managers::

    with with_ssh({"username": "root"}):
        with on("n1"):
            with su(), cd("/opt/db"):
                exec_("bin/db", "start", ">", "db.log")
"""

from __future__ import annotations

import contextlib
import logging
import threading
from typing import Any, Callable, Iterable

from ..util import real_pmap
from . import dummy as dummy_mod
from . import ssh as ssh_mod
from .core import (Literal, Remote, RemoteError, escape, lit,
                   throw_on_nonzero_exit)

log = logging.getLogger(__name__)

PIPE = lit("|")
AND = lit("&&")

_DEFAULTS = {
    "dummy": False,
    "host": None,
    "session": None,
    "trace": False,
    "dir": "/",
    "sudo": None,
    "sudo-password": None,
    "username": "root",
    "password": "root",
    "port": 22,
    "private-key-path": None,
    "strict-host-key-checking": True,
    "remote": None,
    "retries": 5,
}


class _Env(threading.local):
    def __init__(self):
        self.vars = dict(_DEFAULTS)


_env = _Env()


def var(name: str) -> Any:
    return _env.vars[name]


@contextlib.contextmanager
def binding(**kw):
    """Scoped rebinding of control vars (underscores → dashes)."""
    kw = {k.replace("_", "-"): v for k, v in kw.items()}
    old = {k: _env.vars[k] for k in kw}
    _env.vars.update(kw)
    try:
        yield
    finally:
        _env.vars.update(old)


def bound_fn(f: Callable) -> Callable:
    """Capture the current control bindings and re-establish them in
    whatever thread later calls f — the reference's `bound-fn*`, needed
    because worker threads see only default bindings."""
    saved = dict(_env.vars)

    def wrapper(*args, **kwargs):
        old = _env.vars
        _env.vars = dict(saved)
        try:
            return f(*args, **kwargs)
        finally:
            _env.vars = old

    return wrapper


def default_remote() -> Remote:
    """The bound remote, or the default: dummy when `dummy` is set,
    otherwise retry-wrapped OpenSSH (`control.clj:35-37` + the sshj/scp/
    retry wrapper stack)."""
    r = var("remote")
    if r is not None:
        return r
    if var("dummy"):
        return dummy_mod.remote()
    from . import retry as retry_mod
    return retry_mod.remote(ssh_mod.remote())


def conn_spec() -> dict:
    """Conn spec from current bindings (`control.clj:55-70`)."""
    return {k: var(k) for k in
            ("dummy", "host", "port", "username", "password",
             "private-key-path", "strict-host-key-checking")}


def cmd_context() -> dict:
    """Command context from current bindings (`control.clj:72-78`)."""
    return {"dir": var("dir"), "sudo": var("sudo"),
            "sudo-password": var("sudo-password")}


def session(host: str) -> Remote:
    """Connect the bound remote to host (`control.clj:226-229`)."""
    return default_remote().connect({**conn_spec(), "host": host})


def disconnect(remote: Remote) -> None:
    remote.disconnect()


# -- command execution ------------------------------------------------------

def ssh_star(action: dict) -> dict:
    """Wrap an action in cd+sudo and evaluate it against the current
    session (`control.clj:103-136` — wrapping happens here at the DSL
    layer, exactly once, so every Remote backend sees a fully-formed
    command)."""
    from .core import wrap_cd, wrap_sudo

    sess = var("session")
    if sess is None:
        raise RemoteError("no session bound for this host; use on()/"
                          "on_nodes()/with_session()")
    ctx = cmd_context()
    wrapped = wrap_sudo(ctx, wrap_cd(ctx, action))
    res = sess.execute(ctx, wrapped)
    return {**res, "host": var("host"), "action": action}


def exec_raw(*commands) -> str:
    """Join commands unescaped, run, throw on nonzero exit, return
    trimmed stdout (`control.clj:138-149` exec*)."""
    cmd = " ".join(str(c.string if isinstance(c, Literal) else c)
                   for c in commands)
    if var("trace"):
        log.info("Host: %s cmd: %s", var("host"), cmd)
    res = ssh_star({"cmd": cmd})
    throw_on_nonzero_exit(res)
    return res.get("out", "").rstrip("\r\n")


def exec_(*commands) -> str:
    """Escape each argument, run, return stdout (`control.clj:151-157`)."""
    return exec_raw(*[escape(c) for c in commands])


def upload(local_paths, remote_path: str) -> str:
    """Copy local path(s) to the remote node (`control.clj:167-173`)."""
    var("session").upload(cmd_context(), local_paths, remote_path, {})
    return remote_path


def upload_str(content: str | bytes, remote_path: str) -> str:
    """Upload literal content (the reference's `upload-resource!`,
    `control.clj:175-184`, generalized to any string)."""
    import tempfile

    mode = "wb" if isinstance(content, bytes) else "w"
    with tempfile.NamedTemporaryFile(mode, suffix=".upload",
                                     delete=False) as f:
        f.write(content)
        tmp = f.name
    try:
        return upload(tmp, remote_path)
    finally:
        import os
        os.unlink(tmp)


def download(remote_paths, local_path: str) -> None:
    """Copy remote path(s) to the control node (`control.clj:186-189`)."""
    var("session").download(cmd_context(), remote_paths, local_path, {})


def expand_path(path: str) -> str:
    """Resolve path against the bound dir (`control.clj:191-201`)."""
    if path.startswith("/"):
        return path
    d = var("dir")
    return d + ("" if d.endswith("/") else "/") + path


# -- scoping ----------------------------------------------------------------

def cd(dir: str):
    """Evaluate body in dir (`control.clj:203-207`)."""
    return binding(dir=expand_path(dir))


def sudo(user: str):
    """Evaluate body as user (`control.clj:209-213`)."""
    return binding(sudo=str(user))


def su():
    """sudo root (`control.clj:215-218`)."""
    return sudo("root")


def trace():
    """Evaluate body with command tracing (`control.clj:220-224`)."""
    return binding(trace=True)


def with_remote(remote: Remote):
    return binding(remote=remote)


def with_ssh(ssh: dict):
    """Scope SSH config from a test's :ssh map (`control.clj:241-262`)."""
    keys = ("dummy", "username", "password", "sudo-password", "port",
            "private-key-path", "strict-host-key-checking", "remote")
    return binding(**{k.replace("-", "_"): ssh[k]
                      for k in keys if k in ssh})


def with_session(host: str, sess: Remote):
    """Bind host+session without opening/closing (`control.clj:264-270`)."""
    return binding(host=host, session=sess)


@contextlib.contextmanager
def on(host: str):
    """Open a session to host, evaluate body, close
    (`control.clj:272-281`)."""
    sess = session(host)
    try:
        with with_session(host, sess):
            yield sess
    finally:
        sess.disconnect()


def on_many(hosts: Iterable[str], f: Callable[[], Any]) -> dict:
    """Run f() on each host in parallel with that host's session bound;
    returns {host: value} (`control.clj:283-293`)."""
    hosts = list(hosts)
    saved = dict(_env.vars)

    def run1(host):
        _env.vars = dict(saved)
        with on(host):
            return host, f()

    return dict(real_pmap(run1, hosts))


def on_nodes(test: dict, f: Callable[[dict, str], Any],
             nodes: Iterable[str] | None = None) -> dict:
    """Evaluate f(test, node) in parallel on each node with that node's
    *already-open* session (from test["sessions"]) bound; returns
    {node: value} (`control.clj:295-311`)."""
    nodes = list(test["nodes"] if nodes is None else nodes)
    sessions = test.get("sessions") or {}
    saved = dict(_env.vars)

    def run1(node):
        sess = sessions.get(node)
        assert sess is not None, f"No session for node {node!r}"
        _env.vars = dict(saved)
        with with_session(node, sess):
            return node, f(test, node)

    return dict(real_pmap(run1, nodes))
