"""Remote-execution protocol and shell-command construction.

Reference: `jepsen/src/jepsen/control/core.clj` — the `Remote` protocol
(connect/disconnect/execute/upload/download, `:7-58`), POSIX shell escaping
with `lit` literals (`:60-110`), env-var construction (`:112-140`), sudo
wrapping with password on stdin (`:142-153`), and nonzero-exit → throw
(`:155-171`).

A *conn spec* describes how to reach a node::

    {"host": ..., "port": 22, "username": ..., "password": ...,
     "private-key-path": ..., "strict-host-key-checking": True,
     "dummy": False}

A *context map* describes how to run a command::

    {"dir": ..., "sudo": ..., "sudo-password": ...}

An *action* is ``{"cmd": str, "in": optional stdin str}``; executing it
returns the action plus ``{"exit": int, "out": str, "err": str}``.
"""

from __future__ import annotations

import re
from typing import Any, Mapping


class Remote:
    """Polymorphic remote-execution backend (SSH, docker, k8s, dummy)."""

    def connect(self, conn_spec: dict) -> "Remote":
        """Returns a Remote bound to the node described by conn_spec,
        ready for execute/upload/download."""
        raise NotImplementedError

    def disconnect(self) -> None:
        pass

    def execute(self, context: dict, action: dict) -> dict:
        """Run action's cmd (with optional stdin action["in"]) under
        context; returns action + {"exit", "out", "err"}."""
        raise NotImplementedError

    def upload(self, context: dict, local_paths, remote_path: str,
               opts: dict | None = None) -> None:
        raise NotImplementedError

    def download(self, context: dict, remote_paths, local_path: str,
                 opts: dict | None = None) -> None:
        raise NotImplementedError


class Literal:
    """A string passed to the shell unescaped (`control/core.clj:60-65`)."""

    __slots__ = ("string",)

    def __init__(self, string: str):
        self.string = string

    def __repr__(self):
        return f"lit({self.string!r})"

    def __eq__(self, other):
        return isinstance(other, Literal) and other.string == self.string

    def __hash__(self):
        return hash(("lit", self.string))


def lit(s: str) -> Literal:
    return Literal(s)


# Shell I/O redirection tokens pass through bare, like the reference's
# :> :>> :< keywords (`control/core.clj:90-91`).
_REDIRECTS = {">", ">>", "<"}

_NEEDS_QUOTING = re.compile(r'[\\$`"\s(){}\[\]*?<>&;|!#~\']')
_QUOTE_THESE = re.compile(r'([\\$`"])')


def escape(s: Any) -> str:
    """Escape one argument (or sequence of arguments) for a POSIX shell.

    None → empty string; Literal → verbatim; ">", ">>", "<" → bare
    redirection operators; lists/tuples/sets → each element escaped,
    space-joined; everything else is str()'d and double-quoted when it
    contains shell-special characters (`control/core.clj:67-110`).
    """
    if s is None:
        return ""
    if isinstance(s, Literal):
        return s.string
    if isinstance(s, (list, tuple, set, frozenset)):
        return " ".join(escape(x) for x in s)
    s = str(s)
    if s in _REDIRECTS:
        return s
    if s == "":
        return '""'
    if _NEEDS_QUOTING.search(s):
        return '"' + _QUOTE_THESE.sub(r"\\\1", s) + '"'
    return s


def env(e: Any) -> Literal | None:
    """Build an env-var binding prefix for a command: a mapping of names to
    values becomes the Literal ``K1=v1 K2=v2``; strings/Literals pass
    through as Literals; None → None (`control/core.clj:112-140`)."""
    if e is None:
        return None
    if isinstance(e, Literal):
        return e
    if isinstance(e, str):
        return lit(e)
    if isinstance(e, Mapping):
        return lit(" ".join(f"{k}={escape(v)}" for k, v in e.items()))
    raise TypeError(f"can't build an env mapping from {e!r}")


def wrap_sudo(context: dict, action: dict) -> dict:
    """If the context asks for sudo, wrap the action's cmd in
    ``sudo -k -S -u <user> bash -c <escaped cmd>``, prepending the sudo
    password to stdin when present (`control/core.clj:142-153`)."""
    user = context.get("sudo")
    if not user:
        return action
    out = dict(action)
    out["cmd"] = f"sudo -k -S -u {user} bash -c {escape(action['cmd'])}"
    pw = context.get("sudo-password")
    if pw:
        out["in"] = f"{pw}\n{action.get('in', '')}"
        out["secret-in"] = True  # so error reporting redacts stdin
    return out


def wrap_cd(context: dict, action: dict) -> dict:
    """Prefix the command with a cd to the context's dir."""
    d = context.get("dir")
    if not d:
        return action
    out = dict(action)
    out["cmd"] = f"cd {escape(d)}; {action['cmd']}"
    return out


class RemoteError(Exception):
    """A remote command failed (nonzero exit, or transport trouble)."""

    def __init__(self, message: str, result: dict | None = None):
        super().__init__(message)
        self.result = result or {}

    @property
    def exit(self):
        return self.result.get("exit")

    @property
    def out(self):
        return self.result.get("out")

    @property
    def err(self):
        return self.result.get("err")


def throw_on_nonzero_exit(result: dict) -> dict:
    """Raise RemoteError unless the result's exit status is 0
    (`control/core.clj:155-171`)."""
    if result.get("exit") == 0:
        return result
    stdin = "[redacted]" if result.get("secret-in") \
        else result.get("in", "")
    raise RemoteError(
        "Command exited with non-zero status {} on node {}:\n{}\n\n"
        "STDIN:\n{}\n\nSTDOUT:\n{}\n\nSTDERR:\n{}".format(
            result.get("exit"), result.get("host"),
            (result.get("action") or {}).get("cmd"),
            stdin, result.get("out", ""),
            result.get("err", "")),
        result)


def cli_run(argv, stdin: str | None = None,
            timeout: float | None = None) -> dict:
    """Run a local CLI transport command (ssh/scp/docker/kubectl) and
    return {"exit", "out", "err"} — shared by all subprocess-backed
    Remotes."""
    import subprocess

    try:
        p = subprocess.run(argv, input=stdin, capture_output=True,
                           text=True, timeout=timeout)
        return {"exit": p.returncode, "out": p.stdout, "err": p.stderr}
    except subprocess.TimeoutExpired as e:
        out = e.stdout
        if isinstance(out, bytes):
            out = out.decode(errors="replace")
        return {"exit": -1, "out": out or "",
                "err": f"timeout after {timeout}s"}
    except FileNotFoundError as e:
        return {"exit": -1, "out": "", "err": str(e)}
