"""Transaction micro-op utilities.

Transactions are sequences of micro-ops ("mops"), each a 3-element list/tuple
``[f, k, v]`` where f is 'r' (read), 'w' (write), or 'append'. Behavioral
parity with the reference's vendored txn library
(`txn/src/jepsen/txn.clj:5-73`, `txn/src/jepsen/txn/micro_op.clj:6-35`);
these semantics feed the Elle-class cycle checkers.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator


# -- micro-op accessors (reference: txn/micro_op.clj) -----------------------

def f(mop) -> Any:
    return mop[0]


def key(mop) -> Any:
    return mop[1]


def value(mop) -> Any:
    return mop[2]


def is_read(mop) -> bool:
    return mop[0] == "r"


def is_write(mop) -> bool:
    return mop[0] == "w"


def is_mop(mop) -> bool:
    return len(mop) == 3 and mop[0] in ("r", "w", "append")


# -- transaction reductions (reference: txn.clj) ----------------------------

def reduce_mops(fn: Callable, init: Any, history: Iterable[dict]) -> Any:
    """Reduce ``fn(state, op, mop)`` over every micro-op of every op's
    :value transaction in the history."""
    state = init
    for op in history:
        for mop in op["value"]:
            state = fn(state, op, mop)
    return state


def op_mops(history: Iterable[dict]) -> Iterator[tuple[dict, Any]]:
    """All (op, mop) pairs in the history, in order."""
    for op in history:
        for mop in op["value"]:
            yield op, mop


def ext_reads(txn: Iterable) -> dict:
    """Keys -> values for a transaction's *external reads*: values observed
    that the transaction did not itself write first. A read of a key after
    any prior mop on that key (read or write) is internal."""
    ext: dict = {}
    seen: set = set()
    for mop in txn:
        mf, mk, mv = mop[0], mop[1], mop[2]
        if mf == "r" and mk not in seen:
            ext[mk] = mv
        seen.add(mk)
    return ext


def ext_writes(txn: Iterable) -> dict:
    """Keys -> values for a transaction's *external writes*: the final value
    written to each key (intermediate writes are internal)."""
    ext: dict = {}
    for mop in txn:
        if mop[0] != "r":
            ext[mop[1]] = mop[2]
    return ext


def int_write_mops(txn: Iterable) -> dict:
    """Keys -> list of all non-final write mops to that key (only keys with
    more than one write appear)."""
    writes: dict = {}
    for mop in txn:
        if mop[0] != "r":
            writes.setdefault(mop[1], []).append(list(mop))
    return {k: vs[:-1] for k, vs in writes.items() if len(vs) > 1}
