"""Platform plumbing: backend env, fault classification, fault injection.

Some interpreters pre-import jax via sitecustomize and bake a real-TPU
platform into the live config, overriding any JAX_PLATFORMS set by the
caller (config beats env once the plugin has registered);
`honor_platform_env()` re-asserts the caller's choice so CPU dry-runs
stay hermetic and a deliberately-invalid platform (how the bench tests
simulate a dead backend) genuinely fails init instead of silently
reaching the chip. (The test conftest goes further and forces CPU
unconditionally.)

This module is also the one place the checkers learn what a backend
failure *means*. jax surfaces every device-path failure as a
RuntimeError (usually an XlaRuntimeError), which tells a recovery
ladder nothing about what to do next; `classify_backend_error` buckets
them into the four faults a production checking service on preemptible
TPUs actually sees — OOM, device loss/preemption, compile failure, and
a wedged backend — and returns None for ordinary RuntimeErrors, which
are checker bugs, not device faults, and must never trigger recovery
(or masquerade as degradation in `check_safe`).

Because real faults are hard to produce on demand, the same module
carries the test-only injection shim: `maybe_inject_fault(site)` is
called immediately before every recovery-aware device dispatch, and
either the `JEPSEN_TPU_FAULT_INJECT` env knob (``kind@site:n`` — raise
an InjectedFault of `kind` at the n-th dispatch on `site`), an
installed :class:`FaultSchedule` (an ORDERED multi-event schedule:
each event arms only after the previous one fired, so `oom` at chunk
3 *then* `bitflip` one staging later lands the second fault inside
the first one's recovery replay), or the monkeypatchable `fault_hook`
makes each bucket deterministically reproducible in tier-1, on CPU,
with no hardware.

The chaos harness (jepsen_tpu/chaos/) additionally listens through
`probe_hook`: the pipeline emits tiny lifecycle/recovery *probe*
events (replay begin/end, fault absorbed, stream state transitions)
through :func:`probe`, which is a no-op unless a harness installed a
hook — production pays one attribute check."""

from __future__ import annotations

import fnmatch
import os
import threading

# Fault buckets (classify_backend_error return values). Anything the
# classifier recognizes as a backend failure but cannot place more
# precisely lands in FAULT_WEDGED — the "wedged-other" rung, handled
# with a plain bounded retry. FAULT_CORRUPT is raised by the checkers
# THEMSELVES (checker/abft.py): an ABFT checksum mismatch means a
# staged buffer or device result was silently corrupted — the rung is
# a re-stage/replay from canonical host data.
FAULT_OOM = "oom"
FAULT_DEVICE_LOST = "device-lost"
FAULT_COMPILE = "compile"
FAULT_WEDGED = "wedged"
FAULT_CORRUPT = "corrupt"
FAULT_KINDS = (FAULT_OOM, FAULT_DEVICE_LOST, FAULT_COMPILE,
               FAULT_WEDGED, FAULT_CORRUPT)

FAULT_INJECT_ENV = "JEPSEN_TPU_FAULT_INJECT"
SYNC_DEADLINE_ENV = "JEPSEN_TPU_SYNC_DEADLINE_S"
ATTEST_ENV = "JEPSEN_TPU_ATTEST"


class InjectedFault(RuntimeError):
    """A deterministic stand-in for a backend fault (test/bench only).

    Subclasses RuntimeError — the same surface jax's real backend
    errors present — so the recovery ladders exercise exactly the
    production catch/classify/retry path."""

    def __init__(self, kind: str, site: str, seq: int):
        super().__init__(
            f"injected {kind} fault at {site} dispatch #{seq}")
        self.kind = kind


class CorruptDeviceResult(RuntimeError):
    """An ABFT attestation checksum disagreed: a staged buffer, a
    device reduction, or a fetched carry was silently corrupted
    (bit-flip in HBM / on the transfer path / in a compute unit).

    Classified FAULT_CORRUPT so the recovery ladders treat silent
    corruption like any other backend fault: re-stage from canonical
    host data (offline/batch/sharded), or restore the last carry
    checkpoint and replay the host-side steps log (stream) — the
    resumed verdict is identical to an uncorrupted run's, instead of
    confidently wrong."""

    kind = FAULT_CORRUPT

    def __init__(self, site: str, detail: str):
        super().__init__(
            f"attestation mismatch at {site}: {detail}")
        self.site = site


class WedgedDeviceSync(RuntimeError):
    """A blocking device sync exceeded its watchdog deadline.

    Raised by guarded_device_get; per util.timeout semantics the
    blocked fetch is *abandoned*, not killed — it may still complete in
    the background, and its late result is discarded. Classified as
    FAULT_WEDGED so the recovery ladders treat a hung TPU call as a
    recoverable fault instead of hanging analyze forever."""

    kind = FAULT_WEDGED


def _xla_error_types() -> tuple:
    """jax's backend-error classes, lazily (jax may not be imported —
    or even importable — when the host-only paths run)."""
    types: tuple = ()
    try:
        from jaxlib.xla_extension import XlaRuntimeError
        types += (XlaRuntimeError,)
    except ImportError:
        pass
    try:
        from jax.errors import JaxRuntimeError
        if JaxRuntimeError not in types:
            types += (JaxRuntimeError,)
    except ImportError:
        pass
    return types


# message fragments → bucket, checked in order (an OOM message may also
# contain "allocator", a preemption may mention the device — first
# match wins, and the more specific buckets come first)
_FAULT_PATTERNS = (
    (FAULT_OOM, ("resource_exhausted", "out of memory", "oom",
                 "allocation failure", "failed to allocate")),
    (FAULT_DEVICE_LOST, ("device_lost", "device lost", "unavailable",
                         "preempt", "halted", "device or chip",
                         "data_loss", "connection reset")),
    (FAULT_COMPILE, ("mosaic", "compilation", "compile",
                     "unimplemented", "lowering")),
    (FAULT_WEDGED, ("deadline_exceeded", "timed out", "timeout")),
)


# jax's backend-*initialization* failures are plain RuntimeErrors
# (xla_bridge.py raises RuntimeError(f"Unable to initialize backend
# '{platform}': ...")); libtpu init failures surface similarly. These
# exact signatures classify as device-lost even without the
# XlaRuntimeError type.
_PLAIN_INIT_FRAGS = ("unable to initialize backend",
                     "failed to initialize tpu")


def classify_backend_error(exc: BaseException) -> str | None:
    """Bucket a backend failure into one of FAULT_KINDS, or None when
    the exception is an ordinary bug rather than the device path
    falling over.

    Only jax's XlaRuntimeError family (plus this module's own fault
    types, which carry an explicit ``kind``) classify: a plain
    RuntimeError raised by checker logic returns None, so recovery
    ladders re-raise it and `check_safe` reports it as a checker error
    instead of device degradation. An XlaRuntimeError whose message
    matches no pattern still classifies — as FAULT_WEDGED, the
    retry-and-see bucket. The one plain-RuntimeError carve-out is
    backend *initialization* failure (_PLAIN_INIT_FRAGS): xla_bridge
    raises those untyped, and they are unambiguously the device path
    falling over. Those fragments are matched as substrings — jax
    prepends status prefixes like 'INTERNAL:' so anchoring to the
    message start would miss them — but each is a full distinctive
    phrase, not a keyword, so a checker bug only matches by quoting
    the backend's own failure text (in which case device-lost is the
    right call anyway)."""
    kind = getattr(exc, "kind", None)
    if kind in FAULT_KINDS:
        return kind
    if not isinstance(exc, _xla_error_types()):
        # one narrow exception to the XlaRuntimeError-only rule: jax's
        # xla_bridge raises a PLAIN RuntimeError when a backend fails
        # to initialize (a dead/unreachable device at first touch) —
        # that is the device path falling over, not a checker bug, so
        # it must reach the device-lost rung. The allowlist holds full
        # distinctive phrases (matched as substrings — jax prepends
        # status prefixes like 'INTERNAL:'), not keywords, so checker
        # bugs don't match unless they quote the backend's own text.
        if type(exc) is RuntimeError:
            msg = str(exc).lower()
            if any(f in msg for f in _PLAIN_INIT_FRAGS):
                return FAULT_DEVICE_LOST
        return None
    msg = str(exc).lower()
    for bucket, frags in _FAULT_PATTERNS:
        if any(f in msg for f in frags):
            return bucket
    return FAULT_WEDGED


def backend_reinit() -> None:
    """Best-effort in-process backend re-initialization after a
    device-lost fault: drop jax's live compiled-executable caches so
    the next dispatch rebuilds device state instead of re-poking dead
    buffers. The kernel-level LRU caches (wgl._kernel and friends) are
    cleared by the callers that own them."""
    try:
        import jax
        jax.clear_caches()
    except Exception:  # noqa: BLE001 — reinit is best-effort by design
        pass


# ---------------------------------------------------------------------------
# Fault injection (tests / bench only)
# ---------------------------------------------------------------------------

# Monkeypatchable hook around dispatch: fn(site) -> None, may raise.
# Checked on every maybe_inject_fault call, before the env knob.
fault_hook = None

# Monkeypatchable hook around staging: fn(site, arr) -> ndarray | None
# (None = leave the buffer alone). Checked on every maybe_corrupt
# call, before the env knob — the bitflip analog of fault_hook, for
# corruption schedules the env spec can't express.
corrupt_hook = None

# the deterministic bit a bitflip clause flips (bit 12 of the middle
# element): any single flipped bit is detected by the attestation
# digests, and a fixed site keeps the injected corruption reproducible
BITFLIP_KIND = "bitflip"
_BITFLIP_BIT = 12

_fault_seq: dict[str, int] = {}
_corrupt_seq: dict[str, int] = {}


class FaultEvent:
    """One scheduled fault: raise/flip `kind` at the `after`-th hit on
    a site matching `site` (fnmatch pattern — ``stream-chunk/*``
    matches every stream), counted from the moment the event ARMS.
    The first event arms at install; each later event arms when its
    predecessor fires — triggers are relative, which is what lets a
    schedule express "one staging into the recovery replay"."""

    __slots__ = ("kind", "site", "after")

    def __init__(self, kind: str, site: str, after: int = 1):
        if after < 1:
            raise ValueError(f"after must be >= 1, got {after}")
        self.kind = kind
        self.site = site
        self.after = int(after)

    def __repr__(self) -> str:
        return f"{self.kind}@{self.site}:{self.after}"

    def to_dict(self) -> dict:
        return {"kind": self.kind, "site": self.site,
                "after": self.after}

    @classmethod
    def from_dict(cls, d: dict) -> "FaultEvent":
        return cls(d["kind"], d["site"], int(d.get("after", 1)))


class FaultSchedule:
    """An ordered list of FaultEvents, advanced by the injection shim.

    Unlike the env knob's clauses — which all count the SAME absolute
    per-site counters and therefore cannot say "after the first fault
    fired" — schedule events arm strictly in order: event i+1 starts
    counting hits only once event i fired. ``bitflip`` events consume
    staging hits (maybe_corrupt); every other kind consumes dispatch
    hits (maybe_inject_fault). Thread-safe: the service pumps streams
    from many worker threads. `fired` records (kind, site, hit) per
    fired event for the chaos stamp-consistency oracle."""

    def __init__(self, events):
        self.events = [e if isinstance(e, FaultEvent)
                       else FaultEvent.from_dict(e) for e in events]
        self._lock = threading.Lock()
        self._i = 0             # guarded-by: _lock
        self._hits = 0          # hits on the armed event's site
        self.fired: list = []   # guarded-by: _lock

    @classmethod
    def from_clauses(cls, clauses) -> "FaultSchedule":
        """Build from ``kind@site:n`` strings (the env-knob grammar,
        but ordered: n counts hits after the previous clause fired)."""
        events = []
        for clause in clauses:
            clause = clause.strip()
            if not clause:
                continue
            kind, _, rest = clause.partition("@")
            site, _, after = rest.partition(":")
            events.append(FaultEvent(kind, site, int(after or 1)))
        return cls(events)

    def done(self) -> bool:
        with self._lock:
            return self._i >= len(self.events)

    def remaining(self) -> int:
        with self._lock:
            return len(self.events) - self._i

    def _advance(self, site: str, staging: bool):
        """One hit on `site`. Returns the armed event when it fires
        (caller raises/flips outside the lock), else None."""
        with self._lock:
            if self._i >= len(self.events):
                return None
            evt = self.events[self._i]
            if (evt.kind == BITFLIP_KIND) != staging:
                return None
            if not fnmatch.fnmatch(site, evt.site):
                return None
            self._hits += 1
            if self._hits < evt.after:
                return None
            self._i += 1
            self._hits = 0
            self.fired.append((evt.kind, site, evt.after))
            return evt

    def on_dispatch(self, site: str) -> None:
        evt = self._advance(site, staging=False)
        if evt is not None:
            probe("inject", kind=evt.kind, site=site,
                  source="schedule")
            raise InjectedFault(evt.kind, site, evt.after)

    def on_staging(self, site: str, arr):
        evt = self._advance(site, staging=True)
        if evt is None:
            return arr
        probe("corrupt", kind=evt.kind, site=site, source="schedule")
        return flip_bit(arr)


# the installed schedule, if any (chaos harness / tests only)
_schedule: FaultSchedule | None = None


def install_fault_schedule(
        schedule: "FaultSchedule | None") -> "FaultSchedule | None":
    """Install (or clear, with None) the process-wide fault schedule.
    Returns the previous one. reset_fault_injection() also clears it."""
    global _schedule
    prev, _schedule = _schedule, schedule
    return prev


def current_fault_schedule() -> "FaultSchedule | None":
    return _schedule


# -- chaos probes (jepsen_tpu/chaos/ and tests only) ------------------------

# fn(event: dict) -> None; None = probes are free (one attr check)
probe_hook = None


def probe(event: str, **info) -> None:
    """Emit one chaos probe event ({"event": ..., **info}) to the
    installed hook. Never raises — a broken harness must not take the
    pipeline down with it."""
    hook = probe_hook
    if hook is None:
        return
    d = {"event": event}
    d.update(info)
    try:
        hook(d)
    except Exception:  # noqa: BLE001 — observability must not break us
        pass


def reset_fault_injection() -> None:
    """Zero the per-site dispatch/staging counters and drop any
    installed schedule (each test starts its own deterministic
    injection schedule)."""
    global _schedule
    _fault_seq.clear()
    _corrupt_seq.clear()
    _schedule = None


def maybe_inject_fault(site: str) -> None:
    """Called immediately before each recovery-aware device dispatch.

    Sites in use: 'offline' (wgl.analysis_tpu), 'batch'
    (wgl.analysis_tpu_batch), 'sharded' (wgl.check_batch_sharded),
    'stream-chunk' (streaming.WglStream), 'elle'
    (elle.kernels._classify_batches). The env spec is a
    comma-separated list of ``kind@site:n`` clauses; the n-th dispatch
    on a matching site raises InjectedFault(kind) (n is 1-based and
    counts every dispatch since reset_fault_injection(), so a
    recovery retry advances the counter past the clause — the fault
    fires once, like a real transient). ``bitflip`` clauses never
    raise here — they corrupt staged buffers via maybe_corrupt, on a
    separate per-site staging counter."""
    n = _fault_seq.get(site, 0) + 1
    _fault_seq[site] = n
    hook = fault_hook
    if hook is not None:
        hook(site)
    sched = _schedule
    if sched is not None:
        sched.on_dispatch(site)
    spec = os.environ.get(FAULT_INJECT_ENV)
    if not spec:
        return
    for clause in spec.split(","):
        clause = clause.strip()
        if not clause:
            continue
        kind, _, rest = clause.partition("@")
        if kind == BITFLIP_KIND:
            continue   # silent-corruption clauses act at staging time
        tsite, _, seq = rest.partition(":")
        if tsite == site and n == int(seq or 1):
            probe("inject", kind=kind, site=site, source="env")
            raise InjectedFault(kind, site, n)


def maybe_corrupt(site: str, arr):
    """Called on each host-staged device buffer right before it ships.

    A ``bitflip@site:n`` clause in JEPSEN_TPU_FAULT_INJECT flips one
    bit (_BITFLIP_BIT of the middle element) in a COPY of the n-th
    staged buffer on that site — the caller ships the returned array
    while its canonical host copy (and therefore the attestation
    digest it computes from it) stays intact, exactly the shape of a
    silent DMA/HBM bit-flip. n counts stagings since
    reset_fault_injection(), so a recovery retry's re-stage advances
    past the clause and ships clean data, like a real transient.
    corrupt_hook(site, arr) -> ndarray|None is checked first, for
    schedules the env spec can't express. Returns the array to ship
    (the original object when nothing matched: zero-copy)."""
    n = _corrupt_seq.get(site, 0) + 1
    _corrupt_seq[site] = n
    hook = corrupt_hook
    if hook is not None:
        out = hook(site, arr)
        if out is not None:
            return out
    sched = _schedule
    if sched is not None:
        out = sched.on_staging(site, arr)
        if out is not arr:
            return out
    spec = os.environ.get(FAULT_INJECT_ENV)
    if not spec:
        return arr
    for clause in spec.split(","):
        clause = clause.strip()
        if not clause:
            continue
        kind, _, rest = clause.partition("@")
        if kind != BITFLIP_KIND:
            continue
        tsite, _, seq = rest.partition(":")
        if tsite == site and n == int(seq or 1):
            probe("corrupt", kind=kind, site=site, source="env")
            return flip_bit(arr)
    return arr


def flip_bit(arr, bit: int = _BITFLIP_BIT):
    """A copy of arr with one bit flipped in its middle element (the
    deterministic corruption bitflip clauses inject)."""
    import numpy as np

    out = np.array(arr, copy=True)
    flat = out.reshape(-1).view(np.uint32 if out.dtype.itemsize == 4
                                else np.uint8)
    flat[len(flat) // 2] ^= np.uint32(1 << bit) if flat.dtype.itemsize \
        == 4 else np.uint8(1 << (bit % 8))
    return out


def attest_enabled(override=None) -> bool:
    """Is ABFT attestation on? An explicit checker option beats the
    JEPSEN_TPU_ATTEST env gate (default ON — always-on verification is
    the point; =0 opts out, e.g. to measure the unguarded baseline).
    Resolved outside the kernel caches so flipping it mid-process
    takes effect on the next call."""
    if override is not None:
        return bool(override)
    return os.environ.get(ATTEST_ENV, "1") != "0"


# ---------------------------------------------------------------------------
# Watchdog: bounded device syncs
# ---------------------------------------------------------------------------

def sync_deadline_s() -> float | None:
    """The watchdog deadline for blocking device syncs, from
    JEPSEN_TPU_SYNC_DEADLINE_S (seconds; unset/0 = unbounded, the
    pre-watchdog behavior — the knob exists because a deadline costs
    one daemon thread per guarded sync)."""
    raw = os.environ.get(SYNC_DEADLINE_ENV)
    if not raw:
        return None
    try:
        v = float(raw)
    except ValueError:
        return None
    return v if v > 0 else None


def guarded_device_get(x, deadline_s: float | None = None,
                       site: str = "device-sync"):
    """jax.device_get under a watchdog deadline: a wedged TPU call
    becomes a WedgedDeviceSync (a classified, recoverable fault)
    instead of blocking its caller forever. deadline_s=None defers to
    the env knob; with neither set this is a plain device_get with no
    thread spawned."""
    import jax

    if deadline_s is None:
        deadline_s = sync_deadline_s()
    if not deadline_s:
        return jax.device_get(x)
    from .util import TIMED_OUT, timeout
    r = timeout(deadline_s, lambda: jax.device_get(x),
                default=TIMED_OUT, name=f"jepsen-watchdog {site}")
    if r is TIMED_OUT:
        raise WedgedDeviceSync(
            f"device sync at {site} still blocked after {deadline_s}s "
            f"(watchdog); treating the backend as wedged")
    return r


def honor_platform_env() -> None:
    env = os.environ.get("JAX_PLATFORMS")
    if env:
        import jax

        jax.config.update("jax_platforms", env)


# historical name, used by earlier entry scripts
honor_cpu_env = honor_platform_env


def enable_compilation_cache(cache_dir: str | None = None) -> str | None:
    """Point JAX's persistent compilation cache at a stable directory
    (bench.py has always done this for its per-section subprocesses;
    this is the same lever for the CLI runner, so repeat `test` /
    `analyze` invocations skip recompiling the checker kernels).

    Env-gated: JEPSEN_TPU_COMPILE_CACHE=0 disables entirely; an
    existing JAX_COMPILATION_CACHE_DIR always wins (we only ever
    setdefault). Returns the directory in effect, or None when
    disabled. Safe to call before or after jax import — JAX reads the
    env var lazily at first compile."""
    if os.environ.get("JEPSEN_TPU_COMPILE_CACHE") == "0":
        return None
    d = cache_dir or os.path.join(
        os.path.expanduser("~"), ".cache", "jepsen-tpu", "jax")
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", d)
    return os.environ["JAX_COMPILATION_CACHE_DIR"]
