"""Platform plumbing for driver entry scripts.

Some interpreters pre-import jax via sitecustomize and bake a real-TPU
platform into the live config, overriding any JAX_PLATFORMS set by the
caller (config beats env once the plugin has registered);
`honor_platform_env()` re-asserts the caller's choice so CPU dry-runs
stay hermetic and a deliberately-invalid platform (how the bench tests
simulate a dead backend) genuinely fails init instead of silently
reaching the chip. (The test conftest goes further and forces CPU
unconditionally.)"""

from __future__ import annotations

import os


def honor_platform_env() -> None:
    env = os.environ.get("JAX_PLATFORMS")
    if env:
        import jax

        jax.config.update("jax_platforms", env)


# historical name, used by earlier entry scripts
honor_cpu_env = honor_platform_env
