"""Platform plumbing for driver entry scripts.

Some interpreters pre-import jax via sitecustomize and bake a real-TPU
platform into the live config, overriding any JAX_PLATFORMS set by the
caller (config beats env once the plugin has registered);
`honor_platform_env()` re-asserts the caller's choice so CPU dry-runs
stay hermetic and a deliberately-invalid platform (how the bench tests
simulate a dead backend) genuinely fails init instead of silently
reaching the chip. (The test conftest goes further and forces CPU
unconditionally.)"""

from __future__ import annotations

import os


def honor_platform_env() -> None:
    env = os.environ.get("JAX_PLATFORMS")
    if env:
        import jax

        jax.config.update("jax_platforms", env)


# historical name, used by earlier entry scripts
honor_cpu_env = honor_platform_env


def enable_compilation_cache(cache_dir: str | None = None) -> str | None:
    """Point JAX's persistent compilation cache at a stable directory
    (bench.py has always done this for its per-section subprocesses;
    this is the same lever for the CLI runner, so repeat `test` /
    `analyze` invocations skip recompiling the checker kernels).

    Env-gated: JEPSEN_TPU_COMPILE_CACHE=0 disables entirely; an
    existing JAX_COMPILATION_CACHE_DIR always wins (we only ever
    setdefault). Returns the directory in effect, or None when
    disabled. Safe to call before or after jax import — JAX reads the
    env var lazily at first compile."""
    if os.environ.get("JEPSEN_TPU_COMPILE_CACHE") == "0":
        return None
    d = cache_dir or os.path.join(
        os.path.expanduser("~"), ".cache", "jepsen-tpu", "jax")
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", d)
    return os.environ["JAX_COMPILATION_CACHE_DIR"]
