"""Platform plumbing for driver entry scripts.

Some interpreters pre-import jax via sitecustomize and bake a real-TPU
platform into the live config, overriding a JAX_PLATFORMS=cpu set by
the caller; `honor_cpu_env()` re-asserts the caller's choice so CPU
dry-runs and smoke runs stay hermetic. (The test conftest goes further
and forces CPU unconditionally.)"""

from __future__ import annotations

import os


def honor_cpu_env() -> None:
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
