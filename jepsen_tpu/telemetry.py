"""Pipeline telemetry: a process-wide metrics registry + exposition.

The service (PR 8) turned checking into always-on infrastructure, and
the tiered/recovery machinery (PRs 5, 7) makes runtime decisions —
engine selection, escalation, backpressure, recovery rungs — that were
visible only as log lines. This module is the observability substrate:

  * **Registry.** Process-wide, thread-safe counters, gauges, and
    histograms with label sets. The hot path is lock-cheap: one
    uncontended per-child lock around a few arithmetic ops — the
    registry-wide lock is taken only when a new (metric, label-set)
    child materializes. ``JEPSEN_TPU_METRICS=0`` (or
    :func:`set_enabled`) turns every mutation into a single attribute
    check, which is what ``bench.py --section telemetry`` measures
    the instrumented pipeline against.
  * **Exposition.** :func:`snapshot` (JSON-able dict, also the
    service socket's ``metrics`` verb and the per-section meta in
    BENCH artifacts) and :func:`prometheus_text` (the Prometheus
    text format, served by :func:`serve_metrics` at ``/metrics`` and
    by the results web UI). ``/healthz`` serves the JSON the caller
    provides (the service's ``status()`` shape).
  * **Naming convention** (linted by ``tools/staticcheck``'s metrics
    analyzer in ``make check``):
    ``jepsen_tpu_<layer>_<name>_<unit>`` with layer
    in :data:`LAYERS` and unit in :data:`UNITS`; counters end in
    ``_total``.
  * **Profiler hooks.** ``JEPSEN_TPU_PROFILE=<dir>`` makes
    :func:`profile_section` start one ``jax.profiler`` trace into
    that directory (stopped atexit) and wrap each device section in a
    ``TraceAnnotation`` so chunk dispatches are named in the TPU
    profile. Without the env var every call is a no-op (pinned by
    tests/test_telemetry.py).

Instrumentation sites live with the code they observe (wgl dispatch,
streaming chunks/checkpoints, screens, attestation, the service);
this module deliberately imports none of them.
"""

from __future__ import annotations

import contextlib
import json
import math
import os
import threading
import time
from typing import Callable, Iterable

# metric-name vocabulary (tools/staticcheck's metrics analyzer
# enforces this over every registered metric; keep the sets in sync
# with the doc catalog in doc/observability.md)
LAYERS = ("wgl", "streaming", "screen", "abft", "service", "trace",
          "run", "web", "search", "chaos")
UNITS = ("total", "seconds", "rows", "ops", "chunks", "elementops",
         "bytes", "ratio", "streams", "info", "bits", "genomes")

METRICS_ENV = "JEPSEN_TPU_METRICS"
PROFILE_ENV = "JEPSEN_TPU_PROFILE"

# latency buckets (seconds): device chunks span ~100us (warm CPU sort
# chunk) to minutes (a cold compile on a wedged relay)
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                   0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
                   120.0)

_enabled = os.environ.get(METRICS_ENV, "1") != "0"


def enabled() -> bool:
    return _enabled


def set_enabled(on: bool) -> bool:
    """Flip the registry hot path on/off process-wide (the overhead
    bench measures the pipeline in both states). Returns the previous
    state."""
    global _enabled
    prev, _enabled = _enabled, bool(on)
    return prev


def _label_values(labelnames: tuple, kw: dict) -> tuple:
    if set(kw) != set(labelnames):
        raise ValueError(
            f"labels {sorted(kw)} != declared {sorted(labelnames)}")
    return tuple(str(kw[k]) for k in labelnames)


class _Child:
    """One (metric, label-values) series. Mutations take only this
    child's lock — the lock-cheap hot path."""

    __slots__ = ("_lock", "value")

    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0.0        # guarded-by: _lock


class _CounterChild(_Child):
    def inc(self, amount: float = 1.0) -> None:
        if not _enabled:
            return
        if amount < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self.value += amount


class _GaugeChild(_Child):
    def set(self, value: float) -> None:
        if not _enabled:
            return
        with self._lock:
            self.value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        if not _enabled:
            return
        with self._lock:
            self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)


class _HistogramChild:
    __slots__ = ("_lock", "buckets", "counts", "sum", "count")

    def __init__(self, buckets: tuple):
        self._lock = threading.Lock()
        self.buckets = buckets          # upper bounds, ascending
        self.counts = [0] * (len(buckets) + 1)   # guarded-by: _lock
        self.sum = 0.0                  # guarded-by: _lock
        self.count = 0                  # guarded-by: _lock

    def observe(self, value: float) -> None:
        if not _enabled:
            return
        v = float(value)
        i = 0
        for b in self.buckets:
            if v <= b:
                break
            i += 1
        with self._lock:
            self.counts[i] += 1
            self.sum += v
            self.count += 1

    @contextlib.contextmanager
    def time(self):
        """Observe the wall-clock duration of the with-block."""
        t0 = time.monotonic()
        try:
            yield
        finally:
            self.observe(time.monotonic() - t0)


class Metric:
    """A named family of label-keyed children. ``labels(**kw)``
    returns (creating on first use) the child for one label-value
    set; unlabeled metrics expose the child's methods directly."""

    kind = "untyped"

    def __init__(self, name: str, help: str,  # noqa: A002 — prometheus vocabulary
                 labelnames: Iterable[str] = ()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._children: dict[tuple, object] = {}  # guarded-by: _lock
        self._lock = threading.Lock()
        if not self.labelnames:
            self._children[()] = self._make_child()

    def _make_child(self):
        raise NotImplementedError

    def labels(self, **kw):
        key = _label_values(self.labelnames, kw)
        # lock-free fast path by design: _children is insert-only and
        # dict reads are atomic under the GIL — the hot path must not
        # pay the registry lock per increment
        child = self._children.get(key)  # noqa: JTS201
        if child is None:
            with self._lock:
                child = self._children.setdefault(key,
                                                  self._make_child())
        return child

    def children(self) -> list[tuple[tuple, object]]:
        with self._lock:
            return sorted(self._children.items())

    def clear(self) -> None:
        """Drop every child's accumulated value (tests; the metric and
        its declaration survive)."""
        with self._lock:
            self._children = {}
            if not self.labelnames:
                self._children[()] = self._make_child()

    # unlabeled convenience passthroughs
    def _solo(self):
        if self.labelnames:
            raise ValueError(f"{self.name} needs labels(...)")
        # lock-free by design: the () child is created in __init__ and
        # never replaced except by clear() (test-only)
        return self._children[()]  # noqa: JTS201


class Counter(Metric):
    kind = "counter"

    def _make_child(self):
        return _CounterChild()

    def inc(self, amount: float = 1.0) -> None:
        self._solo().inc(amount)


class Gauge(Metric):
    kind = "gauge"

    def _make_child(self):
        return _GaugeChild()

    def set(self, value: float) -> None:
        self._solo().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._solo().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._solo().dec(amount)


class Histogram(Metric):
    kind = "histogram"

    def __init__(self, name, help, labelnames=(),  # noqa: A002
                 buckets: tuple = DEFAULT_BUCKETS):
        self.buckets = tuple(sorted(float(b) for b in buckets))
        super().__init__(name, help, labelnames)

    def _make_child(self):
        return _HistogramChild(self.buckets)

    def observe(self, value: float) -> None:
        self._solo().observe(value)

    def time(self):
        return self._solo().time()


class Registry:
    """Get-or-create metric registration + exposition. One process-
    wide instance (:data:`REGISTRY`) serves the whole pipeline; tests
    may build private ones."""

    def __init__(self):
        self._metrics: dict[str, Metric] = {}   # guarded-by: _lock
        self._lock = threading.Lock()

    def register(self, cls, name: str, help: str,  # noqa: A002
                 labelnames=(), **kw) -> Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if type(m) is not cls:
                    raise ValueError(
                        f"{name} already registered as {m.kind}")
                if m.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"{name} already registered with labels "
                        f"{m.labelnames}")
                want = kw.get("buckets")
                if want is not None and tuple(
                        sorted(float(b) for b in want)) != m.buckets:
                    # a silently-ignored bucket layout would hand the
                    # second caller coarse data with no signal
                    raise ValueError(
                        f"{name} already registered with buckets "
                        f"{m.buckets}")
                return m
            m = cls(name, help, labelnames, **kw)
            self._metrics[name] = m
            return m

    def metrics(self) -> list[Metric]:
        with self._lock:
            return [self._metrics[n] for n in sorted(self._metrics)]

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._metrics)

    def reset(self) -> None:
        """Zero every metric (tests / per-section bench isolation)."""
        for m in self.metrics():
            m.clear()

    # -- exposition ----------------------------------------------------------

    def snapshot(self, prefix: str = "",
                 compact: bool = False) -> dict:
        """A JSON-able {name: {labels-json: value}} dict. Histograms
        report {count, sum, avg} when compact, full bucket maps
        otherwise. Unlabeled series use the empty-string label key."""
        out: dict = {}
        for m in self.metrics():
            if prefix and not m.name.startswith(prefix):
                continue
            series: dict = {}
            for key, child in m.children():
                lk = ",".join(f"{n}={v}"
                              for n, v in zip(m.labelnames, key))
                if m.kind == "histogram":
                    with child._lock:
                        cnt, tot = child.count, child.sum
                        counts = list(child.counts)
                    if compact:
                        series[lk] = {
                            "count": cnt, "sum": round(tot, 6),
                            "avg": round(tot / cnt, 6) if cnt else 0.0}
                    else:
                        series[lk] = {
                            "count": cnt, "sum": tot,
                            "buckets": dict(zip(
                                [str(b) for b in m.buckets] + ["+Inf"],
                                counts))}
                else:
                    series[lk] = child.value
            # skip all-zero counter/histogram series in compact mode:
            # the BENCH meta should carry what a section exercised,
            # not the catalog. Gauges are ALWAYS kept — a gauge at 0
            # (budget drained, no active streams) is meaningful state,
            # and /healthz consumers must see it, not a vanished key.
            if compact and m.kind != "gauge":
                series = {k: v for k, v in series.items()
                          if (v.get("count") if isinstance(v, dict)
                              else v)}
                if not series:
                    continue
            out[m.name] = series
        return out

    def prometheus_text(self) -> str:
        """The Prometheus text exposition format (0.0.4). HELP/TYPE
        lines are emitted for every registered metric — a scraper sees
        the full catalog even before a labeled series materializes."""
        lines: list[str] = []
        for m in self.metrics():
            lines.append(f"# HELP {m.name} {_esc_help(m.help)}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for key, child in m.children():
                labels = _fmt_labels(m.labelnames, key)
                if m.kind == "histogram":
                    with child._lock:
                        counts = list(child.counts)
                        tot, cnt = child.sum, child.count
                    cum = 0
                    for b, c in zip(m.buckets, counts):
                        cum += c
                        lines.append(
                            f"{m.name}_bucket"
                            f"{_fmt_labels(m.labelnames, key, le=_fmt(b))}"
                            f" {cum}")
                    cum += counts[-1]
                    lines.append(
                        f"{m.name}_bucket"
                        f"{_fmt_labels(m.labelnames, key, le='+Inf')}"
                        f" {cum}")
                    lines.append(f"{m.name}_sum{labels} {_fmt(tot)}")
                    lines.append(f"{m.name}_count{labels} {cnt}")
                else:
                    lines.append(f"{m.name}{labels} "
                                 f"{_fmt(child.value)}")
        return "\n".join(lines) + "\n"


def _fmt(v: float) -> str:
    if isinstance(v, float):
        if math.isinf(v):
            return "+Inf" if v > 0 else "-Inf"
        if v == int(v) and abs(v) < 1e15:
            return str(int(v))
    return repr(float(v))


def _esc_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _esc_label(s: str) -> str:
    return s.replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _fmt_labels(names: tuple, values: tuple, **extra) -> str:
    pairs = [f'{n}="{_esc_label(v)}"' for n, v in zip(names, values)]
    pairs += [f'{n}="{_esc_label(v)}"' for n, v in extra.items()]
    return "{" + ",".join(pairs) + "}" if pairs else ""


# -- the process-wide default registry ---------------------------------------

REGISTRY = Registry()


def counter(name: str, help: str, labelnames=()) -> Counter:  # noqa: A002
    return REGISTRY.register(Counter, name, help, labelnames)


def gauge(name: str, help: str, labelnames=()) -> Gauge:  # noqa: A002
    return REGISTRY.register(Gauge, name, help, labelnames)


def histogram(name: str, help: str, labelnames=(),  # noqa: A002
              buckets: tuple = DEFAULT_BUCKETS) -> Histogram:
    return REGISTRY.register(Histogram, name, help, labelnames,
                             buckets=buckets)


def snapshot(prefix: str = "", compact: bool = False) -> dict:
    return REGISTRY.snapshot(prefix=prefix, compact=compact)


def prometheus_text() -> str:
    return REGISTRY.prometheus_text()


def reset() -> None:
    REGISTRY.reset()


# ---------------------------------------------------------------------------
# HTTP exposition: /metrics (Prometheus text) + /healthz (status JSON)
# ---------------------------------------------------------------------------

def serve_metrics(port: int, host: str = "127.0.0.1",
                  registry: Registry | None = None,
                  healthz: Callable[[], dict] | None = None):
    """Start a daemon-thread HTTP listener serving ``/metrics``
    (Prometheus text, content-type text/plain; version=0.0.4) and
    ``/healthz`` (the JSON from ``healthz()`` — the service passes its
    ``status()``; default ``{"ok": true}``). Returns the server; port
    0 picks a free one (``server.server_address[1]``).

    Binds loopback by default, matching the service socket's posture —
    /healthz carries run names, store paths, and quarantine error
    tails, none of which belong on every interface unasked. Pass
    ``host="0.0.0.0"`` (CLI: ``--metrics-host``) to expose to a
    remote Prometheus deliberately."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    reg = registry if registry is not None else REGISTRY

    class _Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            pass   # scrapes must not spam stderr

        def _send(self, code: int, body: bytes, ctype: str) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 — http.server API
            path = self.path.split("?", 1)[0]
            if path == "/metrics":
                return self._send(
                    200, reg.prometheus_text().encode(),
                    "text/plain; version=0.0.4; charset=utf-8")
            if path == "/healthz":
                try:
                    body = healthz() if healthz is not None \
                        else {"ok": True}
                except Exception as e:  # noqa: BLE001 — health must answer
                    return self._send(
                        500, json.dumps({"ok": False,
                                         "error": str(e)}).encode(),
                        "application/json")
                return self._send(200, json.dumps(body).encode(),
                                  "application/json")
            return self._send(404, b"not found", "text/plain")

    server = ThreadingHTTPServer((host, int(port)), _Handler)
    t = threading.Thread(target=server.serve_forever, daemon=True,
                         name="jepsen-metrics")
    t.start()
    return server


# ---------------------------------------------------------------------------
# JAX profiler hooks (JEPSEN_TPU_PROFILE=<dir>)
# ---------------------------------------------------------------------------

_profiler_lock = threading.Lock()
_profiler_started = False       # guarded-by: _profiler_lock


def profile_dir() -> str | None:
    return os.environ.get(PROFILE_ENV) or None


def _ensure_profiler() -> bool:
    """Start the one process-wide jax.profiler trace on first use
    (stopped atexit). False when the env var is unset or the profiler
    is unavailable — callers then skip annotations too."""
    global _profiler_started
    d = profile_dir()
    if not d:
        return False
    if _profiler_started:  # noqa: JTS201 — double-checked fast path
        return True
    with _profiler_lock:
        if _profiler_started:
            return True
        try:
            import atexit

            import jax
            jax.profiler.start_trace(d)
            atexit.register(stop_profiler)
            _profiler_started = True
        except Exception:  # noqa: BLE001 — profiling is best-effort
            return False
    return True


def stop_profiler() -> None:
    global _profiler_started
    with _profiler_lock:
        if not _profiler_started:
            return
        try:
            import jax
            jax.profiler.stop_trace()
        except Exception:  # noqa: BLE001 — already stopped / torn down
            pass
        _profiler_started = False


@contextlib.contextmanager
def profile_section(name: str):
    """Wrap a device section in a named ``jax.profiler``
    TraceAnnotation when JEPSEN_TPU_PROFILE is set; a strict no-op
    otherwise (no jax import, no profiler start — pinned by
    tests/test_telemetry.py)."""
    if not _ensure_profiler():
        yield
        return
    try:
        import jax
        ann = jax.profiler.TraceAnnotation(name)
    except Exception:  # noqa: BLE001 — profiling is best-effort
        yield
        return
    with ann:
        yield
