// adj_time: gradual clock slew.
//
// TPU-host-native C++ port of the behavior of the reference's
// cockroachdb/resources/adjtime.c (19 LoC C): ask the kernel to slew
// the wall clock by <delta> milliseconds gradually via adjtime(2) —
// unlike bump_time's discontinuous jump, the clock stays monotonic
// while running fast/slow until the offset is absorbed.
//
// Usage: adj_time <delta-ms>
// Exit:  0 ok, 1 usage, 2 adjtime error (needs root).

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <sys/time.h>

int main(int argc, char **argv) {
  if (argc < 2) {
    std::fprintf(stderr, "usage: %s <delta-ms>\n", argv[0]);
    return 1;
  }

  const auto delta_us =
      static_cast<std::int64_t>(std::atof(argv[1]) * 1000.0);

  timeval delta{};
  delta.tv_sec = delta_us / 1'000'000;
  delta.tv_usec = delta_us % 1'000'000;

  timeval remaining{};  // any still-unabsorbed previous adjustment
  if (adjtime(&delta, &remaining) != 0) {
    std::perror("adjtime");
    return 2;
  }
  std::printf("%lld.%06lld\n", static_cast<long long>(remaining.tv_sec),
              static_cast<long long>(remaining.tv_usec));
  return 0;
}
