// bump_time: one-shot wall-clock jump.
//
// TPU-host-native C++ port of the behavior of the reference's
// jepsen/resources/bump-time.c (53 LoC C): shift the system wall clock
// by <delta> milliseconds via settimeofday(2), then print the resulting
// wall-clock time as "<sec>.<usec>" so the caller can compute offsets.
//
// Usage: bump_time <delta-ms>     (delta may be negative / fractional)
// Exit:  0 ok, 1 usage/gettimeofday error, 2 settimeofday error (needs
//        root and a real clock — not valid inside containers).

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <sys/time.h>

namespace {

constexpr std::int64_t kUsecPerSec = 1'000'000;

// Normalize tv_usec into [0, 1e6).
void normalize(timeval &tv) {
  while (tv.tv_usec < 0) {
    tv.tv_sec -= 1;
    tv.tv_usec += kUsecPerSec;
  }
  while (tv.tv_usec >= kUsecPerSec) {
    tv.tv_sec += 1;
    tv.tv_usec -= kUsecPerSec;
  }
}

}  // namespace

int main(int argc, char **argv) {
  if (argc < 2) {
    std::fprintf(stderr, "usage: %s <delta-ms>\n", argv[0]);
    return 1;
  }

  const auto delta_us =
      static_cast<std::int64_t>(std::atof(argv[1]) * 1000.0);

  timeval now{};
  if (gettimeofday(&now, nullptr) != 0) {
    std::perror("gettimeofday");
    return 1;
  }

  now.tv_sec += delta_us / kUsecPerSec;
  now.tv_usec += delta_us % kUsecPerSec;
  normalize(now);

  if (settimeofday(&now, nullptr) != 0) {
    std::perror("settimeofday");
    return 2;
  }

  if (gettimeofday(&now, nullptr) != 0) {
    std::perror("gettimeofday");
    return 1;
  }
  std::printf("%lld.%06lld\n", static_cast<long long>(now.tv_sec),
              static_cast<long long>(now.tv_usec));
  return 0;
}
