// strobe_time: oscillate the wall clock around true time.
//
// TPU-host-native C++ port of the behavior of the reference's
// jepsen/resources/strobe-time.c (171 LoC C): every <period> ms, flip
// the wall clock between true time and true time + <delta> ms, for
// <duration> seconds, using CLOCK_MONOTONIC as the undisturbed
// reference; restore the clock and print the number of flips.
//
// Usage: strobe_time <delta-ms> <period-ms> <duration-s>
// Exit:  0 ok, 1 usage, 2 settimeofday error, 3 nanosleep error.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <ctime>
#include <sys/time.h>

namespace {

constexpr std::int64_t kNanosPerSec = 1'000'000'000;

// All arithmetic in signed 64-bit nanoseconds — simpler and less
// error-prone than timespec carry chains for the ranges involved
// (±2^18 ms skews over ≤32 s runs fit comfortably).
std::int64_t to_nanos(const timespec &ts) {
  return static_cast<std::int64_t>(ts.tv_sec) * kNanosPerSec + ts.tv_nsec;
}

std::int64_t monotonic_nanos() {
  timespec ts{};
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return to_nanos(ts);
}

std::int64_t wall_nanos() {
  timeval tv{};
  if (gettimeofday(&tv, nullptr) != 0) {
    std::perror("gettimeofday");
    std::exit(1);
  }
  return static_cast<std::int64_t>(tv.tv_sec) * kNanosPerSec +
         static_cast<std::int64_t>(tv.tv_usec) * 1000;
}

void set_wall_nanos(std::int64_t nanos) {
  timeval tv{};
  tv.tv_sec = nanos / kNanosPerSec;
  tv.tv_usec = (nanos % kNanosPerSec) / 1000;
  if (tv.tv_usec < 0) {
    tv.tv_sec -= 1;
    tv.tv_usec += 1'000'000;
  }
  if (settimeofday(&tv, nullptr) != 0) {
    std::perror("settimeofday");
    std::exit(2);
  }
}

}  // namespace

int main(int argc, char **argv) {
  if (argc < 4) {
    std::fprintf(stderr,
                 "usage: %s <delta-ms> <period-ms> <duration-s>\n"
                 "Every period ms, toggles the wall clock between true "
                 "time and true time + delta ms, for duration seconds; "
                 "then restores the clock. Useful for confusing systems "
                 "that assume clocks are monotonic and linear.\n",
                 argv[0]);
    return 1;
  }

  const auto delta = static_cast<std::int64_t>(
      std::atof(argv[1]) * 1'000'000.0);
  const auto period_ns = static_cast<std::int64_t>(
      std::atof(argv[2]) * 1'000'000.0);
  const auto duration = static_cast<std::int64_t>(
      std::atof(argv[3]) * 1'000'000'000.0);

  // Wall time = monotonic time + offset; the strobe toggles the offset.
  const std::int64_t true_offset = wall_nanos() - monotonic_nanos();
  const std::int64_t skew_offset = true_offset + delta;
  const std::int64_t end = monotonic_nanos() + duration;

  timespec period{};
  period.tv_sec = period_ns / kNanosPerSec;
  period.tv_nsec = period_ns % kNanosPerSec;

  bool skewed = false;
  std::int64_t flips = 0;
  while (monotonic_nanos() < end) {
    set_wall_nanos(monotonic_nanos() +
                   (skewed ? true_offset : skew_offset));
    skewed = !skewed;
    ++flips;
    timespec rem{};
    if (nanosleep(&period, &rem) != 0) {
      std::perror("nanosleep");
      return 3;
    }
  }

  set_wall_nanos(monotonic_nanos() + true_offset);
  std::printf("%lld\n", static_cast<long long>(flips));
  return 0;
}
