// strobe_time_experiment: phase-locked wall-clock strobing.
//
// TPU-host-native C++ port of the *intent* of the reference's
// jepsen/resources/strobe-time-experiment.c (205 LoC C). That file is
// an abandoned draft: it builds tick-alignment machinery (next_tick /
// sleep_until_next_tick anchored to CLOCK_MONOTONIC) but its main()
// never calls it, and the file does not compile (a stray token in
// timespec_to_nanos, `null` for NULL). This port finishes the idea:
// unlike the shipped strobe_time, which sleeps a *relative* period
// between flips and therefore drifts by the per-iteration overhead,
// this variant sleeps until the next absolute tick anchor + n*period
// on the monotonic clock, so flip edges stay phase-locked over long
// durations — the property the experiment was reaching for.
//
// Usage: strobe_time_experiment <delta-ms> <period-ms> <duration-s>
// Exit:  0 ok, 1 usage, 2 settimeofday error, 3 nanosleep error.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <ctime>
#include <sys/time.h>

namespace {

constexpr std::int64_t kNanosPerSec = 1'000'000'000;

std::int64_t to_nanos(const timespec &ts) {
  return static_cast<std::int64_t>(ts.tv_sec) * kNanosPerSec + ts.tv_nsec;
}

std::int64_t monotonic_nanos() {
  timespec ts{};
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return to_nanos(ts);
}

std::int64_t wall_nanos() {
  timeval tv{};
  if (gettimeofday(&tv, nullptr) != 0) {
    std::perror("gettimeofday");
    std::exit(1);
  }
  return static_cast<std::int64_t>(tv.tv_sec) * kNanosPerSec +
         static_cast<std::int64_t>(tv.tv_usec) * 1000;
}

void set_wall_nanos(std::int64_t nanos) {
  timeval tv{};
  tv.tv_sec = nanos / kNanosPerSec;
  tv.tv_usec = (nanos % kNanosPerSec) / 1000;
  if (tv.tv_usec < 0) {
    tv.tv_sec -= 1;
    tv.tv_usec += 1'000'000;
  }
  if (settimeofday(&tv, nullptr) != 0) {
    std::perror("settimeofday");
    std::exit(2);
  }
}

// Sleep until the next absolute tick anchor + n*period (n integral)
// strictly after "now" — the experiment's next_tick/
// sleep_until_next_tick, collapsed into 64-bit nanosecond arithmetic.
int sleep_until_next_tick(std::int64_t anchor, std::int64_t period) {
  const std::int64_t now = monotonic_nanos();
  const std::int64_t next = now + (period - (now - anchor) % period);
  const std::int64_t delta = next - monotonic_nanos();
  if (delta <= 0) return 0;
  timespec ts{};
  ts.tv_sec = delta / kNanosPerSec;
  ts.tv_nsec = delta % kNanosPerSec;
  timespec rem{};
  return nanosleep(&ts, &rem);
}

}  // namespace

int main(int argc, char **argv) {
  if (argc < 4) {
    std::fprintf(stderr,
                 "usage: %s <delta-ms> <period-ms> <duration-s>\n"
                 "Phase-locked strobe: on every absolute period tick "
                 "of the monotonic clock, toggles the wall clock "
                 "between true time and true time + delta ms, for "
                 "duration seconds; then restores the clock.\n",
                 argv[0]);
    return 1;
  }

  const auto delta = static_cast<std::int64_t>(
      std::atof(argv[1]) * 1'000'000.0);
  const auto period = static_cast<std::int64_t>(
      std::atof(argv[2]) * 1'000'000.0);
  const auto duration = static_cast<std::int64_t>(
      std::atof(argv[3]) * 1'000'000'000.0);
  if (period <= 0) {
    std::fprintf(stderr, "period must be positive\n");
    return 1;
  }

  const std::int64_t true_offset = wall_nanos() - monotonic_nanos();
  const std::int64_t skew_offset = true_offset + delta;
  const std::int64_t anchor = monotonic_nanos();
  const std::int64_t end = anchor + duration;

  bool skewed = false;
  std::int64_t flips = 0;
  while (monotonic_nanos() < end) {
    set_wall_nanos(monotonic_nanos() +
                   (skewed ? true_offset : skew_offset));
    skewed = !skewed;
    ++flips;
    if (sleep_until_next_tick(anchor, period) != 0) {
      std::perror("nanosleep");
      return 3;
    }
  }

  set_wall_nanos(monotonic_nanos() + true_offset);
  std::printf("%lld\n", static_cast<long long>(flips));
  return 0;
}
