"""The chaos loop: schedule, execute, oracle, cover, mutate, shrink.

One chaos run is `run_chaos(ChaosConfig)`: per schedule, a genome's
backend events are installed as a `_platform.FaultSchedule` and its
lifecycle events scripted against a live `VerificationService` while a
fixed deterministic workload streams through it; after every run the
oracles (`oracles.py`) compare the outcome against the uninjected solo
verdict and the schedule the harness itself injected. Coverage bits
over (fault-kind x site x lifecycle-state) transitions
(`search.coverage.extract_chaos_coverage`) feed the corpus, so a
guided run gradients toward untrodden recovery paths — most prizedly
the fault-DURING-replay conjunction no single-fault test reaches.
Oracle failures shrink to a minimal schedule via the budgeted greedy
shrinker, `search/driver.py` style.

Execution transports:

  in-process  admit/offer/seal against VerificationService directly,
              the driver mirroring every op into the run's
              journal.jsonl (store layout) so kill-recover / failover
              / drain-resume can promote a standby that re-feeds from
              the journal — the PR 14 crash-consistency machinery IS
              the system under test
  socket      chosen when the genome schedules a socket `drop`: the
              feed rides a ServiceClient through a drop-proxy whose
              connections the driver cuts on cue (session replay must
              make the drops invisible)

Determinism: one `random.Random(cfg.seed)` owns sampling + mutation;
the workload history derives from the genome's seed; probes are
emitted synchronously from the single worker thread. Same config ->
same search.
"""

from __future__ import annotations

import dataclasses
import gc
import gzip
import json
import os
import random
import shutil
import socket as _socket
import tempfile
import threading
import time as _time
from typing import Optional

from .. import _platform, models, store, telemetry
from ..checker import synth
from ..search.coverage import CoverageMap, extract_chaos_coverage
from . import genome as genome_mod
from .genome import ChaosGenome, genome_size, mutate, sample_genome
from .oracles import ORACLES, check_oracles

_M_SCHEDULES = telemetry.counter(
    "jepsen_tpu_chaos_schedules_total",
    "Chaos schedules executed against the live pipeline, by strategy",
    ("strategy",))
_M_FAILURES = telemetry.counter(
    "jepsen_tpu_chaos_oracle_failures_total",
    "Oracle failures observed (pre-shrink), by oracle", ("oracle",))
_M_COV = telemetry.gauge(
    "jepsen_tpu_chaos_coverage_bits",
    "Accumulated chaos-corpus coverage bits")
_M_CORPUS = telemetry.gauge(
    "jepsen_tpu_chaos_corpus_genomes",
    "Genomes in the chaos corpus")
_M_SHRINK = telemetry.counter(
    "jepsen_tpu_chaos_shrink_steps_total",
    "Shrink candidate re-executions")
_M_RUN_S = telemetry.histogram(
    "jepsen_tpu_chaos_schedule_seconds",
    "Wall-clock seconds per executed chaos schedule")

# guided-mode fresh-blood fraction, as in search/driver.py
FRESH_FRACTION = 0.2

# the fixed verification workload (small enough that a smoke budget of
# ~20 schedules stays in CPU seconds; sized so recovery replays span
# 1-2 chunks — the conjunction window)
_MODEL = models.cas_register()
CHUNK = 64
SLOTS = 8
FRONTIER = 128
CKPT = 2

WORKLOADS = ("register", "register-corrupt")


def workload_spec() -> dict:
    from ..service import model_spec
    return {"linear": {"kind": "wgl", "model": model_spec(_MODEL),
                       "chunk-entries": CHUNK, "slots": SLOTS,
                       "engine": "sort", "frontier": FRONTIER,
                       "checkpoint-every": CKPT}}


def workload_ops(workload: str, n: int, seed: int) -> list:
    """Deterministic journal-form ops for a genome. 'register-corrupt'
    plants one definite violation so the violation-missed oracle has
    ground truth to defend."""
    h = synth.register_history(n, concurrency=3, values=5, seed=seed)
    if workload == "register-corrupt":
        h = synth.corrupt(h, seed=7)
    elif workload != "register":
        raise ValueError(f"unknown chaos workload {workload!r}")
    return [json.loads(json.dumps(op, default=store._json_default))
            for op in h.ops]


@dataclasses.dataclass
class ChaosConfig:
    workload: str = "register"
    ops: int = 256
    budget: int = 40              # total schedule executions
    seed: int = 45100
    strategy: str = "guided"      # guided | random
    lifecycle_p: float = genome_mod.LIFECYCLE_P
    deadline_s: float = 120.0     # per-run watchdog (wedge oracle)
    stop_on_failure: bool = True
    shrink: bool = True
    store_dir: Optional[str] = None   # artifact dir (chaos.json, coverage.bin)
    scratch_dir: Optional[str] = None  # per-run store roots (tmp if None)


def _count_fds() -> int:
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return 0


def _settle(fds0: int, threads0: int, budget_s: float = 3.0) -> tuple:
    """Post-run resource snapshot with a settle wait: terminal worker
    threads and just-closed sockets need a beat to leave the process
    tables, and a transiently elevated count is not a leak."""
    deadline = _time.monotonic() + budget_s
    while True:
        gc.collect()
        fds, threads = _count_fds(), threading.active_count()
        if (fds <= fds0 and threads <= threads0) \
                or _time.monotonic() >= deadline:
            return fds, threads
        _time.sleep(0.05)


def replay_conjunction(probes: list) -> bool:
    """Did a fault land inside an open recovery-replay window? (The
    probe stream is worker-thread-ordered, so this is deterministic.)"""
    open_sites: set = set()
    for p in probes:
        ev = p.get("event")
        sc = str(p.get("site", "")).split("/", 1)[0]
        if ev == "replay-begin":
            open_sites.add(sc)
        elif ev == "replay-end":
            open_sites.discard(sc)
        elif ev in ("fault", "inject", "corrupt") and sc in open_sites:
            return True
    return False


class _DropProxy:
    """A TCP proxy in front of the service's unix socket whose live
    connections the driver cuts on cue — the socket-drop injector
    (the PR 14 drop-proxy, harness-side)."""

    def __init__(self, upstream_addr: str):
        self.upstream_addr = upstream_addr
        self.ls = _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM)
        self.ls.setsockopt(_socket.SOL_SOCKET, _socket.SO_REUSEADDR, 1)
        self.ls.bind(("127.0.0.1", 0))
        self.ls.listen(16)
        self.addr = "127.0.0.1:%d" % self.ls.getsockname()[1]
        self._lock = threading.Lock()
        self._conns: list = []      # guarded-by: _lock
        self._closing = False
        self._thread = threading.Thread(
            target=self._accept, daemon=True,
            name="jepsen-chaos-proxy")
        self._thread.start()

    def _accept(self) -> None:
        while True:
            try:
                down, _ = self.ls.accept()
            except OSError:
                return
            if self._closing:       # close()'s wake-up poke
                down.close()
                return
            up = _socket.socket(_socket.AF_UNIX, _socket.SOCK_STREAM)
            try:
                up.connect(self.upstream_addr)
            except OSError:
                down.close()
                continue
            with self._lock:
                self._conns.append((down, up))
            for a, b in ((down, up), (up, down)):
                threading.Thread(target=self._pump, args=(a, b),
                                 daemon=True).start()

    @staticmethod
    def _pump(src, dst) -> None:
        try:
            while True:
                data = src.recv(65536)
                if not data:
                    break
                dst.sendall(data)
        except OSError:
            pass
        for s in (src, dst):
            try:
                s.shutdown(_socket.SHUT_RDWR)
            except OSError:
                pass

    def drop_all(self) -> None:
        with self._lock:
            conns, self._conns = self._conns, []
        for down, up in conns:
            for s in (down, up):
                try:
                    s.shutdown(_socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    s.close()
                except OSError:
                    pass

    def close(self) -> None:
        self.drop_all()
        self._closing = True
        # accept() does not wake on close() alone; poke it
        try:
            with _socket.socket(_socket.AF_INET,
                                _socket.SOCK_STREAM) as poke:
                poke.settimeout(0.2)
                poke.connect(self.ls.getsockname())
        except OSError:
            pass
        try:
            self.ls.close()
        except OSError:
            pass
        self._thread.join(timeout=1.0)


class _Chaos:
    def __init__(self, cfg: ChaosConfig):
        self.cfg = cfg
        self.rng = random.Random(cfg.seed)
        self.cmap = CoverageMap()
        self.corpus: list = []          # (genome, novel-bit-count)
        self._keys: set = set()
        self.curve: list = []
        self.runs = 0
        self.shrink_steps = 0
        self.failures: list = []
        self.conjunction_hits = 0
        self._baselines: dict = {}
        self._scratch = cfg.scratch_dir
        self._own_scratch = False
        self._seq = 0

    # -- plumbing ----------------------------------------------------------

    def budget_left(self) -> bool:
        return self.runs < self.cfg.budget

    def _count_run(self) -> None:
        self.runs += 1
        _M_SCHEDULES.labels(strategy=self.cfg.strategy).inc()

    def scratch(self) -> str:
        if self._scratch is None:
            self._scratch = tempfile.mkdtemp(prefix="jepsen-chaos-")
            self._own_scratch = True
        return self._scratch

    def baseline(self, g: ChaosGenome) -> dict:
        """The uninjected tier-full solo verdict for this genome's
        workload — the oracle ground truth, cached per (workload,
        ops, seed)."""
        key = (g.workload, g.ops, g.seed)
        if key not in self._baselines:
            from ..checker.streaming import WglStream
            s = WglStream(_MODEL, chunk_entries=CHUNK, slots=SLOTS,
                          frontier=FRONTIER, checkpoint_every=CKPT)
            for op in workload_ops(g.workload, g.ops, g.seed):
                s.feed(op)
            self._baselines[key] = {"linear": s.finish()}
        return self._baselines[key]

    # -- one schedule -------------------------------------------------------

    def run_schedule(self, g: ChaosGenome) -> dict:
        """Execute one genome against a fresh service and check every
        oracle. Returns the outcome record (fired events, applied
        actions, probe stream, coverage, oracle failures)."""
        baseline = self.baseline(g)
        ops = workload_ops(g.workload, g.ops, g.seed)
        self._seq += 1
        base = os.path.join(self.scratch(), f"run{self._seq}")
        run_dir = os.path.join(base, "chaos", "0")
        os.makedirs(run_dir, exist_ok=True)

        probes: list = []
        hook_prev = _platform.probe_hook
        _platform.probe_hook = probes.append
        _platform.reset_fault_injection()
        schedule = _platform.FaultSchedule(
            [_platform.FaultEvent(e.kind, e.site, e.at)
             for e in g.backend_events()])
        _platform.install_fault_schedule(schedule)

        fds0, threads0 = _count_fds(), threading.active_count()
        socket_mode = any(e.kind == "drop"
                          for e in g.lifecycle_events())
        t0 = _time.monotonic()
        try:
            if socket_mode:
                out = self._run_socket(g, base, run_dir, ops)
            else:
                out = self._run_inproc(g, base, run_dir, ops)
        finally:
            _platform.install_fault_schedule(None)
            _platform.probe_hook = hook_prev
            _platform.reset_fault_injection()
        wall = _time.monotonic() - t0
        _M_RUN_S.observe(wall)
        fds1, threads1 = _settle(fds0, threads0)
        try:
            shutil.rmtree(base)
        except OSError:
            pass

        outcome = {
            "genome": g,
            "fired": list(schedule.fired),
            "probes": probes,
            "deadline-s": self.cfg.deadline_s,
            "wall-s": round(wall, 3),
            **out,
        }
        resources = {"fds-before": fds0, "fds-after": fds1,
                     "threads-before": threads0,
                     "threads-after": threads1}
        outcome["failures"] = check_oracles(baseline, outcome,
                                            resources)
        outcome["coverage"] = extract_chaos_coverage(
            probes, outcome.get("actions") or [])
        outcome["conjunction"] = replay_conjunction(probes)
        return outcome

    def _wait_verdict(self, svc, name: str, run_dir: str) -> tuple:
        """(results, timed_out): the worker's in-memory verdict or the
        store's delivered/deferred one, whichever lands first."""
        deadline = _time.monotonic() + self.cfg.deadline_s
        while _time.monotonic() < deadline:
            w = svc._worker(name)
            if w is not None and w.done.is_set():
                res = dict(w.results)
                if not res:
                    res = store.load_streamed_results(run_dir) or res
                return res, False
            res = store.load_streamed_results(run_dir)
            if res is not None:
                return res, False
            _time.sleep(0.01)
        return None, True

    def _run_inproc(self, g: ChaosGenome, base: str, run_dir: str,
                    ops: list) -> dict:
        from ..service import VerificationService
        spec = workload_spec()
        name = "chaos/0"
        lifecycle: dict = {}
        for e in g.lifecycle_events():
            lifecycle.setdefault(min(e.at, len(ops) - 1),
                                 []).append(e.kind)
        svc = VerificationService(adaptive=False)
        svc.claim_store(base)   # so a promoted standby fences us
        svcs = [svc]
        to_seal: list = []
        cur = svc
        applied: list = []
        skipped: list = []
        journal_fed = False
        jf = open(os.path.join(run_dir, "journal.jsonl"), "a")
        try:
            cur.admit(name, spec, store_dir=run_dir)
            for i, op in enumerate(ops):
                for kind in lifecycle.get(i, ()):
                    cur, journal_fed = self._apply_action(
                        kind, cur, svcs, to_seal, name, base,
                        run_dir, spec, applied, skipped,
                        journal_fed)
                jf.write(json.dumps(op,
                                    default=store._json_default)
                         + "\n")
                jf.flush()
                if not journal_fed:
                    cur.offer(name, op)
        finally:
            jf.close()
        if journal_fed:
            # the journal is the feed now: publish the completed
            # history so the watcher seals the tailed stream
            with gzip.open(os.path.join(run_dir,
                                        "history.jsonl.gz"),
                           "wt") as fh:
                for op in ops:
                    fh.write(json.dumps(
                        op, default=store._json_default) + "\n")
        else:
            cur.seal(name)
        for s in to_seal:
            s.seal(name)
        results, timed_out = self._wait_verdict(cur, name, run_dir)
        # teardown: every service instance down, every worker terminal
        deadline = _time.monotonic() + 5.0
        for s in svcs:
            w = s._worker(name)
            if w is not None:
                w.done.wait(max(0.0, deadline - _time.monotonic()))
            s.stop()
        shed = any(k == "shed" for k in applied)
        deferred = bool(isinstance(results, dict)
                        and results.get("deferred")) or shed
        degraded = bool(isinstance(results, dict)
                        and results.get("degraded"))
        return {"results": results, "timed-out": timed_out,
                "deferred": deferred, "degraded": degraded,
                "actions": applied, "skipped-actions": skipped}

    def _apply_action(self, kind: str, cur, svcs: list,
                      to_seal: list, name: str, base: str,
                      run_dir: str, spec: dict, applied: list,
                      skipped: list, journal_fed: bool) -> tuple:
        from ..service import VerificationService
        if kind == "shed":
            cur.shed(name, "chaos: scripted shed")
            applied.append(kind)
            return cur, journal_fed
        if kind in ("kill-recover", "failover", "drain-resume"):
            if journal_fed:
                # one promotion per run: a second would fence the
                # standby we are waiting on
                skipped.append(kind)
                return cur, journal_fed
            if kind == "drain-resume":
                cur.drain(timeout_s=10.0)
            b = VerificationService(adaptive=False)
            # claims the store -> fences `cur`; the journal re-feeds
            # from offset 0 while device dispatch skips to the last
            # durable checkpoint
            b.recover(base, spec_fn=lambda _d: dict(spec))
            if kind == "kill-recover":
                # SIGKILL semantics in-process: the old worker is
                # abandoned mid-queue (fenced, so its residue cannot
                # reach the store) and bled
                cur.shed(name, "chaos: sigkill")
            elif kind == "failover":
                # split-brain window: the old primary keeps running
                # its fed prefix to a (fenced, memory-only) verdict
                to_seal.append(cur)
            svcs.append(b)
            applied.append(kind)
            return b, True
        skipped.append(kind)     # 'drop' without socket transport
        return cur, journal_fed

    def _run_socket(self, g: ChaosGenome, base: str, run_dir: str,
                    ops: list) -> dict:
        from ..service import ServiceClient, VerificationService
        spec = workload_spec()
        name = "chaos/0"
        drops: dict = {}
        skipped: list = []
        for e in g.lifecycle_events():
            if e.kind == "drop":
                drops.setdefault(min(e.at, len(ops) - 1),
                                 []).append(e.kind)
            else:
                # socket transport scripts only drops; service-side
                # lifecycle would race the live client connection
                skipped.append(e.kind)
        svc = VerificationService(adaptive=False)
        svc.claim_store(base)
        addr = svc.serve(os.path.join(base, "sock"))
        proxy = _DropProxy(addr)
        applied: list = []
        results, timed_out = None, False
        try:
            client = ServiceClient(
                proxy.addr,
                {"name": "chaos", "start-time": "0",
                 "store-dir": base},
                spec=spec)
            with open(os.path.join(run_dir, "journal.jsonl"),
                      "a") as jf:
                for i, op in enumerate(ops):
                    for kind in drops.get(i, ()):
                        proxy.drop_all()
                        applied.append(kind)
                    jf.write(json.dumps(
                        op, default=store._json_default) + "\n")
                    client.offer(op)
            try:
                results = client.finalize(
                    timeout_s=self.cfg.deadline_s)
            except Exception:  # noqa: BLE001 — the oracles judge it
                results, timed_out = None, True
            client.close()
        finally:
            proxy.close()
            svc.stop()
            w = svc._worker(name)
            if w is not None:
                w.done.wait(5.0)
        deferred = bool(isinstance(results, dict)
                        and results.get("deferred"))
        degraded = bool(isinstance(results, dict)
                        and results.get("degraded"))
        return {"results": results, "timed-out": timed_out,
                "deferred": deferred, "degraded": degraded,
                "actions": applied, "skipped-actions": skipped}

    # -- shrinking ---------------------------------------------------------

    def _reproduces(self, g: ChaosGenome, oracle_names: set) -> bool:
        self._count_run()
        _M_SHRINK.inc()
        self.shrink_steps += 1
        out = self.run_schedule(g)
        got = {f["oracle"] for f in out["failures"]}
        return bool(got & oracle_names)

    def _shrink(self, g: ChaosGenome, oracle_names: set) -> ChaosGenome:
        """Greedy minimization: accept any reduction that still trips
        (one of) the same oracles and is no larger; restart the
        reduction walk from each accepted genome."""
        cur = g
        improved = True
        while improved and self.budget_left():
            improved = False
            for cand in genome_mod.shrink_reductions(cur):
                if not self.budget_left():
                    break
                if cand.key() == cur.key() \
                        or genome_size(cand) > genome_size(cur):
                    continue
                if self._reproduces(cand, oracle_names):
                    cur = cand
                    improved = True
                    break
        return cur

    def _record_failure(self, g: ChaosGenome, outcome: dict) -> None:
        names = {f["oracle"] for f in outcome["failures"]}
        for f in outcome["failures"]:
            _M_FAILURES.labels(oracle=f["oracle"]).inc()
        found_at = self.runs
        minimized = self._shrink(g, names) if self.cfg.shrink else g
        self.failures.append({
            "genome": g.to_dict(),
            "minimized": minimized.to_dict(),
            "oracles": sorted(names),
            "details": outcome["failures"],
            "fired": outcome["fired"],
            "actions": outcome.get("actions") or [],
            "found-at-schedule": found_at,
            "shrink-steps": self.shrink_steps,
        })

    # -- the loop ----------------------------------------------------------

    def _next_genome(self) -> ChaosGenome:
        cfg = self.cfg
        if cfg.strategy == "random" or not self.corpus \
                or self.rng.random() < FRESH_FRACTION:
            return sample_genome(self.rng, cfg.workload, cfg.ops,
                                 cfg.lifecycle_p)
        # recency-weighted draw, as in search/driver.py
        n = len(self.corpus)
        i = self.rng.choices(range(n), weights=range(1, n + 1))[0]
        parent = self.corpus[i][0]
        mates = [c[0] for c in self.corpus]
        return mutate(parent, self.rng, mates)

    def run(self) -> dict:
        cfg = self.cfg
        t_start = _time.monotonic()
        try:
            while self.budget_left():
                g = self._next_genome()
                outcome = self.run_schedule(g)
                self._count_run()
                novel = self.cmap.add(outcome["coverage"])
                if outcome["conjunction"]:
                    self.conjunction_hits += 1
                if cfg.strategy == "guided" and novel \
                        and g.key() not in self._keys:
                    self._keys.add(g.key())
                    self.corpus.append((g, len(novel)))
                self.curve.append(len(self.cmap))
                _M_COV.set(len(self.cmap))
                _M_CORPUS.set(len(self.corpus))
                if outcome["failures"]:
                    self._record_failure(g, outcome)
                    if cfg.stop_on_failure:
                        break
        finally:
            if self._own_scratch and self._scratch:
                shutil.rmtree(self._scratch, ignore_errors=True)
                self._scratch = None
        result = {
            "workload": cfg.workload,
            "strategy": cfg.strategy,
            "seed": cfg.seed,
            "schedules": self.runs,
            "coverage-bits": len(self.cmap),
            "coverage-curve": self.curve,
            "coverage-digest": self.cmap.digest(),
            "corpus-size": len(self.corpus),
            "conjunction-hits": self.conjunction_hits,
            "found-conjunction": self.conjunction_hits > 0,
            "shrink-steps": self.shrink_steps,
            "failures": self.failures,
            "found": bool(self.failures),
            "oracles": list(ORACLES),
            "wall-s": round(_time.monotonic() - t_start, 3),
        }
        if cfg.store_dir:
            self._store(result)
        return result

    # -- artifacts ---------------------------------------------------------

    def _store(self, result: dict) -> None:
        d = self.cfg.store_dir
        os.makedirs(d, exist_ok=True)
        artifact = dict(result)
        artifact["config"] = {
            f.name: getattr(self.cfg, f.name)
            for f in dataclasses.fields(self.cfg)}
        artifact["corpus"] = [
            {"genome": g.to_dict(), "new-bits": n}
            for g, n in self.corpus]
        with open(os.path.join(d, "chaos.json"), "w") as f:
            json.dump(artifact, f, indent=2, sort_keys=True)
        with open(os.path.join(d, "coverage.bin"), "wb") as f:
            f.write(self.cmap.encode())


def run_chaos(cfg: ChaosConfig) -> dict:
    """Run one coverage-guided (or pure-random) chaos fuzz of the
    verification pipeline to its schedule budget. Returns the result
    summary (the store-dir artifact carries the full corpus)."""
    return _Chaos(cfg).run()
