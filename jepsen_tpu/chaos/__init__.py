"""Self-chaos harness: coverage-guided fault-schedule fuzzing of the
verification pipeline itself (doc/robustness.md, "Self-chaos").

The tester gets the Jepsen treatment: generate multi-event backend
fault + service lifecycle schedules, execute each against a live
``VerificationService`` running a fixed deterministic workload, and
hold the outcome to a set of oracles anchored on the uninjected solo
verdict. Coverage over (fault x site x lifecycle-state) transitions
guides the search toward the compound failure paths — fault during
recovery replay, corruption mid-failover — single-fault tests never
reach; oracle failures shrink to a minimal reproducing schedule.
"""

from .driver import (ChaosConfig, run_chaos, workload_ops,
                     workload_spec)
from .genome import (BACKEND_KINDS, LIFECYCLE_KINDS, ChaosEvent,
                     ChaosGenome, mutate, sample_genome,
                     shrink_reductions)
from .oracles import ORACLES, check_oracles, normalize_verdict

__all__ = [
    "BACKEND_KINDS", "LIFECYCLE_KINDS", "ORACLES", "ChaosConfig",
    "ChaosEvent", "ChaosGenome", "check_oracles", "mutate",
    "normalize_verdict", "run_chaos", "sample_genome",
    "shrink_reductions", "workload_ops", "workload_spec",
]
