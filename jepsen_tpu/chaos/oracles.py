"""Chaos oracles: what must hold after EVERY schedule.

The pipeline is decomposed into self-checkable stages (the A-QED
argument, arXiv 2108.06081): each oracle checks one stage's contract
against ground truth the harness already holds — the uninjected solo
verdict, the schedule it injected itself, and the process's own
resource tables. A failure is reported as {oracle, detail}; the
driver shrinks the offending schedule to a minimal repro.

  verdict-identity   a run that reached a full tier-full verdict must
                     match the uninjected solo run byte-for-byte
                     (canonical JSON) modulo the volatile stamps
  violation-missed   a definite violation in the baseline must never
                     come back valid — the one-sided failure no
                     deferred/degraded honesty can excuse
  watchdog           the verdict (or an honest shed/degraded stamp)
                     arrived within the deadline: no wedged worker
  resource-leak      fds and threads return to their pre-run levels
  stamp-consistency  recovered/degraded/deferred stamps match the
                     schedule actually injected: faults fired =>
                     recovered stamp (or honest degradation), nothing
                     fired and no actions => no stamps at all
"""

from __future__ import annotations

import json

from .. import store

# process/feed-timing diagnostics, not verdict content (the same set
# tests/test_service.py strips, plus the fault/attest stamps the
# stamp-consistency oracle checks separately and the per-run ids)
TIMING = ("tail-latency-ms", "duration-ms", "violation-at-op")
VOLATILE = TIMING + ("recovered", "attested", "trace-id",
                     "history-len")

ORACLES = ("verdict-identity", "violation-missed", "watchdog",
           "resource-leak", "stamp-consistency")

# lifecycle actions that promote a standby: after one, the superseded
# (fenced) instance may have consumed schedule events whose stamps the
# successor's verdict never saw — the must-carry-stamp check is only
# sound without a promotion in the schedule
PROMOTIONS = ("kill-recover", "failover", "drain-resume")


def canon(x):
    """Canonical JSON form — 'byte-identical' means identical once
    serialized the way the journal/results serialize everything."""
    return json.loads(json.dumps(x, default=store._json_default,
                                 sort_keys=True))


def normalize_verdict(v: dict) -> dict:
    return canon({k: x for k, x in v.items() if k not in VOLATILE})


def _target_verdicts(results: dict | None) -> dict:
    """The per-target verdict dicts inside a results payload (skips
    the ladder stamp, deferred markers, degraded error strings)."""
    if not isinstance(results, dict):
        return {}
    return {k: v for k, v in results.items()
            if isinstance(v, dict) and "valid?" in v}


def full_verdict(outcome: dict) -> bool:
    """Did this run deliver a complete verdict (not shed-deferred,
    not quarantine-degraded, not timed out)?"""
    return (not outcome.get("timed-out")
            and not outcome.get("deferred")
            and not outcome.get("degraded")
            and bool(_target_verdicts(outcome.get("results"))))


def check_oracles(baseline: dict, outcome: dict,
                  resources: dict | None = None) -> list:
    """All oracle verdicts for one chaos run -> list of failures
    (empty = green). `baseline` maps target name -> solo verdict;
    `outcome` is the driver's run record; `resources` carries the
    before/after fd + thread counts."""
    failures: list = []

    def fail(oracle: str, detail: str) -> None:
        failures.append({"oracle": oracle, "detail": detail})

    fired = list(outcome.get("fired") or [])
    actions = list(outcome.get("actions") or [])
    injected = bool(fired or actions)
    verdicts = _target_verdicts(outcome.get("results"))

    # watchdog: SOMETHING terminal must have arrived in time
    if outcome.get("timed-out"):
        fail("watchdog",
             f"no verdict within {outcome.get('deadline-s')}s "
             f"(fired={fired}, actions={actions})")

    if full_verdict(outcome):
        # verdict-identity (only a full tier-full verdict promises it;
        # a ladder stamp would mark a degraded tier, and the harness
        # runs with the adaptive ladder off)
        for name, solo in baseline.items():
            got = verdicts.get(name)
            if got is None:
                fail("verdict-identity",
                     f"target {name!r} missing from a full verdict")
                continue
            if normalize_verdict(got) != normalize_verdict(solo):
                fail("verdict-identity",
                     f"target {name!r} verdict diverged from the "
                     f"uninjected solo run")

    # violation-missed: one-sided — never report valid over a definite
    # violation, full verdict or not
    for name, solo in baseline.items():
        if solo.get("valid?") is False:
            got = verdicts.get(name)
            if got is not None and got.get("valid?") is True:
                fail("violation-missed",
                     f"target {name!r}: baseline violation reported "
                     f"valid under chaos")

    # stamp-consistency
    backend_fired = [k for (k, _s, _a) in fired]
    promoted = any(a in PROMOTIONS for a in actions)
    if verdicts and not outcome.get("degraded"):
        want = set() if promoted else \
            {"corrupt" if k == "bitflip" else k
             for k in backend_fired}
        for name, got in verdicts.items():
            rec = got.get("recovered")
            have = set((rec or {}).get("faults") or [])
            if want and not rec:
                fail("stamp-consistency",
                     f"target {name!r}: schedule fired {sorted(want)} "
                     f"but the verdict carries no recovered stamp")
            elif want and not want <= have:
                fail("stamp-consistency",
                     f"target {name!r}: recovered stamp {sorted(have)}"
                     f" missing injected {sorted(want - have)}")
            elif not want and rec and not actions:
                fail("stamp-consistency",
                     f"target {name!r}: recovered stamp "
                     f"{rec!r} with nothing injected")
    if outcome.get("degraded") and not injected:
        fail("stamp-consistency",
             "quarantined/degraded with nothing injected")
    if outcome.get("deferred") and not injected:
        fail("stamp-consistency",
             "shed/deferred with nothing injected")
    if not injected and not outcome.get("timed-out") \
            and not full_verdict(outcome):
        fail("stamp-consistency",
             "no faults, no actions, and still no full verdict")

    # resource-leak
    if resources:
        fd0, fd1 = resources.get("fds-before"), resources.get("fds-after")
        th0, th1 = (resources.get("threads-before"),
                    resources.get("threads-after"))
        if fd0 is not None and fd1 is not None and fd1 > fd0:
            fail("resource-leak", f"fds {fd0} -> {fd1}")
        if th0 is not None and th1 is not None and th1 > th0:
            fail("resource-leak", f"threads {th0} -> {th1}")
    return failures
