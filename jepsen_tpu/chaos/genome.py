"""The chaos genome: one fuzzable fault+lifecycle schedule.

A chaos genome describes one self-test of the verification pipeline:
a workload seed + op budget, and an ordered list of *events* to
inject while the workload streams through a live
``VerificationService``. Events come in two families:

  backend    one of ``_platform.FAULT_KINDS`` + ``bitflip``, armed as
             a relative site-hit trigger: event i+1 starts counting
             hits only after event i fired (``FaultSchedule``
             semantics — what the absolute-counter env clauses cannot
             express, and the only way to land a fault *inside* the
             recovery replay of the previous one)
  lifecycle  a scripted service action at an op index: shed, socket
             drop (PR 14 drop-proxy), SIGKILL+recover, standby
             failover, drain+resume

Genomes are plain data (to_dict/from_dict round-trip through JSON for
corpus artifacts and repro files), mutators are deterministic under an
explicit ``random.Random``, and shrink_reductions() yields candidate
reductions in decreasing-aggressiveness order — the same engine shape
as search/mutate.py, over a different universe.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Iterator

# the backend vocabulary: _platform.FAULT_KINDS (dispatch-time raises)
# plus bitflip (staging-time silent corruption, caught by ABFT)
BACKEND_KINDS = ("oom", "device-lost", "compile", "wedged", "corrupt",
                 "bitflip")
LIFECYCLE_KINDS = ("shed", "drop", "kill-recover", "failover",
                   "drain-resume")

# genome sampling ranges (the "seed universe"): guided and random draw
# from exactly this space, so an A/B at a fixed budget compares search
# strategies, not spaces. MAX_AFTER is deliberately wide relative to
# the handful of chunks a smoke workload dispatches: most random
# backend events never fire, and a second event landing inside a
# recovery replay (a 1-2 hit window) is a conjunction random sampling
# essentially never constructs — the gradient the guided search climbs
MAX_EVENTS = 4
MAX_AFTER = 32
_AFTER_LOG2 = 5.0    # log2(MAX_AFTER): sample_at's draw exponent
MIN_OPS = 64
SEED_SPACE = 2 ** 32
DEFAULT_SITE = "stream-chunk/*"
LIFECYCLE_P = 0.2


@dataclasses.dataclass(frozen=True)
class ChaosEvent:
    """One scheduled event. For backend kinds ``at`` is the site-hit
    count after the previous backend event fired (>= 1); for
    lifecycle kinds it is the op index in the feed (>= 0)."""
    kind: str
    at: int
    site: str = DEFAULT_SITE

    @property
    def lifecycle(self) -> bool:
        return self.kind in LIFECYCLE_KINDS

    def to_dict(self) -> dict:
        return {"kind": self.kind, "at": self.at, "site": self.site}

    @classmethod
    def from_dict(cls, d: dict) -> "ChaosEvent":
        return cls(kind=d["kind"], at=int(d["at"]),
                   site=d.get("site", DEFAULT_SITE))


@dataclasses.dataclass(frozen=True)
class ChaosGenome:
    seed: int
    workload: str
    ops: int
    events: tuple

    def to_dict(self) -> dict:
        return {"seed": self.seed, "workload": self.workload,
                "ops": self.ops,
                "events": [e.to_dict() for e in self.events]}

    @classmethod
    def from_dict(cls, d: dict) -> "ChaosGenome":
        return cls(seed=int(d["seed"]), workload=d["workload"],
                   ops=int(d["ops"]),
                   events=tuple(ChaosEvent.from_dict(e)
                                for e in d.get("events", [])))

    def key(self) -> tuple:
        """Canonical identity for corpus dedup. Event ORDER is
        identity — the whole point of a schedule."""
        return (self.seed, self.workload, self.ops,
                tuple((e.kind, e.at, e.site) for e in self.events))

    def backend_events(self) -> list:
        return [e for e in self.events if not e.lifecycle]

    def lifecycle_events(self) -> list:
        return [e for e in self.events if e.lifecycle]


def _clamp(x: int, lo: int, hi: int) -> int:
    return max(lo, min(hi, int(x)))


def sample_at(rng: random.Random) -> int:
    """Log-uniform backend trigger draw over 1..MAX_AFTER: a smoke
    workload only dispatches a handful of chunks, so a uniform draw
    would leave most events inert and the coverage landscape flat
    (no fired fault, no gradient for the guided search to climb).
    Both strategies sample from this same distribution — the A/B
    compares search, not spaces."""
    return _clamp(round(2.0 ** rng.uniform(0.0, _AFTER_LOG2)),
                  1, MAX_AFTER)


def sample_event(rng: random.Random, ops: int,
                 lifecycle_p: float = LIFECYCLE_P) -> ChaosEvent:
    if rng.random() < lifecycle_p:
        return ChaosEvent(kind=rng.choice(LIFECYCLE_KINDS),
                          at=rng.randrange(ops))
    return ChaosEvent(kind=rng.choice(BACKEND_KINDS),
                      at=sample_at(rng))


def sample_genome(rng: random.Random, workload: str, ops: int,
                  lifecycle_p: float = LIFECYCLE_P) -> ChaosGenome:
    """One uniform draw from the seed universe."""
    n = rng.randint(1, MAX_EVENTS - 1)
    return ChaosGenome(
        seed=rng.randrange(SEED_SPACE), workload=workload, ops=ops,
        events=tuple(sample_event(rng, ops, lifecycle_p)
                     for _ in range(n)))


# -- mutators ---------------------------------------------------------------

def _with_event(g: ChaosGenome, i: int, e: ChaosEvent) -> ChaosGenome:
    events = list(g.events)
    events[i] = e
    return dataclasses.replace(g, events=tuple(events))


def _event_bounds(e: ChaosEvent, ops: int) -> tuple:
    return (0, ops - 1) if e.lifecycle else (1, MAX_AFTER)


def _perturb(g: ChaosGenome, rng: random.Random) -> ChaosGenome:
    """Nudge one event's trigger — the workhorse. Small sigma keeps a
    coverage-novel schedule's mutants exploring its neighborhood (a
    fired fault's successors inch toward the replay window)."""
    if not g.events:
        return _add_event(g, rng)
    i = rng.randrange(len(g.events))
    e = g.events[i]
    lo, hi = _event_bounds(e, g.ops)
    sigma = max(1.0, 0.15 * (hi - lo))
    at = _clamp(round(e.at + rng.gauss(0.0, sigma)), lo, hi)
    return _with_event(g, i, dataclasses.replace(e, at=at))


def _hasten(g: ChaosGenome, rng: random.Random) -> ChaosGenome:
    """Halve one trigger toward its floor — the directed version of
    perturb (earlier faults fire more, and a small relative trigger
    is what lands event i+1 inside event i's recovery replay)."""
    if not g.events:
        return _add_event(g, rng)
    i = rng.randrange(len(g.events))
    e = g.events[i]
    lo, _hi = _event_bounds(e, g.ops)
    return _with_event(g, i, dataclasses.replace(
        e, at=max(lo, e.at // 2)))


def _swap_kind(g: ChaosGenome, rng: random.Random) -> ChaosGenome:
    if not g.events:
        return _add_event(g, rng)
    i = rng.randrange(len(g.events))
    e = g.events[i]
    pool = LIFECYCLE_KINDS if e.lifecycle else BACKEND_KINDS
    others = [k for k in pool if k != e.kind]
    return _with_event(g, i, dataclasses.replace(
        e, kind=rng.choice(others)))


def _add_event(g: ChaosGenome, rng: random.Random) -> ChaosGenome:
    if len(g.events) >= MAX_EVENTS:
        return _perturb(g, rng)
    e = sample_event(rng, g.ops)
    i = rng.randint(0, len(g.events))
    events = list(g.events)
    events.insert(i, e)
    return dataclasses.replace(g, events=tuple(events))


def _stack_fault(g: ChaosGenome, rng: random.Random) -> ChaosGenome:
    """Append a backend fault with a SMALL relative trigger right
    after an existing backend event — the direct constructor of the
    fault-during-recovery conjunction."""
    backend = [i for i, e in enumerate(g.events) if not e.lifecycle]
    if not backend or len(g.events) >= MAX_EVENTS:
        return _add_event(g, rng)
    i = rng.choice(backend)
    stacked = ChaosEvent(kind=rng.choice(BACKEND_KINDS),
                         at=rng.randint(1, 3), site=g.events[i].site)
    events = list(g.events)
    events.insert(i + 1, stacked)
    return dataclasses.replace(g, events=tuple(events))


def _drop_event(g: ChaosGenome, rng: random.Random) -> ChaosGenome:
    if len(g.events) <= 1:
        return _perturb(g, rng)
    i = rng.randrange(len(g.events))
    return dataclasses.replace(
        g, events=g.events[:i] + g.events[i + 1:])


def _swap_order(g: ChaosGenome, rng: random.Random) -> ChaosGenome:
    if len(g.events) < 2:
        return _perturb(g, rng)
    i = rng.randrange(len(g.events) - 1)
    events = list(g.events)
    events[i], events[i + 1] = events[i + 1], events[i]
    return dataclasses.replace(g, events=tuple(events))


def _reseed(g: ChaosGenome, rng: random.Random) -> ChaosGenome:
    return dataclasses.replace(g, seed=rng.randrange(SEED_SPACE))


MUTATORS = (
    (_perturb, 5), (_hasten, 3), (_swap_kind, 2), (_add_event, 1),
    (_stack_fault, 3), (_drop_event, 1), (_swap_order, 1),
    (_reseed, 1),
)


def splice(a: ChaosGenome, b: ChaosGenome,
           rng: random.Random) -> ChaosGenome:
    """Cross two genomes: a prefix of one parent's schedule followed
    by a suffix of the other's (order within each parent preserved —
    schedules are sequences, not sets), capped at MAX_EVENTS."""
    ea, eb = list(a.events), list(b.events)
    cut_a = rng.randint(0, len(ea))
    cut_b = rng.randint(0, len(eb))
    events = tuple((ea[:cut_a] + eb[cut_b:])[:MAX_EVENTS])
    if not events:
        events = tuple(ea or eb)[:MAX_EVENTS]
    return ChaosGenome(
        seed=(a if rng.random() < 0.5 else b).seed,
        workload=a.workload, ops=a.ops, events=events)


def mutate(g: ChaosGenome, rng: random.Random,
           corpus: list | None = None) -> ChaosGenome:
    """One mutation step. With a corpus of >= 2 genomes, splice fires
    with probability 0.25; otherwise a weighted point mutator."""
    if corpus and len(corpus) >= 2 and rng.random() < 0.25:
        mate = corpus[rng.randrange(len(corpus))]
        out = splice(g, mate, rng)
        if out.key() != g.key():
            return out
    total = sum(w for _, w in MUTATORS)
    pick = rng.random() * total
    for fn, w in MUTATORS:
        pick -= w
        if pick <= 0:
            return fn(g, rng)
    return _perturb(g, rng)


# -- shrinking --------------------------------------------------------------

def shrink_reductions(g: ChaosGenome) -> Iterator[ChaosGenome]:
    """Candidate reductions, most aggressive first: drop whole events,
    then halve triggers toward their floor, then trim the op budget.
    Every candidate is strictly 'smaller'; the driver keeps one only
    if the oracle failure still reproduces."""
    if len(g.events) > 1:
        for i in range(len(g.events)):
            yield dataclasses.replace(
                g, events=g.events[:i] + g.events[i + 1:])
    for i, e in enumerate(g.events):
        lo, _hi = _event_bounds(e, g.ops)
        half = max(lo, e.at // 2)
        if half != e.at:
            yield _with_event(g, i, dataclasses.replace(e, at=half))
    if g.ops > 2 * MIN_OPS:
        yield dataclasses.replace(g, ops=max(MIN_OPS, g.ops // 2))


def genome_size(g: ChaosGenome) -> tuple:
    """The (lexicographic) size a shrink minimizes: event count, total
    trigger mass, op budget."""
    return (len(g.events), sum(e.at for e in g.events), g.ops)
