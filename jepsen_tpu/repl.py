"""Helpers for interactive analysis sessions.

Reference: `jepsen/src/jepsen/repl.clj` — load the most recent run for
post-hoc re-checking (:6-9)."""

from __future__ import annotations

from . import store


def latest_test(base: str = store.DEFAULT_BASE) -> dict | None:
    """The most recently-run test, loaded from the store with its
    history and results."""
    d = store.latest(base)
    return store.load_test(d) if d else None


def recheck(test: dict, checker=None) -> dict:
    """Re-run analysis on a stored test — the post-hoc resume path. Use
    a different checker to ask new questions of an old history."""
    from . import core
    if checker is not None:
        test = {**test, "checker": checker}
    return core.analyze(test)
