"""Test persistence: run directories, history/results serialization.

Reference: `jepsen/src/jepsen/store.clj` — runs live under
``store/<test-name>/<date>/`` with ``latest``/``current`` symlinks, a
two-phase save (history before analysis, results after), and re-loadable
histories for post-hoc analysis. This module starts minimal (paths +
save/load) and grows renderer/browser support in the reporting layer.
"""

from __future__ import annotations

import collections as _collections
import datetime as _dt
import gzip
import json
import logging
import os
import threading as _threading
import zipfile
from typing import Any, Callable, Iterable

from .history import INFO, NEMESIS, History, history

log = logging.getLogger(__name__)

DEFAULT_BASE = "store"


def base_dir(test) -> str:
    return test.get("store-dir") or DEFAULT_BASE


def dir_name(test) -> str:
    """The directory for this test run: <base>/<name>/<start-time>."""
    name = test.get("name", "noname")
    start = test.get("start-time") or "unknown"
    return os.path.join(base_dir(test), str(name), str(start))


def path(test, *components) -> str:
    """A path inside the test's store directory."""
    return os.path.join(dir_name(test), *[str(c) for c in components])


def make_path(test, *components) -> str:
    """path(), creating parent directories."""
    p = path(test, *components)
    os.makedirs(os.path.dirname(p), exist_ok=True)
    return p


def start_time() -> str:
    return _dt.datetime.now().strftime("%Y%m%dT%H%M%S.%f%z")


def update_symlinks(test) -> None:
    """Point <base>/<name>/latest and <base>/latest at this run
    (reference store.clj:316-343)."""
    d = dir_name(test)
    if not os.path.isdir(d):
        return
    for link in (os.path.join(base_dir(test), str(test.get("name", "noname")),
                              "latest"),
                 os.path.join(base_dir(test), "latest")):
        try:
            if os.path.islink(link):
                os.unlink(link)
            os.symlink(os.path.abspath(d), link)
        except OSError:
            pass


# -- serialization ----------------------------------------------------------

def _json_default(o: Any):
    import numpy as np
    if isinstance(o, (set, frozenset, tuple)):
        return list(o)
    if isinstance(o, np.integer):
        return int(o)
    if isinstance(o, np.floating):
        return float(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    if isinstance(o, BaseException):
        return {"class": type(o).__name__, "message": str(o)}
    return repr(o)


def write_history(test, hist: Iterable[dict]) -> str:
    """Write history.jsonl.gz — one op per line (replaces the reference's
    Fressian binary history, store.clj:360)."""
    p = make_path(test, "history.jsonl.gz")
    with gzip.open(p, "wt") as fh:
        for op in hist:
            fh.write(json.dumps(op, default=_json_default) + "\n")
    return p


def read_history(p: str) -> History:
    """Parse a history.jsonl.gz file."""
    with gzip.open(p, "rt") as fh:
        return history(json.loads(line) for line in fh if line.strip())


def load_history(test) -> History:
    return read_history(path(test, "history.jsonl.gz"))


# -- write-ahead op journal -------------------------------------------------
#
# Faults are injected on purpose, so the harness itself must survive
# them: a SIGKILL'd or crashed run may never reach save_1, and a lost
# history cannot be regenerated (checking always can be re-run).
# The interpreter therefore appends every history op to journal.jsonl
# as it happens; read_journal replays the surviving prefix.

JOURNAL_FLUSH_INTERVAL_S = 0.25


class Journal:
    """Append-only write-ahead log of ops, one JSON object per line.

    append() is called from the interpreter's scheduler hot path, so it
    only enqueues the op (a lock-free deque push); a background writer
    thread serializes and writes the queue every flush interval. :info
    and nemesis ops — the ops a post-mortem most needs, crashes and
    fault transitions — are drained and flushed *synchronously* on
    append. flush() pushes data to the OS, so the journal survives the
    *process* dying at any moment; it does not fsync, so a kernel panic
    may still lose the last interval's ops."""

    def __init__(self, path: str,
                 flush_interval_s: float = JOURNAL_FLUSH_INTERVAL_S):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self.path = path
        self.flush_interval_s = flush_interval_s
        self._fh = open(path, "a", buffering=64 * 1024)  # guarded-by: _io
        self._buf: _collections.deque = _collections.deque()
        self._io = _threading.Lock()
        self._closed = False
        self._wake = _threading.Event()
        self._subs: list = []           # guarded-by: _sub_lock
        # guards subscriber notification: unsubscribe() takes it too,
        # so unsubscription is SYNCHRONOUS — once it returns, no
        # callback can still be in flight (an async remove raced the
        # notify loop's list snapshot and delivered one late op)
        self._sub_lock = _threading.RLock()
        self._writer = _threading.Thread(
            target=self._write_loop, name="jepsen-journal", daemon=True)
        self._writer.start()

    def subscribe(self, fn) -> Callable[[], None]:
        """Register fn(op), called synchronously with every appended op
        (the live feed for online/streaming checkers — no disk
        round-trip, no flush-interval lag). fn runs on the appending
        thread (the interpreter's scheduler), so it must be cheap: a
        queue push, not a device dispatch. A subscriber that raises is
        dropped, loudly — a broken consumer must never abort the run.
        Returns an unsubscribe thunk. Unsubscription is synchronous:
        the thunk waits out any in-flight delivery (it must not be
        called while holding a lock the callbacks need), so after it
        returns fn will never be called again."""
        with self._sub_lock:
            self._subs.append(fn)

        def unsubscribe() -> None:
            with self._sub_lock:
                try:
                    self._subs.remove(fn)
                except ValueError:
                    pass
        return unsubscribe

    def append(self, op: dict) -> None:
        if self._closed:
            return
        with self._sub_lock:
            for fn in list(self._subs):
                if fn not in self._subs:
                    continue  # unsubscribed by an earlier callback
                try:
                    fn(op)
                except Exception:  # noqa: BLE001 — see subscribe()
                    log.warning("journal subscriber %r failed; "
                                "dropping it", fn, exc_info=True)
                    try:
                        self._subs.remove(fn)
                    except ValueError:
                        pass
        self._buf.append(op)
        if op.get("type") == INFO or op.get("process") == NEMESIS:
            self.flush()

    def flush(self) -> None:
        with self._io:
            self._drain_locked()

    def _drain_locked(self) -> None:  # holds: _io
        if self._fh is None:
            return
        try:
            while True:
                try:
                    op = self._buf.popleft()
                except IndexError:
                    break
                self._fh.write(
                    json.dumps(op, default=_json_default) + "\n")
            self._fh.flush()
        except (OSError, ValueError) as e:
            # the WAL is best-effort protection and must never abort an
            # otherwise-healthy run (a full disk would otherwise kill
            # the run from inside the scheduler). Disable journaling,
            # loudly, and let the run finish — its in-memory history
            # still reaches save_1.
            log.warning("journal %s failed (%s); disabling the "
                        "write-ahead journal for this run", self.path, e)
            try:
                self._fh.close()
            except OSError:
                pass
            self._fh = None
            self._buf.clear()

    def _write_loop(self) -> None:
        while not self._closed:
            self._wake.wait(self.flush_interval_s)
            with self._io:
                if self._fh is None:
                    return
                self._drain_locked()

    def close(self) -> None:
        self._closed = True
        self._wake.set()  # let the writer exit promptly
        with self._io:
            self._drain_locked()
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError as e:
                    log.warning("journal %s close failed: %s",
                                self.path, e)
                self._fh = None


def journal_path(test) -> str:
    return path(test, "journal.jsonl")


def open_journal(test) -> Journal | None:
    """A Journal in the test's store directory, or None when the test
    has no prepared store identity (interpreter-only runs without a
    name/start-time journal nowhere rather than littering ./store)."""
    if not (test.get("name") and test.get("start-time")):
        return None
    j = Journal(make_path(test, "journal.jsonl"))
    # a run killed before save_1 should still be `latest` for salvage
    update_symlinks(test)
    return j


def read_journal(p: str) -> History:
    """Replay a journal into a History, tolerating a torn final line (a
    crash can land mid-write; the readable prefix is still a checkable
    history). Corruption anywhere *before* the final line is real
    damage, not a torn write, and raises ValueError."""
    with open(p) as fh:
        lines = fh.read().split("\n")
    ops: list = []
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            ops.append(json.loads(line))
        except ValueError as e:
            if any(rest.strip() for rest in lines[i + 1:]):
                raise ValueError(
                    f"{p}: corrupt journal line {i + 1} "
                    f"(not the final line): {e}") from e
            break  # torn final line: keep the prefix
    return history(ops)


class JournalTail:
    """Tail-follow reader of a journal.jsonl another thread/process is
    still appending to — the out-of-process feed for online checking
    (the in-process feed is Journal.subscribe). poll() returns the ops
    whose lines have *completely* landed since the last poll; a torn
    trailing line (the writer mid-write, or mid-OS-flush) is buffered
    until the rest of it arrives, so a consumer polling a live journal
    never sees a parse error for an op that is still being written. A
    corrupt line that HAS been completed (newline present) is real
    damage and raises ValueError, mirroring read_journal.

    Idle backoff: re-polling a quiet journal at a fixed interval is
    cheap for one tail and ruinous for a service tailing hundreds of
    dormant runs. Each empty poll advances `idle_s` down
    `control.retry.backoff`'s decorrelated-jitter schedule (capped);
    any poll that returns data (or buffers a torn tail — the writer
    is mid-line, so it is NOT idle) resets it to zero. Pollers sleep
    `tail.idle_s` between polls: zero while data flows, jittered up
    to `idle_cap_s` once the run goes quiet."""

    def __init__(self, path: str, idle_base_s: float = 0.05,
                 idle_cap_s: float = 1.0, rng=None):
        self.path = path
        self._pos = 0
        self._buf = ""
        self.idle_s = 0.0
        self._idle_base_s = idle_base_s
        self._idle_cap_s = idle_cap_s
        self._rng = rng
        self._delays = None
        self._closed = False

    def close(self) -> None:
        """Retire the tail: drop the torn-line buffer and make every
        later poll() a no-op. poll() opens the journal per call (no
        persistent fd to leak), so close() exists for the CONSUMER
        side — a service dropping a finished run's tail must not race
        a concurrent poller into re-feeding buffered ops."""
        self._closed = True
        self._buf = ""
        self._delays = None
        self.idle_s = self._idle_cap_s

    def _note_idle(self, active: bool) -> None:
        if active:
            self.idle_s = 0.0
            self._delays = None
            return
        if self._delays is None:
            from .control.retry import backoff
            self._delays = backoff(self._idle_base_s,
                                   self._idle_cap_s, self._rng)
        self.idle_s = next(self._delays)

    def poll(self) -> list[dict]:
        if self._closed:
            return []
        try:
            with open(self.path) as fh:
                fh.seek(self._pos)
                data = fh.read()
                self._pos = fh.tell()
        except FileNotFoundError:
            self._note_idle(False)
            return []
        if not data:
            self._note_idle(False)
            return []
        self._note_idle(True)
        self._buf += data
        lines = self._buf.split("\n")
        self._buf = lines.pop()   # incomplete tail (or "")
        out = []
        for i, line in enumerate(lines):
            if not line.strip():
                continue
            try:
                out.append(json.loads(line))
            except ValueError as e:
                raise ValueError(
                    f"{self.path}: corrupt journal line (newline-"
                    f"terminated, so not a torn tail): {e}") from e
        return out


def load_journal(test) -> History | None:
    """The journal-backed history for a test, or None if no journal was
    ever written."""
    p = journal_path(test)
    if not os.path.exists(p):
        return None
    return read_journal(p)


# -- verification-service handoff -------------------------------------------
#
# A long-lived verification service (jepsen_tpu/service.py) owns no
# histories: the run's journal is the source of truth, and the service
# leaves its own state NEXT TO it so anyone can pick the run up —
# `analyze` reads streamed-results.json like core.run's in-memory
# streamed results, and a restarted service resumes device work from
# resume.json's carry checkpoints instead of recomputing.

SERVICE_SUBDIR = "service"
STREAMED_RESULTS_FILE = "streamed-results.json"


def _service_dir(run_dir: str) -> str:
    return os.path.join(run_dir, SERVICE_SUBDIR)


def write_streamed_results(run_dir: str, results: dict) -> str:
    """Flush a service's per-run verdicts (complete or partial) into
    the run's store directory; load_test surfaces them as
    'streamed-results' so the checkers' reuse guards see exactly what
    an in-process online run would have stashed."""
    os.makedirs(run_dir, exist_ok=True)
    p = os.path.join(run_dir, STREAMED_RESULTS_FILE)
    # tmp-then-rename (the write_service_resume idiom): this file's
    # very EXISTENCE means "verdict delivered" to recover()'s orphan
    # scan and to concurrent pollers — a torn write would read as an
    # empty verdict (found by the chaos harness's verdict poller
    # racing a shed's deferred flush)
    tmp = f"{p}.{os.getpid()}.tmp"
    with open(tmp, "w") as fh:
        json.dump(results, fh, indent=2, default=_json_default)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, p)
    return p


def load_streamed_results(run_dir: str) -> dict | None:
    p = os.path.join(run_dir, STREAMED_RESULTS_FILE)
    if not os.path.exists(p):
        return None
    with open(p) as fh:
        return json.load(fh)


def write_service_resume(run_dir: str, manifest: dict) -> str:
    """Persist a service's resume manifest for one run — at drain,
    and periodically at every carry-checkpoint cycle, so a SIGKILL'd
    daemon recovers from its last durable checkpoint. Checkpoint
    entries under manifest['checkpoints'] may carry a 'carry' list of
    arrays; they are split out into .npz files next to resume.json
    (JSON-ing device carries would be both huge and lossy) and
    rejoined by load_service_resume.

    Atomicity (the calibrate.py idiom, multiplied out for the
    json+npz pair): each carry file is written under a pid-unique tmp
    name and renamed into a chunk-versioned final name, and
    resume.json — which references the carries by those versioned
    names — is tmp-then-renamed LAST. A crash at any point leaves
    either the previous consistent (json, npz) pair or the new one,
    never a manifest pointing at a half-written carry. Stale carry
    versions are pruned only after the manifest lands."""
    import numpy as np
    d = _service_dir(run_dir)
    os.makedirs(d, exist_ok=True)
    man = dict(manifest)
    cks = {}
    fresh: set[str] = set()
    for target, ck in (manifest.get("checkpoints") or {}).items():
        ck = dict(ck)
        carry = ck.pop("carry", None)
        if carry is not None:
            safe = str(target).replace(os.sep, "_")
            fn = f"{safe}.carry.c{int(ck.get('chunks', 0))}.npz"
            tmp = os.path.join(d, f"{fn}.{os.getpid()}.tmp")
            with open(tmp, "wb") as fh:
                np.savez(fh, *[np.asarray(a) for a in carry])
            os.replace(tmp, os.path.join(d, fn))
            ck["carry-file"] = fn
            fresh.add(fn)
        cks[target] = ck
    man["checkpoints"] = cks
    p = os.path.join(d, "resume.json")
    tmp = f"{p}.{os.getpid()}.tmp"
    with open(tmp, "w") as fh:
        json.dump(man, fh, indent=2, default=_json_default)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, p)
    for fn in os.listdir(d):
        # superseded carry versions and orphaned tmps from a crashed
        # writer; the manifest's own references were just renamed in
        if (".carry." in fn or fn.endswith(".tmp")) \
                and fn not in fresh:
            try:
                os.unlink(os.path.join(d, fn))
            except OSError:
                pass
    return p


def load_service_resume(run_dir: str) -> dict | None:
    """The resume manifest for a run, with carry arrays rejoined, or
    None when no service ever checkpointed here. Mirrors
    calibrate.Calibration.load's posture on damage: a corrupt or
    truncated resume.json returns None (the stream re-checks cold
    from its journal), and a corrupt/missing carry .npz drops only
    that target's checkpoint — a bad file must never stop the
    daemon."""
    import numpy as np
    p = os.path.join(_service_dir(run_dir), "resume.json")
    if not os.path.exists(p):
        return None
    try:
        with open(p) as fh:
            man = json.load(fh)
        if not isinstance(man, dict):
            raise ValueError(f"expected a json object, got "
                             f"{type(man).__name__}")
    except (OSError, ValueError) as e:
        log.warning("%s: corrupt resume manifest (%s); the stream "
                    "will re-check cold from its journal", p, e)
        return None
    cks = man.get("checkpoints")
    if not isinstance(cks, dict):
        man["checkpoints"] = {}
        return man
    for target in list(cks):
        ck = cks[target]
        if not isinstance(ck, dict):
            del cks[target]
            continue
        fn = ck.pop("carry-file", None)
        if not fn:
            continue
        try:
            with np.load(os.path.join(_service_dir(run_dir),
                                      os.path.basename(fn))) as z:
                ck["carry"] = [
                    z[k] for k in sorted(
                        z.files, key=lambda s: int(s.split("_")[-1]))]
        except (OSError, ValueError, EOFError,
                zipfile.BadZipFile) as e:
            log.warning("%s: corrupt carry checkpoint %s for %r (%s);"
                        " that target resumes cold", p, fn, target, e)
            del cks[target]
    return man


def clear_service_resume(run_dir: str) -> None:
    """Drop a consumed resume manifest (a finished resume must not be
    resumed twice)."""
    import shutil
    d = _service_dir(run_dir)
    if os.path.isdir(d):
        shutil.rmtree(d, ignore_errors=True)


# -- store-level service epoch (replica fencing) ----------------------------
#
# One monotonic integer per store root, bumped by every service
# instance that takes ownership of the store (cold-start recovery, or
# a standby promoting over a dead primary). A fenced-out instance —
# one whose claimed epoch no longer matches the file — must stop
# persisting checkpoints and verdicts: the classic split-brain guard,
# so a zombie primary cannot clobber the new owner's state.

SERVICE_EPOCH_FILE = "service.epoch"


def service_epoch(base: str) -> int:
    """The store's current service epoch (0 when never claimed; a
    corrupt epoch file reads as 0 — claiming bumps past it)."""
    try:
        with open(os.path.join(base, SERVICE_EPOCH_FILE)) as fh:
            return int(fh.read().strip() or 0)
    except (OSError, ValueError):
        return 0


def fence_service_epoch(base: str) -> int:
    """Bump the store's service epoch (atomic tmp-then-rename) and
    return the new value — the caller now owns the store, and any
    instance still holding the previous epoch is fenced."""
    os.makedirs(base, exist_ok=True)
    epoch = service_epoch(base) + 1
    p = os.path.join(base, SERVICE_EPOCH_FILE)
    tmp = f"{p}.{os.getpid()}.tmp"
    with open(tmp, "w") as fh:
        fh.write(f"{epoch}\n")
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, p)
    return epoch


def write_results(test, results: dict) -> str:
    p = make_path(test, "results.json")
    with open(p, "w") as fh:
        json.dump(results, fh, indent=2, default=_json_default)
    return p


def load_results(test) -> dict:
    with open(path(test, "results.json")) as fh:
        return json.load(fh)


def save_1(test) -> dict:
    """Phase 1: persist the test map + history before analysis, so crashed
    analyses still leave the history on disk (reference save-1!,
    store.clj:388)."""
    write_history(test, test.get("history", []))
    meta = {k: v for k, v in test.items()
            if k not in ("history", "results") and _plain(v)}
    p = make_path(test, "test.json")
    with open(p, "w") as fh:
        json.dump(meta, fh, indent=2, default=_json_default)
    update_symlinks(test)
    return test


def save_2(test) -> dict:
    """Phase 2: persist analysis results (reference save-2!, store.clj:401)."""
    write_results(test, test.get("results", {}))
    update_symlinks(test)
    return test


def _plain(v) -> bool:
    return isinstance(v, (str, int, float, bool, list, tuple, dict,
                          type(None)))


def tests(base: str = DEFAULT_BASE) -> dict:
    """Map of test name -> {start-time -> run dir} for all stored runs
    (reference store.clj:284)."""
    out: dict = {}
    if not os.path.isdir(base):
        return out
    for name in sorted(os.listdir(base)):
        d = os.path.join(base, name)
        if not os.path.isdir(d) or name == "latest":
            continue
        runs = {t: os.path.join(d, t) for t in sorted(os.listdir(d))
                if not t.startswith("latest")
                and os.path.isdir(os.path.join(d, t))}
        if runs:
            out[name] = runs
    return out


def latest(base: str = DEFAULT_BASE) -> str | None:
    link = os.path.join(base, "latest")
    return os.path.realpath(link) if os.path.islink(link) else None


def delete(base: str = DEFAULT_BASE, name: str | None = None) -> None:
    """Delete stored runs (reference store.clj:470)."""
    import shutil
    target = os.path.join(base, name) if name else base
    if os.path.isdir(target):
        shutil.rmtree(target)


# -- logging bootstrap ------------------------------------------------------
#
# Reference store.clj:431-459 (unilog): each run logs to its own
# <dir>/jepsen.log in addition to the console, optionally as JSON.

_log_handler = None
_log_lock = _threading.Lock()

LOG_FORMAT = "%(asctime)s{%(threadName)s} %(levelname)s [%(name)s] %(message)s"


class _JsonFormatter(logging.Formatter):
    def format(self, record):
        out = {
            "time": self.formatTime(record),
            "thread": record.threadName,
            "level": record.levelname,
            "logger": record.name,
            "message": record.getMessage(),
        }
        if record.exc_info:
            out["exception"] = self.formatException(record.exc_info)
        if record.stack_info:
            out["stack"] = self.formatStack(record.stack_info)
        return json.dumps(out)


def _coerce_level(level) -> int:
    if isinstance(level, int):
        return level
    s = str(level)
    return int(s) if s.isdigit() else \
        getattr(logging, s.upper(), logging.INFO)


# levels to restore on stop: [(logger-name-or-None-for-root, level)]
_saved_levels: list = []


def start_logging(test) -> None:
    """Route the root logger into this run's jepsen.log
    (reference start-logging!, store.clj:431-453). Honors
    test['logging']['json?'] and per-logger overrides."""
    global _log_handler
    if not test.get("name"):
        return
    with _log_lock:
        stop_logging()
        opts = test.get("logging") or {}
        h = logging.FileHandler(make_path(test, "jepsen.log"))
        h.setFormatter(_JsonFormatter() if opts.get("json?")
                       else logging.Formatter(LOG_FORMAT))
        root = logging.getLogger()
        if root.level > logging.INFO or root.level == logging.NOTSET:
            _saved_levels.append((None, root.level))
            root.setLevel(logging.INFO)
        for name, level in (opts.get("overrides") or {}).items():
            logger = logging.getLogger(name)
            _saved_levels.append((name, logger.level))
            logger.setLevel(_coerce_level(level))
        root.addHandler(h)
        _log_handler = h


def stop_logging() -> None:
    global _log_handler
    if _log_handler is not None:
        logging.getLogger().removeHandler(_log_handler)
        _log_handler.close()
        _log_handler = None
    while _saved_levels:
        name, level = _saved_levels.pop()
        logging.getLogger(name).setLevel(level)


def load_test(d: str) -> dict:
    """Reconstruct a test map (with history and, when present, results)
    from a run directory — the post-hoc analysis path (reference
    store/load, store.clj:193-250).

    Salvage path: a run killed mid-history may have died before save_1,
    leaving neither test.json nor history.jsonl.gz. The test identity
    is then reconstructed from the <base>/<name>/<start-time> layout
    and the history replayed from the write-ahead journal; such tests
    carry 'salvaged-from-journal': True."""
    # realpath, not normpath: callers pass the `latest` symlink, and the
    # salvage fallback below reads name/start-time out of the path
    d = os.path.realpath(d)
    tj = os.path.join(d, "test.json")
    have_test_json = os.path.exists(tj)
    if have_test_json:
        with open(tj) as fh:
            test = json.load(fh)
    else:
        test = {"name": os.path.basename(os.path.dirname(d)),
                "start-time": os.path.basename(d)}
    hist_path = os.path.join(d, "history.jsonl.gz")
    if os.path.exists(hist_path):
        # save_1 runs pre-analysis, so the stored history carries no
        # 'index' fields; index here so index-dependent consumers
        # (timeline anchors, linearizability reports) work post-hoc
        test["history"] = read_history(hist_path).index()
    else:
        jp = os.path.join(d, "journal.jsonl")
        if os.path.exists(jp):
            log.warning("%s: no history.jsonl.gz; salvaging history "
                        "from the write-ahead journal", d)
            test["history"] = read_journal(jp).index()
            test["salvaged-from-journal"] = True
        elif not have_test_json:
            # nothing to reconstruct from — fail clearly instead of
            # fabricating an identity for a wrong/empty directory
            raise FileNotFoundError(
                f"{d}: no test.json, history.jsonl.gz, or journal.jsonl"
                " — not a test run directory")
    res_path = os.path.join(d, "results.json")
    if os.path.exists(res_path):
        with open(res_path) as fh:
            test["results"] = json.load(fh)
    sr = load_streamed_results(d)
    if sr is not None:
        # a verification service checked this run: its verdicts ride
        # the same reuse guards as core.run's in-memory streamed
        # results (analyze adopts covered targets, re-checks the rest)
        test["streamed-results"] = sr
    return test
