"""Core orchestrator: entry point for all tests.

Coordinates server setup, test execution, fault injection, and result
analysis (reference `jepsen/src/jepsen/core.clj:326-401`). A test is a
plain dict; `run(test)` takes it through the full lifecycle:

1. set up the operating system on every node,
2. teardown-then-setup the database (with primary setup if supported),
3. set up the nemesis and one client per node,
4. drive the generator through the interpreter, journaling a history
   (with test['online'], a streaming checker tails the journal and
   advances the device search *during* the run; with
   test['abort-on-violation'] a confirmed nonlinearizable prefix
   stops the run early),
5. capture DB log files,
6. tear down database and OS,
7. index the history and run the checker — on TPU for the offloaded
   checkers; a result already streamed online is reused instead of
   re-checked — writing results to the store.

The run survives its own faults the way the reference does: resources
started in parallel are unwound on partial failure (`with-resources`,
`core.clj:70-91`), logs are snarfed even when the run crashes
(`with-log-snarfing`, `core.clj:150-170`), and the history is persisted
*before* analysis so a crashed checker still leaves data on disk
(`save-1!`, `core.clj:397-398`).
"""

from __future__ import annotations

import contextlib
import logging
import threading
from typing import Any, Callable, Iterable

from . import checker as jchecker
from . import client as jclient
from . import control
from . import db as jdb
from . import nemesis as jnemesis
from . import store, util
from .control import util as cu
from .generator import interpreter
from .history import History

log = logging.getLogger(__name__)

NO_BARRIER = "no-barrier"

# Shutdown-path deadlines (seconds): faults are injected on purpose, so
# a dead node must not be able to hang teardown or log collection.
# Override per test with 'teardown-timeout' / 'snarf-timeout'.
TEARDOWN_TIMEOUT_S = 60.0
SNARF_TIMEOUT_S = 300.0

_snarf_lock = threading.Lock()


def _deadline_s(test: dict, key: str, default: float) -> float:
    v = test.get(key)
    return default if v is None else float(v)


def synchronize(test: dict, timeout_s: float = 60) -> None:
    """Block until all nodes have arrived at the same point
    (`core.clj:44-57`). Used by IO-heavy DB setup code; the default
    60 s timeout keeps one crashed thread from deadlocking the rest."""
    barrier = test.get("barrier")
    if barrier == NO_BARRIER or barrier is None:
        return
    barrier.wait(timeout=timeout_s)


def primary(test: dict) -> str:
    """The test's primary node (`core.clj:65-68`)."""
    return test["nodes"][0]


@contextlib.contextmanager
def with_resources(start: Callable, stop: Callable, resources: Iterable):
    """Start resources in parallel, yield them, and ensure all are
    stopped afterwards — including when some starts fail, in which case
    the ones that did start are stopped and the first error is raised
    (`core.clj:70-91`)."""
    resources = list(resources)

    def start1(r):
        try:
            return True, start(r)
        except Exception as e:  # noqa: BLE001 — fcatch semantics
            return False, e

    results = util.real_pmap(start1, resources)
    started = [v for ok, v in results if ok]
    errors = [v for ok, v in results if not ok]

    def stop_all():
        def stop1(r):
            try:
                stop(r)
            except Exception as e:  # noqa: BLE001
                log.warning("error stopping resource: %s", e)
        util.real_pmap(stop1, started)

    if errors:
        stop_all()
        raise errors[0]
    try:
        yield started
    finally:
        stop_all()


@contextlib.contextmanager
def with_os(test: dict):
    """OS setup on entry, teardown on exit (`core.clj:93-100`)."""
    os = test["os"]
    control.on_nodes(test, os.setup)
    try:
        yield test
    finally:
        control.on_nodes(test, os.teardown)


def _short_paths(full_paths: list[str]) -> dict[str, str]:
    """Map full remote paths to their shortest unambiguous suffixes:
    the common *proper* directory prefix is dropped, so a lone file
    keeps its basename (`util/drop-common-proper-prefix`)."""
    if not full_paths:
        return {}
    split = [p.split("/") for p in full_paths]
    prefix = util.longest_common_prefix(split)
    # proper prefix: never swallow an entire path
    n = min(len(prefix), min(len(s) for s in split) - 1)
    return {p: "/".join(s[n:]) for p, s in zip(full_paths, split)}


def snarf_logs(test: dict) -> None:
    """Download DB log files for each node into the store directory and
    refresh symlinks (`core.clj:102-136`). Downloads run under a
    'snarf-timeout' deadline: this is shutdown-path code, and a dead
    node's hung sftp must not wedge the run that was busy killing it.
    _snarf_lock is taken *inside* the deadlined thread, so an abandoned
    (timed out but still downloading) snarf keeps excluding the next
    one — two snarfs interleaving into the same local files is exactly
    what the lock exists to prevent."""
    db = test["db"]
    if jdb.supports(db, "log-files") and test.get("sessions"):
        log.info("Snarfing log files")

        def snarf1(test, node):
            full_paths = list(db.log_files(test, node) or [])
            for remote, local in _short_paths(full_paths).items():
                if cu.exists(remote):
                    dest = store.make_path(
                        test, str(node), local.lstrip("/"))
                    log.info("downloading %s to %s", remote, dest)
                    try:
                        control.download(remote, dest)
                    except OSError as e:
                        log.info("%s: %s", remote, e)

        def snarf_all():
            with _snarf_lock:
                control.on_nodes(test, snarf1)

        t_s = _deadline_s(test, "snarf-timeout", SNARF_TIMEOUT_S)
        if util.timeout(t_s, snarf_all,
                        default=util.TIMED_OUT) is util.TIMED_OUT:
            log.warning("log snarfing still running after %ss; "
                        "abandoning it and continuing shutdown", t_s)
    if test.get("name"):
        # racing an abandoned snarf is fine: update_symlinks tolerates
        # concurrent callers (symlink errors pass)
        store.update_symlinks(test)


def maybe_snarf_logs(test: dict) -> None:
    """Snarf logs, swallowing all errors — used on the abort path where
    a snarfing error must not supersede the root cause
    (`core.clj:138-148`)."""
    try:
        snarf_logs(test)
    except Exception:  # noqa: BLE001
        log.warning("Error snarfing logs and updating symlinks",
                    exc_info=True)


@contextlib.contextmanager
def with_log_snarfing(test: dict):
    """Evaluate body and ensure logs are snarfed afterwards, on success
    and on crash alike (`core.clj:150-170`)."""
    try:
        yield test
        snarf_logs(test)
    finally:
        maybe_snarf_logs(test)


@contextlib.contextmanager
def with_db(test: dict):
    """DB cycle (teardown+setup, with retries) on entry; teardown on
    exit unless `leave-db-running?` (`core.clj:172-181`)."""
    try:
        with with_log_snarfing(test):
            jdb.cycle(test)
            yield test
    finally:
        if not test.get("leave-db-running?"):
            control.on_nodes(test, test["db"].teardown)


def _spawn(fn, box: list, name: str) -> threading.Thread:
    """Run fn on a daemon thread, capturing ('ok', value) or ('err', e)
    into box. Daemon (not a pool worker) so an abandoned hang can never
    block interpreter exit."""
    def run():
        try:
            box.append(("ok", fn()))
        except BaseException as e:  # noqa: BLE001 — surfaced via box
            box.append(("err", e))

    t = threading.Thread(target=run, name=name, daemon=True)
    t.start()
    return t


@contextlib.contextmanager
def with_client_nemesis_setup_teardown(test: dict):
    """Set up the nemesis (concurrently) and one client per node before
    the body; tear them all down after (`core.clj:183-212`). The set-up
    nemesis replaces test['nemesis'] so the interpreter drives the
    initialized instance.

    Teardown runs under 'teardown-timeout' deadlines: client teardown,
    client close, and nemesis teardown are each bounded, so one dead
    node can't hang the shutdown path (the hung call is abandoned per
    util.timeout semantics and logged)."""
    client = test["client"]
    nemesis = jnemesis.validate(test["nemesis"])
    t_s = _deadline_s(test, "teardown-timeout", TEARDOWN_TIMEOUT_S)

    def open1(node):
        c = client.open(test, node)
        c.setup(test)
        return c

    nbox: list = []
    nth = _spawn(lambda: nemesis.setup(test), nbox, "jepsen nemesis")
    try:
        clients = util.real_pmap(open1, test["nodes"])
    except BaseException:
        # wait out an in-flight nemesis setup before propagating, so
        # the enclosing teardown never runs concurrently with it
        nth.join()
        if nbox and nbox[0][0] == "err":
            log.warning("nemesis setup also failed: %s", nbox[0][1])
        raise
    nth.join()
    tag, val = nbox[0]
    if tag == "err":
        raise val
    test = {**test, "nemesis": val or nemesis}
    try:
        yield test
    finally:
        tbox: list = []
        tth = _spawn(lambda: test["nemesis"].teardown(test), tbox,
                     "jepsen nemesis teardown")

        def close1(c):
            try:
                if util.timeout(t_s, lambda: c.teardown(test),
                                default=util.TIMED_OUT) is util.TIMED_OUT:
                    log.warning("client teardown timed out after %ss; "
                                "abandoning it", t_s)
            finally:
                if util.timeout(t_s, lambda: c.close(test),
                                default=util.TIMED_OUT) is util.TIMED_OUT:
                    log.warning("client close timed out after %ss; "
                                "abandoning it", t_s)

        try:
            util.real_pmap(close1, clients)
        finally:
            tth.join(t_s)
            if tth.is_alive():
                log.warning("nemesis teardown still running after %ss; "
                            "abandoning it", t_s)
            elif tbox and tbox[0][0] == "err":
                raise tbox[0][1]


def run_case(test: dict) -> History:
    """Spawn nemesis and clients, run the generator, return the history
    (`core.clj:214-219`)."""
    with with_client_nemesis_setup_teardown(test) as test:
        return interpreter.run(test)


def _salvage_journal(test: dict) -> None:
    """Persist the journal-backed history prefix when the run dies
    before its normal save_1 — checking can always be re-run, but a
    lost history cannot be regenerated. Never raises: the root-cause
    exception is already on its way up."""
    if not test.get("name"):
        return
    try:
        part = store.load_journal(test)
        if part is None:
            return
        done = {k: v for k, v in test.items()
                if k not in ("barrier", "sessions")}
        done["history"] = part
        log.warning("run crashed with %d journaled ops (%d pending "
                    "invocations); writing salvaged history",
                    len(part), len(part.pending()))
        store.save_1(done)
    except Exception:  # noqa: BLE001 — must not mask the root cause
        log.warning("failed to salvage journal-backed history",
                    exc_info=True)


def analyze(test: dict) -> dict:
    """Index the history, run the checker, persist results
    (`core.clj:221-236`)."""
    log.info("Analyzing...")
    test = {**test, "history": History(test["history"]).index()}
    test = {**test,
            "results": jchecker.check_safe(test["checker"], test,
                                           test["history"])}
    log.info("Analysis complete")
    if test.get("name"):
        store.save_2(test)
    return test


def log_results(test: dict) -> dict:
    """Log the results and a verdict (`core.clj:238-251`)."""
    results = test.get("results", {})
    verdict = {
        False: "Analysis invalid! (ﾉಥ益ಥ）ﾉ ┻━┻",
        jchecker.UNKNOWN: ("Errors occurred during analysis, "
                           "but no anomalies found. ಠ~ಠ"),
        True: "Everything looks good! ヽ('ー`)ノ",
    }.get(results.get("valid?"), "")
    err = results.get("error")
    log.info("%s%s\n\n%s", _pstr(results),
             f"\n\n{err}" if err else "", verdict)
    # partial degradation (a checker exhausted its recovery ladder —
    # its verdict is missing) is a different outcome from full
    # recovery (every verdict present; the device faulted en route)
    deg = results.get("degraded-checkers") or \
        (["results"] if results.get("degraded") else [])
    rec = results.get("recovered-checkers") or \
        (["results"]
         if isinstance(results.get("recovered"), dict) else [])
    if deg:
        log.warning("analysis DEGRADED: %s lost their device verdict "
                    "to backend faults past the recovery budget",
                    sorted(deg))
    elif rec:
        from . import report
        detail = "; ".join(filter(None, (
            report.recovery_line(results if k == "results"
                                 else results.get(k))
            for k in sorted(rec))))
        log.info("analysis recovered from backend faults (%s); all "
                 "verdicts are complete%s", sorted(rec),
                 f" — {detail}" if detail else "")
    # tiered verification: which verdicts came from the O(n) screen
    # alone vs escalated to the full search, and which device results
    # carried (passing) ABFT attestation
    scr = results.get("screened-checkers") or \
        (["results"] if results.get("screened")
         and not results.get("escalated") else [])
    esc = results.get("escalated-checkers") or \
        (["results"]
         if isinstance(results.get("escalated"), dict) else [])
    att = results.get("attested-checkers") or \
        (["results"]
         if isinstance(results.get("attested"), dict) else [])
    if scr or esc:
        from . import report
        detail = "; ".join(filter(None, (
            report.tier_line(results if k == "results"
                             else results.get(k))
            for k in sorted(set(scr) | set(esc)))))
        log.info("tier-1 verification: %d screened, %d escalated%s",
                 len(scr), len(esc), f" — {detail}" if detail else "")
    if att:
        log.info("ABFT attestation passed on %s", sorted(att))
    from . import report
    tl = report.telemetry_line(results)
    if tl:
        log.info(tl)
    return test


def _pstr(x: Any, indent: int = 0) -> str:
    pad = " " * indent
    if isinstance(x, dict):
        if not x:
            return "{}"
        lines = [f"{pad} {k!r}: {_pstr(v, indent + 1).lstrip()}"
                 for k, v in x.items()]
        return "{\n" + ",\n".join(lines) + "}"
    return pad + repr(x)


@contextlib.contextmanager
def with_sessions(test: dict):
    """Bind the test's remote + SSH options, open a session to every
    node in parallel, and yield the test with a node→session map under
    'sessions' (`core.clj:274-294`)."""
    with control.with_remote(test.get("remote")), \
            control.with_ssh(test.get("ssh") or {}):
        with with_resources(control.bound_fn(control.session),
                            control.disconnect,
                            test["nodes"]) as sessions:
            yield {**test,
                   "sessions": dict(zip(test["nodes"], sessions))}


@contextlib.contextmanager
def with_logging(test: dict):
    """Per-test log capture into the store directory; crashes are
    logged so they land in the test's own log file
    (`core.clj:296-308`)."""
    store.start_logging(test)
    try:
        log.info("Running test: %s %s", test.get("name"),
                 test.get("start-time"))
        yield test
    except BaseException:
        log.warning("Test crashed!", exc_info=True)
        raise
    finally:
        store.stop_logging()


def prepare_test(test: dict) -> dict:
    """Ensure start-time, concurrency, and barrier fields; required
    before accessing the test's store directory (`core.clj:310-324`).
    Validates the node list: a duplicated node would open two control
    sessions to the same host and only surface much later as a
    port-bind error on the node, so it fails HERE with a clear
    message (the CLI's parse_nodes applies the same rule to --node/
    --nodes/--nodes-file; this covers programmatically-built tests)."""
    test = dict(test)
    nodes = list(test.get("nodes") or [])
    dupes = sorted({n for n in nodes if nodes.count(n) > 1})
    if dupes:
        raise ValueError(
            f"test 'nodes' lists node(s) more than once: "
            f"{', '.join(str(n) for n in dupes)} — each node gets one "
            f"control session and one client; a duplicate would only "
            f"fail later as a bind error on the node")
    if not test.get("start-time"):
        test["start-time"] = store.start_time()
    if not test.get("concurrency"):
        test["concurrency"] = len(test.get("nodes") or [])
    if not test.get("barrier"):
        n = len(test.get("nodes") or [])
        test["barrier"] = threading.Barrier(n) if n > 0 else NO_BARRIER
    test.setdefault("os", _default_os())
    test.setdefault("db", jdb.noop)
    test.setdefault("client", jclient.noop)
    test.setdefault("nemesis", jnemesis.noop)
    test.setdefault("checker", jchecker.unbridled_optimism())
    return test


def _default_os():
    from . import os_ as jos
    return jos.noop


def run(test: dict) -> dict:
    """Run a test end to end and return it with 'history' and 'results'
    (`core.clj:326-401`). See the module docstring for the lifecycle;
    the docstring of the reference `run!` documents the test-map keys,
    which this accepts unchanged (string keys)."""
    test = prepare_test(test)
    with with_logging(test):
        with with_sessions(test) as stest:
            with with_os(stest), with_db(stest):
                oc = _maybe_online(stest)
                if oc is not None:
                    stest = {**stest, "online-checker": oc}
                with util.relative_time():
                    try:
                        hist = run_case(stest)
                    except BaseException:
                        # the journal-backed prefix is still written
                        # even when the run itself dies
                        if oc is not None:
                            oc.close()
                        _salvage_journal(stest)
                        raise
                # strip run-state the analysis/persistence layers must
                # not see (reference dissoc, core.clj:393-395)
                done = {k: v for k, v in stest.items()
                        if k not in ("barrier", "sessions",
                                     "online-checker")}
                done["history"] = hist
                if oc is not None:
                    streamed = oc.finalize()
                    if streamed:
                        done["streamed-results"] = streamed
                        finished = sorted(set(streamed)
                                          - {"degraded", "error",
                                             "ladder"})
                        if streamed.get("degraded"):
                            # targets WITH a streamed verdict keep it;
                            # the crash cost the ones without, and the
                            # offline re-check path covers exactly those
                            lost = sorted(set(oc.targets) -
                                          set(finished))
                            log.warning(
                                "Online checker degraded (driver "
                                "crashed); falling through to the "
                                "offline re-check path for %s",
                                lost or "no targets (all verdicts "
                                        "streamed before the crash)")
                        else:
                            log.info("Online verification finished %s "
                                     "during the run", finished)
                    if oc.aborted:
                        done["aborted-on-violation"] = True
                log.info("Run complete, writing")
                if done.get("name"):
                    store.save_1(done)
            done = analyze(done)
        return log_results(done)


def _maybe_online(test: dict):
    """The streaming/online checker for a test that asked for one, or
    None — never raises: online checking is an optimization and its
    setup failing must not kill the run. A test with a 'service'
    address (CLI --service) attaches to the persistent verification
    service instead of spawning an in-process OnlineChecker; a
    refused/unreachable service falls back to the local online path
    when the test also asked for 'online', else to plain offline."""
    try:
        if test.get("service"):
            from . import service as _service
            sc = _service.maybe_attach(test)
            if sc is not None:
                return sc
            if not test.get("online"):
                return None
        from .checker import streaming
        return streaming.maybe_online(test)
    except Exception:  # noqa: BLE001
        log.warning("online verification setup failed; running "
                    "offline only", exc_info=True)
        return None
