"""The persistent verification service: many runs, one device.

Per-run checking (offline `analyze`, or the per-run `OnlineChecker`)
ties a checker's lifetime to a run's. A serving deployment inverts
that: one long-lived daemon owns the device, and many concurrent runs
hand it their journal streams — over a local socket (`jepsen-tpu
service`, `run --service ADDR`), or by the service tail-following
journals under a store directory (`--watch`). P-compositionality
(arXiv 1504.00204) is why multiplexing wins: histories decompose into
many small independent projections, so scheduling thousands of small
streams beats one giant search — and the per-stream machinery already
exists (`WglStream`/`WrStream`/`ScreenStream` online checkers, the
recovery ladder, carry checkpoints). This module is the serving layer
that makes them safe to share:

  * **Per-stream fault isolation.** Each stream runs on its own
    worker; a classified backend fault climbs that stream's
    `_RecoveryTrail` and restores its own carry checkpoint (the
    machinery inside `WglStream`) without stalling siblings, and an
    *unclassified* exception quarantines only that stream
    (``degraded`` with the error attached) — the journal remains, so
    offline `analyze` still covers it.
  * **Cost-model scheduling.** Chunk dispatch across streams flows
    through a global element-op budget priced by `wgl.select_engine`:
    each stream's chunk acquires its modeled cost before dispatching,
    so cheap streams interleave freely while an expensive one cannot
    monopolize the device.
  * **Admission control + OOM-aware backpressure.** Per-stream op
    queues are bounded; attach is refused past ``max_streams``; a
    stream whose queue stays saturated past ``shed_timeout_s`` is
    *shed* — it gets a ``deferred`` verdict (written into its run's
    store dir) and offline `analyze` picks it up from its journal.
    Any stream's OOM fault halves the global budget (restored
    gradually by clean chunks), so one stream's memory pressure
    throttles the whole service before the backend does.
  * **Graceful drain.** SIGTERM (or `drain()`) stops admissions,
    checkpoints every stream's carry, and writes a resume manifest +
    partial verdicts into each run's store dir
    (`store.write_service_resume`); a restarted service `resume()`s
    from the checkpoints — the journal re-feeds the encoder, dispatch
    skips row-for-row up to the restored carry, and the final verdict
    is identical to an uninterrupted service's (pinned by
    tests/test_service.py).
  * **Status.** `status()` (socket ``{"type": "status"}`` — the
    /healthz shape) reports per-stream state, queue depths, recovery
    and attestation-failure counts, and the budget level.

Stream lifecycle::

    admitted ──▶ streaming ──▶ verdict
                    │ ▲
         ┌──────────┼─┴─ recovering (stream's own ladder; siblings
         │          │                unaffected)
         │          ├──▶ quarantined (unclassified exception;
         │          │                 'degraded' + error)
         │          ├──▶ shed        (backpressure; 'deferred',
         │          │                 analyze covers from journal)
         │          └──▶ drained     (SIGTERM; checkpoint + manifest,
         │                            resume() continues to verdict)
         └─ admission refused (saturated): never admitted, run falls
            back to its local online/offline checking
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import queue as _queue
import signal as _signal
import socket as _socket
import threading
import time as _time
import traceback
import zlib
from collections import deque
from typing import Callable

from . import calibrate as _calibrate, reconnect as _reconnect, store, \
    telemetry as _telemetry, trace as _trace
from ._platform import probe as _probe

log = logging.getLogger(__name__)

# -- telemetry (doc/observability.md catalogs these) -------------------------
_M_EVENTS = _telemetry.counter(
    "jepsen_tpu_service_stream_events_total",
    "Stream lifecycle events (admitted / refused / shed / "
    "quarantined / drained / verdict / resumed)", ("event",))
_M_ACTIVE = _telemetry.gauge(
    "jepsen_tpu_service_active_streams",
    "Streams currently admitted and not yet terminal")
_M_QUEUE = _telemetry.histogram(
    "jepsen_tpu_service_queue_rows",
    "Per-stream op-queue depth, observed at each pump",
    buckets=(0, 16, 64, 256, 1024, 4096, 16384, 65536))
_M_OPS = _telemetry.counter(
    "jepsen_tpu_service_ops_total",
    "Journal ops fed into stream workers")
_M_BUDGET_CAP = _telemetry.gauge(
    "jepsen_tpu_service_budget_capacity_seconds",
    "ChunkBudget capacity in priced device-seconds (AIMD: cut "
    "multiplicatively on OOM/latency blowout, restored additively)")
_M_BUDGET_AVAIL = _telemetry.gauge(
    "jepsen_tpu_service_budget_available_seconds",
    "ChunkBudget device-seconds currently available")
_M_OOMS = _telemetry.counter(
    "jepsen_tpu_service_budget_ooms_total",
    "OOM backpressure events that halved the global budget")
_M_CUTS = _telemetry.counter(
    "jepsen_tpu_service_budget_cuts_total",
    "AIMD multiplicative capacity cuts by triggering signal",
    ("signal",))
_M_PRIO = _telemetry.counter(
    "jepsen_tpu_service_priority_grants_total",
    "Budget grants by scheduling priority class (suspect streams "
    "acquire ahead of clean ones under contention)", ("priority",))
_M_LADDER = _telemetry.counter(
    "jepsen_tpu_service_ladder_transitions_total",
    "Degradation-ladder transitions by direction and destination tier",
    ("direction", "tier"))
_M_TIER = _telemetry.gauge(
    "jepsen_tpu_service_ladder_streams",
    "Live streams per degradation-ladder tier", ("tier",))
_M_VERB = _telemetry.histogram(
    "jepsen_tpu_service_verb_seconds",
    "Socket verb handling latency", ("verb",))
_M_RECOVERIES = _telemetry.counter(
    "jepsen_tpu_service_recoveries_total",
    "Streams resumed by recover() after an ungraceful death, by "
    "resume source (checkpoint = durable carry restored, cold = "
    "journal re-check from scratch)", ("how",))
_M_REPLAYS = _telemetry.counter(
    "jepsen_tpu_service_replays_total",
    "Duplicate session ops deduplicated by the server-side sequence "
    "table (at-least-once delivery, exactly-once application)")
_M_RECONNECTS = _telemetry.counter(
    "jepsen_tpu_service_reconnects_total",
    "Session re-attaches after a dropped connection, by side",
    ("side",))
_M_FAILOVERS = _telemetry.counter(
    "jepsen_tpu_service_failovers_total",
    "Replica failovers: standby promotions and client address-list "
    "failovers", ("role",))

_KNOWN_VERBS = frozenset(
    {"op", "attach", "poll", "finish", "status", "metrics", "close"})

# the socket layer's line cap: a single journal op is a few hundred
# bytes; anything near this is garbage or an attack on the reader's
# memory, and gets an error reply instead of an allocation
MAX_LINE_BYTES = 1 << 20
# client acks: every Nth op asks the server for its sequence
# high-water mark so the replay buffer stays bounded
ACK_EVERY = 64
# reconnect attempts (each cycles the whole address list) before the
# client declares the service gone and falls back to offline checking
RECONNECT_TRIES = 8
# standby failover defaults: consecutive failed health probes (at
# poll_s cadence) before the standby fences the primary and promotes
DEFAULT_STANDBY_POLL_S = 1.0
DEFAULT_STANDBY_FAILURES = 3

# stream lifecycle states (see module docstring)
ADMITTED = "admitted"
STREAMING = "streaming"
RECOVERING = "recovering"
QUARANTINED = "quarantined"
SHED = "shed"
DRAINED = "drained"
VERDICT = "verdict"

# degradation-ladder tiers (doc/robustness.md: the overload ladder).
# Orthogonal to the lifecycle states above: a streaming stream sits at
# exactly one tier; climbing trades verification depth for device time
# and never loses a definite violation (screens keep running at every
# tier, and a suspect stream descends to full immediately).
TIER_FULL = 0           # all targets pump normally
TIER_SAMPLED = 1        # device chunks only for suspect/sampled streams
TIER_SCREEN = 2         # O(n) screens only; device verdict deferred
TIER_SHED = 3           # shed-to-offline (the pre-existing last rung)
TIER_NAMES = ("full", "sampled-escalation-only", "screen-only", "shed")

DEFAULT_MAX_STREAMS = 64
DEFAULT_QUEUE_OPS = 50_000
DEFAULT_SHED_TIMEOUT_S = 2.0
# idle sessions older than this are swept even while the table is
# small — a reconnecting client past the TTL re-attaches fresh (its
# unacked tail replays; the worker is long done, so ops drop at offer)
SESSION_TTL_S = 600.0
# global in-flight device budget, in select_engine-modeled element-ops
# (~a dozen default-shape sort chunks); acquire clamps to capacity so
# a single over-budget chunk always eventually dispatches. The budget
# itself runs in priced device-seconds — element-ops convert through
# the calibration (measured coefficients when known, the nominal
# constant otherwise, so uncalibrated scheduling is unchanged: costs
# and capacity scale by the same constant).
DEFAULT_BUDGET_ELEMENTOPS = 1e9
# -- AIMD budget constants (doc/robustness.md documents the policy) --
BUDGET_FLOOR_FRACTION = 1 / 64.0    # capacity never cut below this
BUDGET_RESTORE_STEP = 0.02      # additive restore per clean chunk,
#                                 as a fraction of max capacity
BUDGET_HYSTERESIS_S = 5.0       # after a cut: no restore, and no
#                                 further latency cut, for this long
BUDGET_BLOWOUT_P95_S = 5.0      # p95 chunk latency that cuts capacity
BUDGET_RESTORE_SLOW_FRACTION = 0.5  # clean chunks between the
#                                 low-latency bar and this fraction of
#                                 blowout restore at HALF step — slow
#                                 re-open beats permanent halving
BUDGET_RESTORE_LATENCY_FRACTION = 0.25  # "low-latency" chunk bar for
#                                 restore, as a fraction of blowout
BUDGET_AGING_S = 2.0            # a waiter older than this reserves
#                                 capacity (cheap chunks stop bypassing)
BUDGET_LATENCY_WINDOW = 64      # rolling chunk-latency window for p95
BUDGET_HUNGRY_ROWS = 4096       # queue-depth EWMA past which clean
#                                 chunks restore at double step
# -- ladder controller defaults --
LADDER_TICK_S = 0.25
LADDER_CLIMB_HOLD_S = 2.0       # sustained overload before one climb
LADDER_DESCEND_HOLD_S = 6.0     # sustained calm before one descend
#                                 (descend > climb: transition hysteresis)
# deterministic sampled-escalation fraction for ladder tier 1 (keyed
# on the stream name, so a re-admitted run makes the same choice)
LADDER_SAMPLE = 0.25
# clean chunks between budget re-pricings of a stream's chunk cost —
# the cadence at which a converging calibration reaches the scheduler
REPRICE_EVERY_CHUNKS = 32

# kernel identities whose first execution (= the jit compile for that
# shape) some stream in this process already paid: only the ONE
# builder stream per shape has a compile-tainted first chunk, so every
# other stream's chunk-0 sample is a legitimate execution measurement
_CAL_SEEN_LOCK = threading.Lock()
_CAL_KERNELS_SEEN: set = set()      # guarded-by: _CAL_SEEN_LOCK


def _kernel_already_run(key) -> bool:
    """True if a stream in this process already ran this jitted
    kernel; marks it run otherwise."""
    with _CAL_SEEN_LOCK:
        if key in _CAL_KERNELS_SEEN:
            return True
        if len(_CAL_KERNELS_SEEN) > 4096:   # id()s of a 32-entry LRU:
            _CAL_KERNELS_SEEN.clear()       # bounded churn, cheap reset
        _CAL_KERNELS_SEEN.add(key)
        return False

_SEAL = object()
_CLOSE = object()


class AdmissionRefused(Exception):
    """The service refused a new stream (saturated or draining)."""


# ---------------------------------------------------------------------------
# serializable target specs (client builds, service rebuilds)
# ---------------------------------------------------------------------------

def _jsonable(v):
    if isinstance(v, (set, frozenset)):
        return sorted(v)
    if isinstance(v, tuple):
        return list(v)
    return v


def model_spec(model) -> dict:
    """A wire-serializable description of a device-model instance
    (the registered models are flat dataclasses)."""
    d: dict = {"class": type(model).__name__}
    if dataclasses.is_dataclass(model):
        d["fields"] = {f.name: _jsonable(getattr(model, f.name))
                       for f in dataclasses.fields(model)}
    return d


def model_from_spec(spec: dict):
    from . import models
    cls = getattr(models, spec.get("class", ""), None)
    if not (isinstance(cls, type) and issubclass(cls, models.Model)):
        raise ValueError(f"unknown model class {spec.get('class')!r}")
    kw = {}
    fields = spec.get("fields") or {}
    for f in dataclasses.fields(cls):
        if f.name in fields:
            v = fields[f.name]
            if "frozenset" in str(f.type) and isinstance(v, list):
                v = frozenset(v)
            kw[f.name] = v
    return cls(**kw)


def targets_spec(test: dict) -> dict:
    """The serializable stream-target spec for a test — the same
    checker walk `streaming.maybe_online` does, minus the stream
    construction (the service builds the streams on its side)."""
    from .checker import screen as _screen
    from .checker.elle import RWRegisterChecker
    from .checker.linear import Linearizable
    from .checker.streaming import (DEFAULT_CHECKPOINT_EVERY,
                                    DEFAULT_CHUNK_ENTRIES,
                                    _walk_checkers)

    specs: dict = {}
    tiered = _screen.tier_is_screen(test.get("tier"))
    for c in _walk_checkers(test.get("checker")):
        if tiered and isinstance(c, Linearizable) \
                and "screen-linear" not in specs:
            specs["screen-linear"] = {"kind": "screen",
                                      "model": model_spec(c.model)}
        if tiered and isinstance(c, RWRegisterChecker) \
                and not c.additional_graphs \
                and "screen-wr" not in specs:
            specs["screen-wr"] = {"kind": "screen-wr",
                                  "anomalies": sorted(c.anomalies)}
        if isinstance(c, Linearizable) and "linear" not in specs:
            if c.model.device_model is None or c.algorithm == "host":
                continue
            srange = test.get("online-state-range")
            specs["linear"] = {
                "kind": "wgl",
                "model": model_spec(c.model),
                "frontier": c.opts.get("frontier", 256),
                "max-frontier": c.opts.get("max_frontier", 65536),
                "chunk-entries": test.get("online-chunk-entries",
                                          DEFAULT_CHUNK_ENTRIES),
                "engine": "auto" if srange else "sort",
                "state-range": (list(srange) if srange else None),
                "concurrency-hint": test.get("concurrency"),
                "pallas": c.opts.get("pallas"),
                "checkpoint-every": test.get("online-checkpoint-every",
                                             DEFAULT_CHECKPOINT_EVERY),
                "max-recovery-retries": test.get("max-recovery-retries"),
            }
        elif isinstance(c, RWRegisterChecker) \
                and "elle-wr" not in specs:
            if c.additional_graphs:
                continue
            specs["elle-wr"] = {"kind": "wr",
                                "anomalies": sorted(c.anomalies)}
    return specs


def build_targets(spec: dict, stream_name: str = "",
                  overrides: dict | None = None) -> dict:
    """Instantiate stream workers from a targets spec. WGL streams are
    built service-schedulable (auto_pump=False; the worker pumps under
    the budget) with a per-stream fault site
    (``stream-chunk/<name>``) so faults inject and account per
    stream. `overrides` maps target name -> kernel-shape overrides
    from a resume checkpoint (slots/chunk/frontier/pallas/engine must
    match the exported carry)."""
    from .checker import screen as _screen
    from .checker.streaming import (DEFAULT_CHECKPOINT_EVERY,
                                    DEFAULT_CHUNK_ENTRIES, WglStream,
                                    WrStream)

    out: dict = {}
    for name, ts in spec.items():
        kind = ts.get("kind")
        if kind == "wgl":
            ov = (overrides or {}).get(name) or {}
            srange = ov.get("state-range", ts.get("state-range"))
            out[name] = WglStream(
                model_from_spec(ts["model"]),
                slots=ov.get("p", ts.get("slots")),
                frontier=ov.get("frontier", ts.get("frontier", 256)),
                max_frontier=ts.get("max-frontier", 65536),
                chunk_entries=ov.get("chunk",
                                     ts.get("chunk-entries",
                                            DEFAULT_CHUNK_ENTRIES)),
                engine=ov.get("engine", ts.get("engine", "sort")),
                state_range=(tuple(srange) if srange else None),
                concurrency_hint=ts.get("concurrency-hint"),
                pallas=ov.get("pallas", ts.get("pallas")),
                checkpoint_every=ts.get("checkpoint-every",
                                        DEFAULT_CHECKPOINT_EVERY),
                max_recovery_retries=ts.get("max-recovery-retries"),
                auto_pump=False,
                fault_site=(f"stream-chunk/{stream_name}"
                            if stream_name else "stream-chunk"),
            )
        elif kind == "wr":
            out[name] = WrStream(anomalies=ts.get("anomalies"))
        elif kind == "screen":
            out[name] = _screen.ScreenStream(
                model_from_spec(ts["model"]))
        elif kind == "screen-wr":
            out[name] = _screen.WrScreen(anomalies=ts.get("anomalies"))
        else:
            raise ValueError(f"unknown target kind {kind!r}")
    return out


# ---------------------------------------------------------------------------
# the global chunk budget (cost-model scheduling + OOM backpressure)
# ---------------------------------------------------------------------------

class _Waiter:
    """One blocked acquirer; entitlement orders grants (priority
    first, FIFO within a priority class)."""

    __slots__ = ("priority", "seq", "need", "t0")

    def __init__(self, priority: int, seq: int, need: float,
                 t0: float):
        self.priority = priority
        self.seq = seq
        self.need = need
        self.t0 = t0

    def entitlement(self) -> tuple:
        return (self.priority, -self.seq)


class ChunkBudget:
    """A self-tuning weighted semaphore over priced device-seconds:
    each stream acquires its chunk's cost (modeled element-ops priced
    through the calibration, see `chunk_cost`) before dispatching.
    Cheap chunks interleave many-at-a-time; an expensive stream
    serializes against the budget instead of monopolizing the device.

    **AIMD capacity.** An OOM anywhere halves capacity immediately
    (safety first — no hysteresis on memory pressure); a p95
    chunk-latency blowout halves it too (at most once per
    ``hysteresis_s``). Clean low-latency chunks restore capacity
    *additively* (``restore_step`` of max per chunk; doubled while
    queues run deep — an over-cut hungry system re-opens faster), but
    never within ``hysteresis_s`` of a cut and never past the
    configured max. The floor clamp keeps one chunk always
    dispatchable. ``adaptive=False`` freezes capacity except for the
    pre-existing OOM halving/restore (the bench A/B lever).

    **Priority.** ``acquire(priority=1)`` (suspect streams) grants
    ahead of priority 0 under contention. Grants are work-conserving:
    a cheap waiter may bypass a more-entitled one whose cost does not
    fit *yet* — until that waiter has aged past ``aging_s``, at which
    point capacity is reserved for it (no bypass starvation in either
    direction; pinned by tests/test_adaptive.py)."""

    def __init__(self, capacity: float = DEFAULT_BUDGET_ELEMENTOPS
                 * _calibrate.NOMINAL_SECONDS_PER_ELEMENTOP,
                 *, adaptive: bool = True,
                 blowout_s: float = BUDGET_BLOWOUT_P95_S,
                 hysteresis_s: float = BUDGET_HYSTERESIS_S,
                 restore_step: float = BUDGET_RESTORE_STEP,
                 aging_s: float = BUDGET_AGING_S):
        self.initial = float(capacity)      # the AIMD ceiling
        self.floor = self.initial * BUDGET_FLOOR_FRACTION
        self.adaptive = bool(adaptive)
        self.blowout_s = float(blowout_s)
        self.hysteresis_s = float(hysteresis_s)
        self.restore_step = float(restore_step)
        self.aging_s = float(aging_s)
        self.capacity = float(capacity)     # guarded-by: _cv
        # outstanding granted cost: availability is DERIVED as
        # capacity - _out, so AIMD capacity moves (cuts and restores)
        # are spendable immediately — a stored available-pool would
        # conserve the post-cut pool and never see the restore
        self._out = 0.0                     # guarded-by: _cv
        self._cv = threading.Condition()
        self.ooms = 0                       # guarded-by: _cv
        self.cuts = 0                       # guarded-by: _cv
        self._waiters: list = []            # guarded-by: _cv
        self._seq = 0                       # guarded-by: _cv
        self._lat: deque = deque(
            maxlen=BUDGET_LATENCY_WINDOW)   # guarded-by: _cv
        self._last_cut = float("-inf")      # guarded-by: _cv
        self._qdepth = 0.0                  # guarded-by: _cv
        _M_BUDGET_CAP.set(self.capacity)
        _M_BUDGET_AVAIL.set(self.capacity)

    def _avail_locked(self) -> float:  # holds: _cv
        """Spendable device-seconds; negative while an over-capacity
        chunk is in flight or after a cut undercuts outstanding work."""
        return self.capacity - self._out

    def _grantable(self, w: _Waiter, now: float) -> bool:  # holds: _cv
        avail = self._avail_locked()
        # relative tolerance: the ledger accumulates float residue the
        # old clamped pool absorbed, and ~1e-16 of leftover _out must
        # not block a waiter needing exactly the full capacity
        eps = 1e-12 * (self.capacity + self._out)
        if avail < min(w.need, self.capacity) - eps:
            return False
        w_aged = now - w.t0 > self.aging_s
        for o in self._waiters:
            if o is w:
                continue
            o_aged = now - o.t0 > self.aging_s
            if o.entitlement() > w.entitlement() \
                    and avail >= min(o.need, self.capacity) - eps:
                return False    # a more-entitled waiter fits: it first
            if o_aged and (not w_aged or (o.t0, o.seq)
                           < (w.t0, w.seq)):
                # an aged waiter reserves capacity against EVERY
                # younger arrival, suspects included — otherwise a
                # steady suspect load starves a clean stream forever;
                # aged waiters drain among themselves in ARRIVAL
                # order (a strict total order, so no two aged waiters
                # ever block each other), which bounds every class's
                # wait instead of re-starving the less entitled
                return False
        return True

    def acquire(self, cost: float, timeout_s: float | None = None,
                cancel: Callable[[], bool] | None = None,
                priority: int = 0) -> bool:
        cost = max(float(cost), 1e-9)
        deadline = (None if timeout_s is None
                    else _time.monotonic() + timeout_s)
        with self._cv:
            self._seq += 1
            w = _Waiter(int(priority), self._seq, cost,
                        _time.monotonic())
            self._waiters.append(w)
            try:
                while not self._grantable(w, _time.monotonic()):
                    if cancel is not None and cancel():
                        return False
                    wait = 0.1
                    if deadline is not None:
                        wait = min(wait, deadline - _time.monotonic())
                        if wait <= 0:
                            return False
                    self._cv.wait(wait)
                self._out += cost
                _M_BUDGET_AVAIL.set(max(0.0, self._avail_locked()))
                _M_PRIO.labels(priority=str(int(priority))).inc()
                return True
            finally:
                self._waiters.remove(w)
                # a grant/give-up can unblock a DIFFERENT waiter (the
                # entitlement head just left): re-check promptly
                self._cv.notify_all()

    def release(self, cost: float, clean: bool = True,
                seconds: float | None = None) -> None:
        """Return a chunk's cost; `seconds` is its observed device
        latency — the AIMD restore/cut signal."""
        cost = max(float(cost), 1e-9)
        with self._cv:
            now = _time.monotonic()
            if seconds is not None:
                self._lat.append(float(seconds))
                if self.adaptive and len(self._lat) >= 8 \
                        and now - self._last_cut >= self.hysteresis_s:
                    p95 = self._p95_locked()
                    if p95 is not None and p95 > self.blowout_s:
                        self._cut_locked("latency", now)
            low_latency = seconds is None or seconds <= \
                self.blowout_s * BUDGET_RESTORE_LATENCY_FRACTION
            mid_latency = seconds is not None and not low_latency \
                and seconds <= self.blowout_s * \
                BUDGET_RESTORE_SLOW_FRACTION
            if clean and (low_latency or mid_latency) \
                    and self.capacity < self.initial \
                    and now - self._last_cut >= self.hysteresis_s:
                step = self.initial * self.restore_step
                if mid_latency:
                    step *= 0.5   # healthy-but-unhurried chunks
                    #               (between the bars) re-open slowly:
                    #               a fleet whose normal latency sits
                    #               there must not stay halved forever
                elif self._qdepth > BUDGET_HUNGRY_ROWS:
                    step *= 2   # deep queues + clean fast chunks:
                    #             the cut overshot, re-open faster
                self.capacity = min(self.initial,
                                    self.capacity + step)
            self._out = max(0.0, self._out - cost)
            _M_BUDGET_CAP.set(self.capacity)
            _M_BUDGET_AVAIL.set(max(0.0, self._avail_locked()))
            self._cv.notify_all()

    def _cut_locked(self, signal: str, now: float) -> None:  # holds: _cv
        self.capacity = max(self.floor, self.capacity / 2)
        self._last_cut = now
        self.cuts += 1
        _M_CUTS.labels(signal=signal).inc()
        _M_BUDGET_CAP.set(self.capacity)
        _M_BUDGET_AVAIL.set(max(0.0, self._avail_locked()))

    def note_oom(self) -> None:
        """Memory pressure cuts immediately, hysteresis or not — the
        alternative is the backend OOM-killing every stream."""
        with self._cv:
            self.ooms += 1
            self._cut_locked("oom", _time.monotonic())
            _M_OOMS.inc()
            self._cv.notify_all()

    def note_queue_depth(self, rows: int) -> None:
        with self._cv:
            self._qdepth = 0.9 * self._qdepth + 0.1 * float(rows)

    def _p95_locked(self) -> float | None:  # holds: _cv
        if not self._lat:
            return None
        lat = sorted(self._lat)
        return lat[int(0.95 * (len(lat) - 1))]

    def signals(self) -> dict:
        """The overload signals the ladder controller reads (one lock
        round-trip)."""
        with self._cv:
            return {"waiters": len(self._waiters),
                    "capacity": self.capacity,
                    "initial": self.initial,
                    "available": max(0.0, self._avail_locked()),
                    "p95_latency_s": self._p95_locked(),
                    "queue_depth_ewma": self._qdepth,
                    "recent_cut": (_time.monotonic() - self._last_cut
                                   < self.hysteresis_s)}

    def status(self) -> dict:
        with self._cv:
            return {"unit": "device-seconds",
                    "initial": self.initial,
                    "capacity": self.capacity,
                    "available": max(0.0, self._avail_locked()),
                    "floor": self.floor,
                    "adaptive": self.adaptive,
                    "ooms": self.ooms,
                    "cuts": self.cuts,
                    "waiters": len(self._waiters),
                    "p95-chunk-latency-s": self._p95_locked(),
                    "queue-depth-ewma": round(self._qdepth, 1)}


@dataclasses.dataclass
class ChunkPrice:
    """One chunk's price for a WGL stream: modeled element-ops from
    `wgl.select_engine` at the stream's actual kernel shape, priced
    into device-seconds through the calibration."""
    cost: float         # device-seconds (budget units)
    elementops: float   # modeled element-ops for one chunk
    variant: str        # dense | sort | hash | unpriced
    reason: str


def chunk_cost(stream, calibration=None) -> ChunkPrice:
    from .checker import wgl
    srange = stream.state_range or (0, 3)   # undeclared: nominal S=4
    try:
        eng = stream.engine if stream.engine in ("dense", "sort") \
            else "auto"
        dec = wgl.select_engine(tuple(srange), stream.p, stream.chunk,
                                slots=stream.p,
                                frontier=stream.frontier,
                                pallas=stream.pallas, engine=eng,
                                calibration=calibration)
        ops = wgl.engine_cost(dec)
        variant, reason = wgl.engine_variant(dec), dec.reason
    except Exception:  # noqa: BLE001 — pricing is advisory
        ops, variant, reason = 1e6, "unpriced", "unpriced"
    return ChunkPrice(_calibrate.price(calibration, variant, ops),
                      ops, variant, reason)


# ---------------------------------------------------------------------------
# one stream's worker
# ---------------------------------------------------------------------------

class StreamWorker:
    """One admitted run's verification: a bounded op queue, its stream
    targets, and a dedicated thread that feeds/pumps them. All device
    faults stay inside this worker: classified ones climb the
    stream's own recovery ladder, unclassified ones quarantine the
    worker."""

    def __init__(self, name: str, spec: dict, service: "VerificationService",
                 store_dir: str | None = None,
                 overrides: dict | None = None):
        self.name = name
        self.spec = spec
        self.service = service
        self.store_dir = store_dir
        self.state = ADMITTED
        self.q: _queue.Queue = _queue.Queue(maxsize=service.queue_ops)
        self.targets = build_targets(spec, stream_name=name,
                                     overrides=overrides)
        self.target_names = sorted(self.targets)
        self._final_chunks: dict = {}
        self._final_attest_failures = 0
        self.results: dict = {}
        self.error: str | None = None
        self.done = threading.Event()
        self._term_lock = threading.Lock()
        self._terminated = False        # guarded-by: _term_lock
        self.violation = False
        self.ops_fed = 0
        self.recoveries = 0
        self.shed_reason: str | None = None
        self._drain = threading.Event()
        self._dead_targets: set[str] = set()
        # durable periodic checkpoints (worker thread only): the last
        # persisted per-target checkpoint_seq snapshot, plus a flag
        # forcing one persist right after admission — a SIGKILL before
        # the first carry checkpoint must still leave a manifest so
        # recover() resumes the stream cold, no drain required
        self._persisted_seqs: dict[str, int] = {}
        self._persist_pending = bool(store_dir)
        self._costs = {n: chunk_cost(t, service.calibration)
                       for n, t in self.targets.items()
                       if hasattr(t, "pending_chunks")}
        # -- degradation-ladder / suspicion-priority scheduling state.
        # Written by the worker thread AND the service ladder thread,
        # read by status()/socket threads — all through the methods
        # below, under _tier_lock.
        self._tier_lock = threading.Lock()
        self.tier = TIER_FULL               # guarded-by: _tier_lock
        self.max_tier = TIER_FULL           # guarded-by: _tier_lock
        self.tier_transitions = 0           # guarded-by: _tier_lock
        self._tier_frozen = False           # guarded-by: _tier_lock
        self.suspicion_score = 0.0          # guarded-by: _tier_lock
        from .checker import screen as _screen
        # deterministic per-stream sample for the sampled-escalation
        # tier (same Knuth hash the tier-1 audit sampling uses)
        self._sampled = _screen.sample_decision(
            zlib.crc32(name.encode()), LADDER_SAMPLE)
        # a stream is *suspect* at the tier-1 escalation bar, not at
        # any nonzero suspicion: soft signals (crashed mutators, 0.02
        # each capped 0.5) ride nearly every realistic history — below
        # the bar they must neither outrank siblings nor pin a stream
        # to tier-full, or priority and the ladder both degenerate
        self._suspect_bar = _screen.ESCALATE_THRESHOLD
        self._pumped = 0    # clean chunks pumped (worker thread only)
        # per-target chunks pumped IN THIS PROCESS (worker thread
        # only) — `t._chunks` survives a checkpoint resume, so it
        # cannot tell a restarted daemon's compile-paying first chunk
        # from a warm one
        self._pumped_by: dict[str, int] = {}
        # targets whose chunk 0 compiled the kernel: their lagged
        # warm==1 latency sample is compile, not execution
        # (worker thread only)
        self._cal_skip: set = set()
        self.thread = threading.Thread(
            target=self._run, name=f"jepsen-service-{name}",
            daemon=True)

    # -- degradation ladder + suspicion-priority metadata --------------------

    def current_tier(self) -> int:
        with self._tier_lock:
            return self.tier

    def set_tier(self, tier: int, why: str) -> bool:
        """One ladder transition (idempotent). Climbing to TIER_SHED
        sheds the stream (the pre-existing terminal rung). Refused
        once the verdict's ladder stamp is cut (_finish): a climb
        after that would show in status() but not in the verdict."""
        with self._tier_lock:
            if self._tier_frozen:
                return False
            old = self.tier
            if tier == old:
                return False
            self.tier = tier
            self.max_tier = max(self.max_tier, tier)
            self.tier_transitions += 1
        with self.service._lock:
            self.service.ladder_transitions_total += 1
        direction = "climb" if tier > old else "descend"
        _M_LADDER.labels(direction=direction,
                         tier=TIER_NAMES[tier]).inc()
        log.log(logging.WARNING if tier > old else logging.INFO,
                "service %s: ladder %s %s -> %s (%s)", self.name,
                direction, TIER_NAMES[old], TIER_NAMES[tier], why)
        if tier == TIER_SHED:
            self.shed(f"degradation ladder: {why}")
        return True

    def refresh_suspicion(self) -> float:
        """Pull the targets' live suspicion into the scheduling
        metadata. A stream that turns suspect is prioritized for
        device time and — safety beats hysteresis — descends to
        tier-full immediately."""
        targets = self.targets   # snapshot: _release_targets swaps it
        s = 0.0
        for n, t in targets.items():
            if n in self._dead_targets:
                continue
            if getattr(t, "violation", False):
                s = max(s, 1.0)
            try:
                s = max(s, float(getattr(t, "suspicion", 0.0) or 0.0))
            except (TypeError, ValueError):
                pass
        with self._tier_lock:
            was, self.suspicion_score = self.suspicion_score, s
        suspect = s >= self._suspect_bar
        if suspect and was < self._suspect_bar:
            _M_EVENTS.labels(event="prioritized").inc()
        if suspect and self.current_tier() in (TIER_SAMPLED,
                                               TIER_SCREEN):
            self.set_tier(TIER_FULL, "suspicion")
        return s

    def scheduling_priority(self) -> int:
        with self._tier_lock:
            return 1 if self.suspicion_score >= self._suspect_bar \
                else 0

    def device_cost(self) -> float:
        """This stream's priced per-chunk device cost — the ladder
        climbs the most expensive clean stream first (shedding a cheap
        screen-heavy stream frees almost nothing)."""
        return sum(p.cost for p in self._costs.values())

    def _device_allowed(self) -> bool:
        """May this stream's device (WGL) targets dispatch chunks at
        its current tier? Screens always run — they are fed, not
        pumped."""
        with self._tier_lock:
            tier, susp = self.tier, self.suspicion_score
        if tier == TIER_FULL:
            return True
        if tier == TIER_SAMPLED:
            return susp >= self._suspect_bar or self._sampled
        return False

    def _terminal(self, event: str) -> None:
        """Mark the worker done, counting the terminal lifecycle event
        exactly once (the first transition wins; a shed racing a drain
        across threads still counts a single terminal event)."""
        with self._term_lock:
            first, self._terminated = not self._terminated, True
        if first:
            _M_EVENTS.labels(event=event).inc()
            _M_ACTIVE.dec()
            _probe("lifecycle", stream=self.name, state=self.state,
                   cause=event)
            # terminal streams free their service-side residue NOW —
            # the session token/high-water mark (no client can resume
            # a finished stream onto a live worker) and the journal
            # tail's poll slot (its fd would otherwise wait for the
            # next watcher pass)
            self.service._stream_terminal(self.name)
        self.done.set()

    # -- worker thread -----------------------------------------------------

    def _run(self) -> None:
        try:
            self._loop()
        except BaseException:  # noqa: BLE001 — thread boundary
            self._quarantine(traceback.format_exc())
        finally:
            # a long-lived daemon serves thousands of runs: once this
            # worker is terminal, its streams' step logs and staging
            # buffers (the whole history, in int32 rows) must not
            # outlive it — snapshot the status detail, drop the rest
            self._release_targets()

    def _release_targets(self) -> None:
        self._final_chunks = self._chunk_status()
        self._final_attest_failures = self._attest_failures()
        # shed/quarantine can leave ops queued (only the _loop bleed
        # branch drains them, and a quarantine raises past it): drop
        # them here so a terminal worker never pins a full queue of
        # op dicts for the daemon's life
        self._bleed_queue()
        for t in self.targets.values():
            # shed/drained/quarantined streams never reach finish():
            # record their root trace spans before dropping them, or
            # their exported chunk spans orphan in the collector
            if hasattr(t, "end_trace"):
                t.end_trace()
        self.targets = {}
        self._dead_targets = set()

    def _attest_failures(self) -> int:
        if not self.targets:
            return self._final_attest_failures
        return sum(
            sum(1 for k in getattr(t, "faults", []) if k == "corrupt")
            for t in self.targets.values())

    def _chunk_status(self) -> dict:
        out = dict(self._final_chunks)
        for name, t in self.targets.items():
            if hasattr(t, "pending_chunks"):
                price = self._costs.get(name)
                out[name] = {
                    "dispatched": getattr(t, "_chunks", 0),
                    "pending": (t.pending_chunks()
                                if name not in self._dead_targets
                                else 0),
                    "chunk-syncs": getattr(t, "_chunk_syncs", 0),
                    "resumed-from-chunk": getattr(
                        t, "_resumed_from_chunk", None),
                    "cost-per-chunk": price.cost if price else None,
                    "elementops-per-chunk": (price.elementops
                                             if price else None),
                    "variant": price.variant if price else None,
                    "engine-reason": price.reason if price else "",
                }
        return out

    def _loop(self) -> None:
        sealed = False
        while True:
            if self._drain.is_set():
                self._do_drain()
                return
            if self.state in (SHED, QUARANTINED):
                self._bleed_queue()
                return
            try:
                item = self.q.get(timeout=0.05)
            except _queue.Empty:
                item = None
            fed = 0
            while item is not None:
                if item is _CLOSE:
                    self.state = SHED
                    self.shed_reason = "client closed"
                    self._terminal("shed")
                    return
                if item is _SEAL:
                    sealed = True
                    break
                self._feed(item)
                fed += 1
                if fed >= 4096:
                    break   # let the pump keep up with a firehose
                try:
                    item = self.q.get_nowait()
                except _queue.Empty:
                    break
            if fed:
                _M_OPS.inc(fed)   # one batched inc per drain burst
            self.refresh_suspicion()
            self._pump()
            self._note_violation()
            self._maybe_persist()
            if sealed and self.q.empty():
                self._finish()
                return

    def _feed(self, op: dict) -> None:
        if self.state == ADMITTED:
            self.state = STREAMING
            _probe("lifecycle", stream=self.name, state=STREAMING)
        self.ops_fed += 1
        for name, t in self.targets.items():
            if name in self._dead_targets:
                continue
            try:
                t.feed(op)
            except Exception as e:  # noqa: BLE001 — containment
                # a target whose *feed* (host-side encode) breaks is
                # dropped like OnlineChecker does; offline covers it.
                # The whole worker quarantines only on errors with no
                # such containment (thread boundary above).
                log.warning("service %s: target %r failed at feed "
                            "(%s); offline checking covers it",
                            self.name, name, e, exc_info=True)
                self._dead_targets.add(name)
        self._note_violation()

    def _note_violation(self) -> None:
        """Copy the targets' violation flags up (screens flip at feed,
        WGL streams flip at a chunk sync inside pump — check after
        both)."""
        if not self.violation and any(
                getattr(t, "violation", False)
                for n, t in self.targets.items()
                if n not in self._dead_targets):
            self.violation = True

    def _pump(self) -> None:
        """Dispatch pending chunks under the global budget — the
        cost-model scheduling point. One chunk per acquire, so other
        streams' acquires interleave between our chunks. Suspicion
        sets the acquire priority; the degradation ladder gates
        whether device chunks dispatch at all (screens are fed, not
        pumped — they run at every tier)."""
        _M_QUEUE.observe(self.q.qsize())
        self.service.budget.note_queue_depth(self.q.qsize())
        for name, t in self.targets.items():
            if name in self._dead_targets \
                    or not hasattr(t, "pending_chunks"):
                continue
            while t.pending_chunks() > 0 and not self._drain.is_set():
                if not self._device_allowed():
                    # deferred by the ladder; chunks stay pending (a
                    # descend or finish-time suspicion re-opens them)
                    return
                price = self._costs.get(name)
                if price is None:
                    price = self._costs[name] = \
                        chunk_cost(t, self.service.calibration)
                if not self.service.budget.acquire(
                        price.cost, timeout_s=5.0,
                        cancel=self._drain.is_set,
                        priority=self.scheduling_priority()):
                    break
                n0 = len(t.faults)
                clean = True
                t0 = _time.monotonic()
                try:
                    t.pump(1)
                except Exception:  # noqa: BLE001 — unclassified
                    self.service.budget.release(price.cost,
                                                clean=False)
                    raise
                dt = _time.monotonic() - t0
                new = t.faults[n0:]
                if new:
                    clean = False
                    self.recoveries += len(new)
                    self.state = RECOVERING
                    _probe("lifecycle", stream=self.name,
                           state=RECOVERING, faults=list(new))
                    if any(k == "oom" for k in new):
                        self.service.budget.note_oom()
                    # the stream re-priced itself (OOM halves its
                    # chunk, compile drops pallas): re-price the chunk
                    self._costs[name] = chunk_cost(
                        t, self.service.calibration)
                else:
                    # feed the measured cost model. The stream's
                    # liveness sync lags one chunk, so pump k's dt
                    # measures chunk k-1: pump 0 (blocks on the init
                    # carry, measures nothing) never feeds, pump 1
                    # (measures chunk 0) feeds unless THIS stream's
                    # chunk 0 paid the shape's jit compile, and
                    # unpriced targets never feed
                    self._pumped += 1
                    warm = self._pumped_by.get(name, 0)
                    self._pumped_by[name] = warm + 1
                    if warm == 0:
                        kk = getattr(t, "kernel_key", lambda: None)()
                        if kk is not None and \
                                not _kernel_already_run(kk):
                            self._cal_skip.add(name)
                    elif price.variant != "unpriced" and not (
                            warm == 1 and name in self._cal_skip):
                        self.service.calibration.observe(
                            price.variant, price.elementops, dt)
                    if self._pumped % REPRICE_EVERY_CHUNKS == 0:
                        # calibration converges while we pump: re-price
                        # so the budget charge tracks measured seconds
                        self._costs[name] = chunk_cost(
                            t, self.service.calibration)
                self.service.budget.release(price.cost, clean=clean,
                                            seconds=dt)
            if self.state == RECOVERING:
                self.state = STREAMING
                _probe("lifecycle", stream=self.name, state=STREAMING,
                       recovered=True)

    def _finish(self) -> None:
        # last suspicion pull before the verdict: a stream that turned
        # suspect descends to tier-full (refresh_suspicion) and its
        # pending device chunks run after all — safety beats the ladder
        self.refresh_suspicion()
        self._note_violation()
        with self._tier_lock:
            tier, max_tier = self.tier, self.max_tier
        defer_device = not self._device_allowed()
        out: dict = {}
        for name, t in self.targets.items():
            if name in self._dead_targets:
                continue
            if defer_device and hasattr(t, "pending_chunks") \
                    and t.pending_chunks() > 0:
                # the ladder held this stream's device chunks back and
                # nothing ever looked suspect: defer the device verdict
                # to offline checking (no "valid?" key -> the checkers'
                # streamed-results reuse guard skips it) instead of
                # pumping a whole history at seal time under overload.
                # A target with NOTHING pending finished its device
                # work before the climb — its verdict is already paid
                # for, so finish() keeps it
                out[name] = {"deferred": True,
                             "reason": f"degradation ladder: "
                                       f"{TIER_NAMES[tier]}",
                             "ladder-tier": TIER_NAMES[tier],
                             "history-len": self.ops_fed}
                _M_EVENTS.labels(event="device-verdict-deferred").inc()
                continue
            try:
                r = t.finish()
            except RuntimeError:
                # finish runs its own recovery ladder inside the
                # stream; an escape here is unclassified
                self._quarantine(traceback.format_exc())
                return
            if r is not None:
                r.setdefault("history-len", self.ops_fed)
                out[name] = r
        # stamp degraded-tier verdicts so they are distinguishable
        # from full ones. Streams that stayed at tier-full carry NO
        # stamp: their verdicts remain byte-identical to solo runs.
        # Re-read max_tier here, NOT the pre-pump snapshot: the
        # controller can climb this stream while finish() pumps its
        # pending chunks, and status() would then report a max-tier
        # the verdict didn't carry. Freezing the tier under the same
        # lock closes the other half of that race (a climb between
        # this stamp and done.set()).
        with self._tier_lock:
            self._tier_frozen = True
            if self.max_tier > TIER_FULL:
                out["ladder"] = {
                    "tier": TIER_NAMES[self.tier],
                    "max-tier": TIER_NAMES[self.max_tier],
                    "transitions": self.tier_transitions,
                }
        self.results = out
        self.state = VERDICT
        if self.store_dir and not self.service.fenced():
            try:
                store.write_streamed_results(self.store_dir, out)
                store.clear_service_resume(self.store_dir)
            except OSError:
                log.warning("service %s: could not flush verdicts to "
                            "%s", self.name, self.store_dir,
                            exc_info=True)
        self._terminal("verdict")

    def _quarantine(self, tb: str) -> None:
        """Unclassified failure: this stream is done, degraded, with
        the error attached — and ONLY this stream (the journal is
        intact; offline analyze covers it)."""
        self.error = tb
        self.state = QUARANTINED
        self.results = dict(self.results)
        self.results["degraded"] = True
        self.results["error"] = tb
        log.warning("service %s: quarantined on unclassified error; "
                    "siblings unaffected\n%s", self.name, tb)
        self._terminal("quarantined")

    def _bleed_queue(self) -> None:
        try:
            while True:
                self.q.get_nowait()
        except _queue.Empty:
            pass

    def _maybe_persist(self) -> None:
        """Durable periodic checkpoints: whenever a target stored a
        fresh carry checkpoint since the last persist (its
        ``checkpoint_seq`` moved — every ``checkpoint_every`` cycle),
        atomically persist the exported carries + journal offset +
        attestation tallies into the run's store dir. A SIGKILL then
        recovers from the last persisted checkpoint instead of
        re-checking cold — no drain manifest required."""
        if not self.store_dir:
            return
        seqs = {n: t.checkpoint_seq for n, t in self.targets.items()
                if n not in self._dead_targets
                and hasattr(t, "checkpoint_seq")}
        if not self._persist_pending and seqs == self._persisted_seqs:
            return
        if self._persist_checkpoints():
            self._persisted_seqs = seqs
            self._persist_pending = False

    def _export_checkpoints(self) -> dict:
        """Every live target's exportable checkpoint (WGL carries plus
        host streams' progress markers); a target whose export breaks
        is left out — it resumes cold from the journal."""
        checkpoints: dict = {}
        for name, t in self.targets.items():
            if name in self._dead_targets \
                    or not hasattr(t, "export_checkpoint"):
                continue
            try:
                ck = t.export_checkpoint()
            except Exception:  # noqa: BLE001 — persist is best-effort
                log.warning("service %s: could not export %r's "
                            "checkpoint; it will resume cold",
                            self.name, name, exc_info=True)
                continue
            if ck is not None:
                checkpoints[name] = ck
        return checkpoints

    def _persist_checkpoints(self,
                             checkpoints: dict | None = None) -> bool:
        """Write the resume manifest atomically (tmp-then-rename in
        store.write_service_resume) into the run's store dir — unless
        this service has been fenced out of the store by a promoted
        standby, whose recovered state must win over a zombie's late
        writes."""
        if self.service.fenced():
            return False
        if checkpoints is None:
            checkpoints = self._export_checkpoints()
        try:
            store.write_service_resume(self.store_dir, {
                "stream": self.name,
                "targets": self.spec,
                "ops-fed": self.ops_fed,
                "journal-offset": self.ops_fed,
                "epoch": self.service.epoch,
                "checkpoints": checkpoints,
            })
            return True
        except OSError:
            log.warning("service %s: could not persist the resume "
                        "manifest", self.name, exc_info=True)
            return False

    def _do_drain(self) -> None:
        """Checkpoint every WGL target at the exact drain point and
        persist the resume manifest + any partial verdicts into the
        run's store dir."""
        for name, t in self.targets.items():
            if name in self._dead_targets \
                    or not hasattr(t, "checkpoint_now"):
                continue
            try:
                t.checkpoint_now()
            except Exception:  # noqa: BLE001 — drain is best-effort
                log.warning("service %s: checkpoint of %r failed at "
                            "drain; it resumes from its last periodic "
                            "checkpoint", self.name, name,
                            exc_info=True)
        if self.store_dir:
            self._persist_checkpoints()
            if self.results and not self.service.fenced():
                try:
                    store.write_streamed_results(self.store_dir,
                                                 self.results)
                except OSError:
                    log.warning("service %s: could not persist "
                                "partial verdicts", self.name,
                                exc_info=True)
        self.state = DRAINED
        self._terminal("drained")

    # -- service-side API --------------------------------------------------

    def offer(self, op: dict, timeout_s: float) -> bool:
        """Enqueue an op; False (and the stream sheds) when the queue
        stayed full past timeout_s — the admission-control
        backpressure rung."""
        if self.state in (SHED, QUARANTINED, DRAINED):
            return False
        try:
            self.q.put(op, timeout=timeout_s)
            return True
        except _queue.Full:
            self.shed("backpressure: op queue full "
                      f"({self.service.queue_ops}) for {timeout_s}s")
            return False

    def seal(self) -> None:
        self.q.put(_SEAL)

    def shed(self, reason: str) -> None:
        if self.state in (VERDICT, QUARANTINED, DRAINED, SHED):
            return
        self.shed_reason = reason
        self.state = SHED
        log.warning("service %s: shed (%s); offline analyze covers "
                    "it from the journal", self.name, reason)
        if self.store_dir and not self.service.fenced():
            try:
                store.write_streamed_results(
                    self.store_dir,
                    {"deferred": True, "reason": reason})
            except OSError:
                pass
        self._terminal("shed")

    def status(self) -> dict:
        with self._tier_lock:
            tier, max_tier = self.tier, self.max_tier
            transitions = self.tier_transitions
            suspicion = self.suspicion_score
        st = {
            "state": self.state,
            "queue-depth": self.q.qsize(),
            "ops-fed": self.ops_fed,
            "violation": self.violation,
            "recoveries": self.recoveries,
            "attest-failures": self._attest_failures(),
            "targets": self.target_names,
            "dead-targets": sorted(self._dead_targets),
            "ladder-tier": TIER_NAMES[tier],
            "ladder-max-tier": TIER_NAMES[max_tier],
            "tier-transitions": transitions,
            "suspicion": suspicion,
            "priority": (1 if suspicion >= self._suspect_bar else 0),
        }
        chunks = self._chunk_status()
        if chunks:
            st["chunks"] = chunks
        if self.shed_reason:
            st["shed-reason"] = self.shed_reason
        if self.error:
            st["error"] = self.error.splitlines()[-1]
        return st


# ---------------------------------------------------------------------------
# the service
# ---------------------------------------------------------------------------

class _Session:
    """One client session's server-side wire-protocol state: the
    sequence high-water mark that turns at-least-once delivery into
    exactly-once application. Every field is guarded by the service's
    ``_session_lock`` (the table's own lock — see __init__)."""

    __slots__ = ("token", "seq", "replays", "journal_fed", "touched")

    def __init__(self, token: str, journal_fed: bool = False):
        self.token = token          # the client's opaque identity
        self.seq = 0                # highest op sequence applied
        self.replays = 0            # duplicate ops dropped
        # a journal-fed stream is driven by the store tail (recover or
        # watch); socket ops would double-apply and are dropped
        self.journal_fed = journal_fed
        # last attach/apply (monotonic) — the TTL sweep's idle clock
        self.touched = _time.monotonic()


class VerificationService:
    """See the module docstring. In-process API first (admit / offer /
    seal / result / shed / drain / resume / status); `serve()` exposes
    it over a local socket, `watch()` tails a store directory."""

    def __init__(self, max_streams: int = DEFAULT_MAX_STREAMS,
                 queue_ops: int = DEFAULT_QUEUE_OPS,
                 shed_timeout_s: float = DEFAULT_SHED_TIMEOUT_S,
                 budget_elementops: float = DEFAULT_BUDGET_ELEMENTOPS,
                 calibration: "_calibrate.Calibration | None" = None,
                 adaptive: bool = True,
                 ladder_tick_s: float = LADDER_TICK_S,
                 ladder_climb_hold_s: float = LADDER_CLIMB_HOLD_S,
                 ladder_descend_hold_s: float = LADDER_DESCEND_HOLD_S,
                 session_ttl_s: float = SESSION_TTL_S):
        self.max_streams = max_streams
        self.queue_ops = queue_ops
        self.shed_timeout_s = shed_timeout_s
        # every service calibrates a private cost model from its own
        # chunk latencies (the daemon passes the persisted one in and
        # saves it back at drain — calibration_path); budget capacity
        # converts through the same nominal constant the uncalibrated
        # pricing uses, so static scheduling is unchanged
        self.calibration = (calibration if calibration is not None
                            else _calibrate.Calibration())
        self.calibration_path: str | None = None
        self.budget = ChunkBudget(
            budget_elementops
            * _calibrate.NOMINAL_SECONDS_PER_ELEMENTOP,
            adaptive=adaptive)
        self.adaptive = bool(adaptive)
        self.ladder_tick_s = float(ladder_tick_s)
        self.ladder_climb_hold_s = float(ladder_climb_hold_s)
        self.ladder_descend_hold_s = float(ladder_descend_hold_s)
        self._ladder_stop = threading.Event()
        self._ladder_thread: threading.Thread | None = None  # guarded-by: _lock
        # overload/calm onset timestamps (ladder thread only)
        self._overload_t: float | None = None
        self._calm_t: float | None = None
        self.workers: dict[str, StreamWorker] = {}  # guarded-by: _lock
        # finished workers kept (newest last) for late status/result
        # queries; older ones are reaped so a long-lived daemon's
        # worker table stays bounded
        self.keep_done = 64
        self.draining = False           # guarded-by: _lock
        self.drained = threading.Event()
        self.admitted_total = 0         # guarded-by: _lock
        self.refused_total = 0          # guarded-by: _lock
        # monotonic across the daemon's whole life: summing per-worker
        # counts would go BACKWARDS when finished workers are reaped
        self.ladder_transitions_total = 0   # guarded-by: _lock
        self.t0 = _time.monotonic()
        self._lock = threading.Lock()
        self._server: _socket.socket | None = None
        self._server_threads: list[threading.Thread] = []
        self._watch_stop = threading.Event()
        self._watcher: threading.Thread | None = None
        # run_dir -> (tail, name); shared by resume()/watch() callers
        # and the watcher thread
        self._tails: dict[str, tuple] = {}      # guarded-by: _lock
        self._finished_dirs: set[str] = set()   # guarded-by: _lock
        # -- session table (the session-resilient wire protocol).
        # Its own lock, always taken sequentially with _lock, never
        # nested inside it (the JTS202 order discipline).
        self._session_lock = threading.Lock()
        self._sessions: dict[str, _Session] = {}  # guarded-by: _session_lock
        self.session_ttl_s = float(session_ttl_s)
        # -- crash consistency / replica failover state. claim_store
        # runs before any worker exists (single-threaded start or
        # standby promotion), so epoch/store_root need no lock; _fenced
        # is a monotonic False->True flag like ServiceClient._closed.
        self.store_root: str | None = None
        self.epoch = 0
        self._fenced = False
        self.recovered_total = 0        # guarded-by: _lock

    # -- admission ---------------------------------------------------------

    def admit(self, name: str, spec: dict,
              store_dir: str | None = None,
              overrides: dict | None = None) -> StreamWorker:
        self.fenced()   # a fenced-out instance flips itself draining
        with self._lock:
            if self.draining:
                self.refused_total += 1
                _M_EVENTS.labels(event="refused").inc()
                raise AdmissionRefused("service is draining")
            active = sum(1 for w in self.workers.values()
                         if not w.done.is_set())
            if active >= self.max_streams:
                self.refused_total += 1
                _M_EVENTS.labels(event="refused").inc()
                raise AdmissionRefused(
                    f"saturated: {active} active streams "
                    f"(max {self.max_streams})")
            if name in self.workers \
                    and not self.workers[name].done.is_set():
                raise AdmissionRefused(f"stream {name!r} already "
                                       "attached")
            self._reap_done_locked()
            w = StreamWorker(name, spec, self, store_dir=store_dir,
                             overrides=overrides)
            self.workers[name] = w
            self.admitted_total += 1
            _M_EVENTS.labels(event="admitted").inc()
            _M_ACTIVE.inc()
        w.thread.start()
        self._ensure_ladder()
        self._prune_sessions()
        log.info("service: admitted stream %r (targets %s)", name,
                 sorted(w.targets))
        return w

    def _reap_done_locked(self) -> None:  # holds: _lock
        done = [n for n, w in self.workers.items() if w.done.is_set()]
        for n in done[:-self.keep_done] if self.keep_done else done:
            del self.workers[n]

    def _worker(self, name: str | None) -> StreamWorker | None:
        """Locked worker lookup — the JTS2xx discipline: every read of
        the shared worker table goes through the service lock (admit's
        insert and _reap_done_locked's deletes race it otherwise)."""
        if name is None:
            return None
        with self._lock:
            return self.workers.get(name)

    def offer(self, name: str, op: dict) -> bool:
        w = self._worker(name)
        if w is None:
            return False
        return w.offer(op, self.shed_timeout_s)

    def seal(self, name: str) -> None:
        w = self._worker(name)
        if w is not None:
            w.seal()

    def result(self, name: str, timeout_s: float | None = 600.0) -> dict:
        """Block until the stream's verdicts are in; {} for a stream
        that was shed/drained (offline covers those)."""
        w = self._worker(name)
        if w is None:
            return {}
        if not w.done.wait(timeout_s):
            return {}
        return dict(w.results)

    def shed(self, name: str, reason: str = "operator") -> None:
        w = self._worker(name)
        if w is not None:
            w.shed(reason)

    # -- the degradation-ladder controller ---------------------------------

    def _ensure_ladder(self) -> None:
        with self._lock:
            if not self.adaptive or self._ladder_thread is not None:
                return   # a second controller would double the
                #          climb/descend rate (both mutate the hold
                #          timers), defeating the hysteresis
            t = threading.Thread(
                target=self._ladder_loop, name="jepsen-service-ladder",
                daemon=True)
            self._ladder_thread = t
        t.start()

    def _live_workers(self) -> list:
        with self._lock:
            return [w for w in self.workers.values()
                    if not w.done.is_set()]

    def overloaded(self, sig: dict | None = None) -> bool:
        """The ladder's overload predicate over the budget's signals:
        demand visibly exceeding supply — blocked acquirers, a p95
        chunk-latency blowout, or a hungry queue. Supply-side facts
        alone (a recent AIMD cut, capacity still below half of max)
        do NOT count: a lone transient OOM with nobody waiting must
        not climb a clean stream and turn a deterministic verdict
        into a deferred one."""
        s = sig if sig is not None else self.budget.signals()
        return bool(
            s["waiters"] > 0
            or (s["p95_latency_s"] or 0.0) > self.budget.blowout_s
            or s["queue_depth_ewma"] > BUDGET_HUNGRY_ROWS)

    def _ladder_step(self, now: float) -> None:
        """One controller tick: refresh suspicion for idle streams,
        climb ONE stream per sustained-overload hold, descend ONE per
        sustained-calm hold (descend hold > climb hold = transition
        hysteresis), and publish the per-tier stream gauge."""
        workers = self._live_workers()
        for w in workers:
            w.refresh_suspicion()
        if self.overloaded():
            self._calm_t = None
            if self._overload_t is None:
                self._overload_t = now
            elif now - self._overload_t >= self.ladder_climb_hold_s:
                if self._climb_one(workers):
                    self._overload_t = now  # one climb per hold
        else:
            self._overload_t = None
            if self._calm_t is None:
                self._calm_t = now
            elif now - self._calm_t >= self.ladder_descend_hold_s:
                if self._descend_one(workers):
                    self._calm_t = now      # one descend per hold
        counts = dict.fromkeys(TIER_NAMES, 0)
        for w in workers:
            counts[TIER_NAMES[w.current_tier()]] += 1
        for tname, c in counts.items():
            _M_TIER.labels(tier=tname).set(c)

    def _climb_one(self, workers: list) -> bool:
        """Climb ONE clean stream one rung: lowest tier first (spread
        the pain — no stream rides to shed while siblings sit at
        full), most expensive within a tier (climbing a cheap stream
        frees almost nothing). Suspect streams are never climbed —
        under contention they are exactly the ones that must keep
        device time."""
        eligible = [w for w in workers
                    if w.scheduling_priority() == 0
                    and w.current_tier() < TIER_SHED
                    and w._costs]   # streams with device targets only
        if not eligible:
            return False
        w = min(eligible,
                key=lambda w: (w.current_tier(), -w.device_cost()))
        return w.set_tier(w.current_tier() + 1, "sustained overload")

    def _descend_one(self, workers: list) -> bool:
        """Descend ONE degraded stream one rung: most degraded first,
        cheapest within a tier (it re-opens the least device load if
        the calm is a blip)."""
        eligible = [w for w in workers
                    if TIER_FULL < w.current_tier() < TIER_SHED]
        if not eligible:
            return False
        w = min(eligible,
                key=lambda w: (-w.current_tier(), w.device_cost()))
        return w.set_tier(w.current_tier() - 1, "sustained calm")

    def _ladder_loop(self) -> None:
        while not self._ladder_stop.wait(self.ladder_tick_s):
            try:
                self._ladder_step(_time.monotonic())
            except Exception:  # noqa: BLE001 — keep controlling
                log.warning("service: ladder tick failed",
                            exc_info=True)

    # -- drain / resume ----------------------------------------------------

    def drain(self, timeout_s: float = 60.0) -> None:
        """Stop admissions, checkpoint every live stream's carry, and
        persist per-run resume manifests — the SIGTERM path."""
        with self._lock:
            already = self.draining
            if not already:
                self.draining = True
                workers = list(self.workers.values())
        if already:
            # wait for the first drainer OUTSIDE the lock: every
            # service verb (offer/seal/poll/finish/status) now takes
            # _lock for its worker lookup, so blocking here with the
            # lock held would freeze the whole service for timeout_s
            self.drained.wait(timeout_s)
            return
        log.info("service: draining %d streams",
                 sum(1 for w in workers if not w.done.is_set()))
        self._watch_stop.set()
        self._ladder_stop.set()
        for w in workers:
            if not w.done.is_set():
                w._drain.set()
        deadline = _time.monotonic() + timeout_s
        for w in workers:
            w.done.wait(max(0.0, deadline - _time.monotonic()))
        if self.calibration_path:
            try:
                self.calibration.save(self.calibration_path)
                log.info("service: calibration saved to %s",
                         self.calibration_path)
            except OSError:
                log.warning("service: could not persist calibration",
                            exc_info=True)
        self.drained.set()
        log.info("service: drained")

    def install_sigterm(self) -> None:
        """SIGTERM → graceful drain (then the serve loop exits)."""
        def _handler(signum, frame):  # noqa: ARG001
            log.info("service: SIGTERM — draining")
            self.drain()
        _signal.signal(_signal.SIGTERM, _handler)

    def resume(self, run_dir: str) -> str | None:
        """Re-admit a drained run from its resume manifest: the
        journal re-feeds from the start and WGL dispatch skips
        row-for-row up to the restored carry checkpoint. Returns the
        stream name (now being tailed), or None when the run carries
        no manifest."""
        man = store.load_service_resume(run_dir)
        if man is None:
            return None
        if not man.get("targets"):
            log.warning("service: resume manifest in %s carries no "
                        "targets spec; ignoring it", run_dir)
            return None
        name = man.get("stream") or os.path.basename(run_dir)
        overrides = {}
        ck_by_target = man.get("checkpoints") or {}
        for target, ck in ck_by_target.items():
            if ck.get("kind") == "host" or "p" not in ck:
                # host streams checkpoint progress only — they rebuild
                # from the re-fed journal, no kernel-shape overrides
                continue
            overrides[target] = {
                "p": ck.get("p"), "chunk": ck.get("chunk"),
                "frontier": ck.get("frontier"),
                "engine": ck.get("engine"),
                "pallas": ck.get("pallas"),
                "state-range": ck.get("state-range"),
            }
        w = self.admit(name, man["targets"], store_dir=run_dir,
                       overrides=overrides)
        _M_EVENTS.labels(event="resumed").inc()
        for target, ck in ck_by_target.items():
            t = w.targets.get(target)
            if t is not None and hasattr(t, "import_checkpoint") \
                    and "carry" in ck:
                try:
                    if t.import_checkpoint(ck):
                        log.info("service %s: %r resuming from chunk "
                                 "%d", name, target, ck["chunks"])
                except (ValueError, KeyError):
                    log.warning("service %s: bad checkpoint for %r; "
                                "resuming cold", name, target,
                                exc_info=True)
        self._tail_run(run_dir, name)
        return name

    # -- crash recovery / replica failover ---------------------------------

    def claim_store(self, store_root: str) -> int:
        """Take ownership of a store root: bump its service epoch so
        any prior owner still running is fenced the moment it next
        checks, and remember ours for the fence checks every durable
        write makes."""
        self.store_root = os.path.abspath(store_root)
        self.epoch = store.fence_service_epoch(self.store_root)
        return self.epoch

    def fenced(self) -> bool:
        """True once another service instance has claimed this store
        (the epoch file moved past ours): a promoted standby owns the
        streams now, so this instance stops persisting, admitting, and
        flushing verdicts — the new owner's state must win. Sticky:
        checked against the store on every call until it trips."""
        if self.store_root is None:
            return False
        if self._fenced:  # noqa: JTS201 — monotonic False->True flag
            return True
        if store.service_epoch(self.store_root) == self.epoch:
            return False
        self._fenced = True
        log.error("service: fenced out of %s (epoch moved past %d); "
                  "stopping admissions and durable writes",
                  self.store_root, self.epoch)
        with self._lock:
            self.draining = True
        self._watch_stop.set()
        return True

    def recover(self, store_root: str,
                spec_fn: Callable[[str], dict | None] | None = None
                ) -> list[str]:
        """Cold-start crash recovery: claim the store (fencing any
        zombie predecessor), scan it for orphaned in-progress runs — a
        journal with no delivered verdict — and resume each from its
        last durable checkpoint. The journal re-feeds from the start
        (the host-side encoder and blame attribution need the whole
        client-op feed) while device dispatch skips row-for-row up to
        the checkpoint's recorded offset, so the resumed verdict is
        byte-identical to an uninterrupted run's. Runs with no (or a
        corrupt) manifest re-check cold via ``spec_fn``. No drain
        manifest required. Returns the recovered stream names."""
        self.claim_store(store_root)
        recovered: list[str] = []
        with _trace.tracer().span("service.recover") as sp:
            for tname, runs in store.tests(store_root).items():
                for start, d in runs.items():
                    if not os.path.exists(
                            os.path.join(d, "journal.jsonl")):
                        continue
                    if os.path.exists(
                            os.path.join(d, "results.json")) \
                            or os.path.exists(os.path.join(
                                d, store.STREAMED_RESULTS_FILE)):
                        continue
                    man = store.load_service_resume(d)
                    if man is not None:
                        try:
                            name = self.resume(d)
                        except AdmissionRefused:
                            continue
                        if name is None:
                            continue
                        how = ("checkpoint" if any(
                            "carry" in ck for ck in
                            (man.get("checkpoints") or {}).values())
                            else "cold")
                    elif spec_fn is not None:
                        spec = spec_fn(d)
                        if not spec:
                            continue
                        name = f"{tname}/{start}"
                        try:
                            self.admit(name, spec, store_dir=d)
                        except AdmissionRefused:
                            continue
                        self._tail_run(d, name)
                        how = "cold"
                    else:
                        continue
                    _M_RECOVERIES.labels(how=how).inc()
                    recovered.append(name)
            if sp is not None:
                sp.tags["streams"] = str(len(recovered))
                sp.tags["epoch"] = str(self.epoch)
        with self._lock:
            self.recovered_total += len(recovered)
        if recovered:
            log.warning("service: recovered %d orphaned stream(s) "
                        "from %s (epoch %d): %s", len(recovered),
                        store_root, self.epoch,
                        ", ".join(sorted(recovered)))
        return recovered

    # -- the session table (session-resilient wire protocol) ---------------

    def _session_attach(self, stream: str, token: str,
                        journal_fed: bool) -> "_Session | None":
        """Register or re-bind a socket session. Returns the session
        (fresh, or the existing one when the token matches), or None
        on a token mismatch — a live stream must not be hijackable by
        name alone."""
        with self._session_lock:
            s = self._sessions.get(stream)
            if s is None:
                s = self._sessions[stream] = _Session(token,
                                                      journal_fed)
                return s
            if s.token == token:
                if journal_fed:
                    s.journal_fed = True
                s.touched = _time.monotonic()
                return s
            return None

    def _session_apply(self, stream: str | None, seq) -> bool:
        """Should this op be applied? False for a replayed duplicate
        (already applied before the disconnect — counted, dropped) and
        for journal-fed streams (the store tail feeds those). Ops
        without a seq are legacy clients: always applied."""
        if stream is None:
            return False
        if seq is None:
            with self._session_lock:
                s = self._sessions.get(stream)
                return not (s and s.journal_fed)
        try:
            seq = int(seq)
        except (TypeError, ValueError):
            return False
        with self._session_lock:
            s = self._sessions.get(stream)
            if s is None:
                return True     # attached without a session handshake
            if s.journal_fed:
                return False
            s.touched = _time.monotonic()
            if seq <= s.seq:
                s.replays += 1
                _M_REPLAYS.inc()
                return False
            s.seq = seq
            return True

    def _session_ack(self, stream: str | None) -> int:
        """The stream's applied-sequence high-water mark — everything
        at or below it is safe for the client to forget."""
        with self._session_lock:
            s = self._sessions.get(stream) if stream else None
            return s.seq if s else 0

    def _session_journal_fed(self, stream: str | None) -> bool:
        with self._session_lock:
            s = self._sessions.get(stream) if stream else None
            return bool(s and s.journal_fed)

    def _stream_terminal(self, name: str) -> None:
        """A worker reached a terminal state (verdict / shed /
        quarantined / drained): evict its session entry and journal
        tail right away instead of waiting for the size-gated prune or
        the next watcher pass. Locks taken sequentially, never
        nested (and never while holding the worker's _term_lock —
        _terminal releases it before calling here)."""
        with self._session_lock:
            self._sessions.pop(name, None)
        with self._lock:
            stale = [d for d, (_t, n) in self._tails.items()
                     if n == name]
            for d in stale:
                tail, _n = self._tails.pop(d)
                self._finished_dirs.add(d)
                tail.close()

    def _prune_sessions(self) -> None:
        """Bound the session table. Terminal streams already evicted
        their entries (_stream_terminal); this sweep covers the rest:
        sessions idle past the TTL with no live worker (a client that
        attached, went away, and never drove its stream to a verdict),
        plus a size-gated prune of anything not in the worker table as
        a backstop. Locks taken sequentially, never nested."""
        now = _time.monotonic()
        with self._lock:
            live = {n for n, w in self.workers.items()
                    if not w.done.is_set()}
            known = set(self.workers)
        with self._session_lock:
            if self.session_ttl_s > 0:
                for n in [n for n, s in self._sessions.items()
                          if n not in live
                          and now - s.touched > self.session_ttl_s]:
                    del self._sessions[n]
            if len(self._sessions) <= max(256, 4 * self.keep_done):
                return
            for n in [n for n in self._sessions if n not in known]:
                del self._sessions[n]

    # -- store watching ----------------------------------------------------

    def watch(self, base_dir: str,
              spec_fn: Callable[[str], dict | None] | None = None,
              scan_interval_s: float = 1.0) -> None:
        """Tail-follow journals under a store directory: every run dir
        with a journal and no results.json is admitted (spec_fn(run_dir)
        supplies its targets spec; None skips the run — without a
        spec_fn only runs with a resume manifest are picked up). Polls
        back off per-tail with decorrelated jitter while a journal is
        quiet (store.JournalTail.idle_s), so hundreds of dormant runs
        cost almost nothing."""
        self._watch_base = base_dir
        self._watch_spec_fn = spec_fn
        self._watch_scan_s = scan_interval_s
        self._ensure_watcher()

    def _ensure_watcher(self) -> None:
        if self._watcher is None:
            self._watcher = threading.Thread(
                target=self._watch_loop, name="jepsen-service-watch",
                daemon=True)
            self._watcher.start()

    def _tail_run(self, run_dir: str, name: str) -> None:
        jp = os.path.join(run_dir, "journal.jsonl")
        with self._lock:
            self._tails[run_dir] = (store.JournalTail(jp), name)
        self._ensure_watcher()

    def _stream_tailed(self, name: str) -> bool:
        """Is this stream fed from a store-side journal tail (resume /
        recover / watch) rather than by its socket?"""
        with self._lock:
            return any(n == name for _t, n in self._tails.values())

    def _scan(self) -> None:
        base = getattr(self, "_watch_base", None)
        spec_fn = getattr(self, "_watch_spec_fn", None)
        if base is None or not os.path.isdir(base):
            return
        for tname, runs in store.tests(base).items():
            for start, d in runs.items():
                with self._lock:
                    known = (d in self._tails
                             or d in self._finished_dirs)
                if known:
                    continue
                if not os.path.exists(
                        os.path.join(d, "journal.jsonl")):
                    continue
                if os.path.exists(os.path.join(d, "results.json")):
                    continue
                if os.path.exists(os.path.join(
                        d, store.STREAMED_RESULTS_FILE)):
                    # a service (this one or a predecessor) already
                    # delivered/deferred this run: re-admitting would
                    # re-verify the whole history on every scan
                    with self._lock:
                        self._finished_dirs.add(d)
                    continue
                if store.load_service_resume(d) is not None:
                    try:
                        self.resume(d)
                    except AdmissionRefused:
                        pass
                    continue
                if spec_fn is None:
                    continue
                spec = spec_fn(d)
                if not spec:
                    continue
                name = f"{tname}/{start}"
                try:
                    self.admit(name, spec, store_dir=d)
                except AdmissionRefused:
                    continue
                self._tail_run(d, name)

    def _watch_loop(self) -> None:
        last_scan = 0.0
        while not self._watch_stop.is_set():
            now = _time.monotonic()
            if now - last_scan >= getattr(self, "_watch_scan_s", 1.0):
                try:
                    self._scan()
                except Exception:  # noqa: BLE001 — keep watching
                    log.warning("service: store scan failed",
                                exc_info=True)
                last_scan = now
            sleep = 0.25
            with self._lock:
                tails = list(self._tails.items())
            for d, (tail, name) in tails:
                w = self._worker(name)
                if w is None or w.done.is_set():
                    with self._lock:
                        self._tails.pop(d, None)
                        self._finished_dirs.add(d)
                    tail.close()
                    continue
                if tail.idle_s > 0 and now < getattr(
                        tail, "_next_poll", 0.0):
                    sleep = min(sleep, tail._next_poll - now)
                    continue
                try:
                    ops = tail.poll()
                except ValueError:
                    w._quarantine(traceback.format_exc())
                    with self._lock:
                        self._tails.pop(d, None)
                    tail.close()
                    continue
                for op in ops:
                    w.offer(op, self.shed_timeout_s)
                if not ops and os.path.exists(
                        os.path.join(d, "history.jsonl.gz")):
                    # the run saved its history: the journal is
                    # complete and fully fed — seal for the verdict
                    w.seal()
                    with self._lock:
                        self._tails.pop(d, None)
                    tail.close()
                    continue
                # decorrelated-jitter idle backoff (satellite): quiet
                # journals get polled less and less, any data resets
                tail._next_poll = _time.monotonic() + tail.idle_s
                sleep = min(sleep, tail.idle_s or 0.01)
            self._watch_stop.wait(max(0.005, sleep))

    # -- status ------------------------------------------------------------

    def status(self) -> dict:
        """The /healthz shape."""
        with self._lock:
            workers = dict(self.workers)
            draining = self.draining
            admitted, refused = self.admitted_total, self.refused_total
            transitions = self.ladder_transitions_total
            recovered = self.recovered_total
        with self._session_lock:
            sessions = len(self._sessions)
            replays = sum(s.replays
                          for s in self._sessions.values())
        tiers = dict.fromkeys(TIER_NAMES, 0)
        for w in workers.values():
            if not w.done.is_set():
                tiers[TIER_NAMES[w.current_tier()]] += 1
        return {
            "state": ("drained" if self.drained.is_set()
                      else "draining" if draining else "serving"),
            "uptime_s": round(_time.monotonic() - self.t0, 3),
            "streams": {n: w.status() for n, w in workers.items()},
            "admitted-total": admitted,
            "refused-total": refused,
            "recovered-total": recovered,
            "epoch": self.epoch,
            "fenced": self._fenced,  # noqa: JTS201 — monotonic flag
            "sessions": {"count": sessions, "replays": replays},
            "shed": sorted(n for n, w in workers.items()
                           if w.state == SHED),
            "quarantined": sorted(n for n, w in workers.items()
                                  if w.state == QUARANTINED),
            "budget": self.budget.status(),
            "ladder": {"adaptive": self.adaptive,
                       "tiers": tiers,
                       "transitions": transitions},
            "calibration": {
                "platform": self.calibration.platform,
                "coefficients": self.calibration.coefficients(),
            },
            # the service-layer registry slice: stream lifecycle
            # counters, budget gauges, queue-depth/verb histograms
            "telemetry": _telemetry.snapshot(
                prefix="jepsen_tpu_service_", compact=True),
        }

    # -- the socket layer --------------------------------------------------

    def serve(self, addr: str = "127.0.0.1:0") -> str:
        """Listen on a local socket (``host:port``, port 0 picks a
        free one; a path serves a unix socket). Returns the bound
        address for clients."""
        if _is_unix_addr(addr):
            try:
                os.unlink(addr)
            except OSError:
                pass
            srv = _socket.socket(_socket.AF_UNIX, _socket.SOCK_STREAM)
            srv.bind(addr)
            bound = addr
        else:
            host, _, port = addr.rpartition(":")
            srv = _socket.create_server((host or "127.0.0.1",
                                         int(port or 0)))
            bound = "%s:%d" % srv.getsockname()[:2]
        srv.listen(64)
        self._server = srv
        t = threading.Thread(target=self._accept_loop,
                             name="jepsen-service-accept", daemon=True)
        t.start()
        self._server_threads.append(t)
        log.info("verification service listening on %s", bound)
        return bound

    def stop(self) -> None:
        """Hard stop (after drain, or for tests): close the socket and
        stop watching."""
        self._watch_stop.set()
        self._ladder_stop.set()
        srv, self._server = self._server, None
        if srv is not None:
            # closing the fd does NOT interrupt a thread blocked in
            # accept() on Linux — poke the listener with a throwaway
            # connect so the accept loop wakes, sees _server is None,
            # and exits (the chaos resource-leak oracle counts the
            # thread otherwise)
            try:
                with _socket.socket(srv.family,
                                    _socket.SOCK_STREAM) as poke:
                    poke.settimeout(0.2)
                    poke.connect(srv.getsockname())
            except OSError:
                pass
            try:
                srv.close()
            except OSError:
                pass
            for t in self._server_threads:
                t.join(timeout=1.0)
            self._server_threads.clear()

    def _accept_loop(self) -> None:
        while True:
            srv = self._server
            if srv is None:
                return
            try:
                conn, _ = srv.accept()
            except OSError:
                return
            if self._server is None:   # stop()'s wake-up poke
                try:
                    conn.close()
                except OSError:
                    pass
                return
            # daemon thread per connection, deliberately NOT retained:
            # a serving daemon sees one connection per run, and an
            # ever-growing thread list is a leak
            threading.Thread(target=self._handle_conn, args=(conn,),
                             name="jepsen-service-conn",
                             daemon=True).start()

    def _handle_conn(self, conn: _socket.socket) -> None:
        stream: str | None = None
        wlock = threading.Lock()

        def reply(msg: dict, rid) -> None:
            if rid is not None:
                msg["id"] = rid
            data = (json.dumps(msg, default=store._json_default)
                    + "\n").encode()
            with wlock:
                conn.sendall(data)

        try:
            with conn:
                for line in _recv_lines(conn):
                    if line is None:
                        # oversized frame: the reader skipped it;
                        # answer and keep the connection alive
                        reply({"ok": False,
                               "error": "line too long "
                                        f"(max {MAX_LINE_BYTES})"},
                              None)
                        continue
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        msg = json.loads(line)
                    except ValueError:
                        reply({"ok": False,
                               "error": "bad json"}, None)
                        continue
                    if not isinstance(msg, dict):
                        reply({"ok": False,
                               "error": "not an object"}, None)
                        continue
                    rid = msg.get("id")
                    typ = msg.get("type")
                    t_verb = _time.monotonic()
                    try:
                        if typ == "op":
                            if stream is not None:
                                if self._session_apply(
                                        stream, msg.get("seq")):
                                    self.offer(stream,
                                               msg.get("op") or {})
                                if rid is not None or msg.get("ack"):
                                    reply({"ok": True,
                                           "acked": self._session_ack(
                                               stream)}, rid)
                        elif typ == "attach":
                            stream = self._attach_verb(msg, stream,
                                                       reply, rid)
                        elif typ == "poll":
                            w = self._worker(stream)
                            reply({"ok": True,
                                   "violation": bool(w and w.violation),
                                   "state": w.state if w else None},
                                  rid)
                        elif typ == "finish":
                            if stream is None:
                                reply({"ok": False,
                                       "error": "not attached"}, rid)
                                continue
                            if not self._session_journal_fed(stream):
                                # a journal-fed stream seals when its
                                # journal drains (watch loop), not on
                                # the client's say-so — sealing here
                                # would cut the verdict short
                                self.seal(stream)
                            w = self._worker(stream)
                            timeout = float(msg.get("timeout-s")
                                            or 600.0)
                            r = self.result(stream, timeout)
                            reply({"ok": True, "results": r,
                                   "state": w.state if w else None},
                                  rid)
                        elif typ == "status":
                            reply({"ok": True,
                                   "status": self.status()}, rid)
                        elif typ == "metrics":
                            # the whole registry (not just the
                            # service slice): one verb answers what
                            # /metrics answers over HTTP, for
                            # deployments without --metrics-port
                            reply({"ok": True,
                                   "metrics": _telemetry.snapshot(
                                       compact=bool(
                                           msg.get("compact")))}, rid)
                        elif typ == "close":
                            if stream is not None:
                                w = self._worker(stream)
                                if w is not None \
                                        and not w.done.is_set() \
                                        and not self._stream_tailed(
                                            stream):
                                    w.q.put(_CLOSE)
                            return
                        else:
                            reply({"ok": False,
                                   "error": f"unknown type {typ!r}"},
                                  rid)
                    except OSError:
                        raise   # the peer is gone; drop below
                    except Exception as e:  # noqa: BLE001 — a garbage
                        # frame (or a verb-handler bug) must kill
                        # neither this connection nor its thread;
                        # siblings on other sockets feel nothing
                        log.warning("service: verb %r failed",
                                    typ, exc_info=True)
                        reply({"ok": False,
                               "error": f"{type(e).__name__}: {e}"},
                              rid)
                    finally:
                        _M_VERB.labels(
                            verb=(typ if typ in _KNOWN_VERBS
                                  else "unknown")).observe(
                            _time.monotonic() - t_verb)
        except (OSError, ValueError):
            log.info("service: connection dropped%s",
                     f" (stream {stream})" if stream else "")

    def _attach_verb(self, msg: dict, stream: str | None,
                     reply, rid) -> str | None:
        """The attach verb: fresh admission, or — when the named
        worker already exists and the client presents a session token
        — a session re-attach (socket drop, service restart, or
        standby failover) that acks the high-water mark so the client
        replays only unacked ops."""
        name = str(msg.get("stream"))
        token = msg.get("session")
        w = self._worker(name)
        if token is not None and w is not None:
            journal_fed = self._stream_tailed(name)
            s = self._session_attach(name, str(token), journal_fed)
            if s is None:
                reply({"ok": False,
                       "error": "session token mismatch"}, rid)
                return stream
            _M_RECONNECTS.labels(side="server").inc()
            reply({"ok": True, "stream": name, "resumed": True,
                   "acked": self._session_ack(name),
                   "journal-fed": self._session_journal_fed(name),
                   "targets": w.target_names}, rid)
            return name
        if msg.get("resume") and w is None:
            # the stream's acked ops died with its worker and no
            # recovered worker took over (no journal on the store
            # side): re-admitting fresh would silently lose them
            reply({"ok": False, "deferred": True,
                   "error": "unknown session: stream not recovered"},
                  rid)
            return stream
        try:
            w = self.admit(name, msg.get("targets") or {},
                           store_dir=msg.get("store-dir"))
            if token is not None:
                self._session_attach(w.name, str(token), False)
            reply({"ok": True, "stream": w.name,
                   "targets": sorted(w.targets)}, rid)
            return w.name
        except (AdmissionRefused, ValueError) as e:
            reply({"ok": False, "deferred": True,
                   "error": str(e)}, rid)
            return stream


def _recv_lines(conn: _socket.socket):
    """Bounded line reader for the socket protocol: yields one decoded
    line per frame, or None for a frame that blew past MAX_LINE_BYTES
    (the rest of that line is discarded, the connection survives).
    Undecodable bytes are replaced, not fatal — the json parse then
    rejects the frame with an error reply instead of the decode
    exception killing the reader thread."""
    buf = bytearray()
    skipping = False
    while True:
        try:
            data = conn.recv(65536)
        except OSError:
            return
        if not data:
            return
        buf += data
        while True:
            nl = buf.find(b"\n")
            if nl < 0:
                break
            line, buf = buf[:nl], buf[nl + 1:]
            if skipping:        # tail of an oversized frame
                skipping = False
                continue
            if len(line) > MAX_LINE_BYTES:
                # the whole line arrived before the growth check below
                # could trip — still an oversized frame
                yield None
                continue
            yield line.decode("utf-8", errors="replace")
        if len(buf) > MAX_LINE_BYTES:
            buf.clear()
            if not skipping:    # complain once per oversized frame
                skipping = True
                yield None


def _is_unix_addr(addr: str) -> bool:
    return os.sep in addr and ":" not in addr


# ---------------------------------------------------------------------------
# the client (core.run attaches through this)
# ---------------------------------------------------------------------------

POLL_INTERVAL_S = 0.2


class _ClientConn:
    """One live socket — the unit `reconnect.Wrapper` opens and
    closes; its reader thread exits when the socket does."""

    __slots__ = ("sock",)

    def __init__(self, sock: _socket.socket):
        self.sock = sock


class ServiceClient:
    """An `OnlineChecker`-shaped proxy that feeds a remote
    verification service instead of spawning in-process stream
    workers: same offer/should_abort/finalize/close surface, so
    core.run and the interpreter cannot tell the difference.

    Session resilience: ops carry monotonic sequence numbers and stay
    buffered until the server acks its applied high-water mark; the
    socket lives inside a `reconnect.Wrapper`, so any disconnect
    transparently re-attaches — same session token, decorrelated-
    jitter backoff across the whole address list (`addr` may be
    comma-separated ``primary,standby``) — and replays only unacked
    ops. At-least-once delivery, exactly-once application: the
    server's session table drops replayed duplicates."""

    def __init__(self, addr: str, test: dict, spec: dict | None = None):
        self.addrs = [a.strip() for a in str(addr).split(",")
                      if a.strip()]
        self.addr = self.addrs[0] if self.addrs else str(addr)
        self.targets = spec if spec is not None else targets_spec(test)
        if not self.targets:
            raise ValueError("no streamable checker targets")
        self.abort_on_violation = bool(test.get("abort-on-violation"))
        self.aborted = False
        self.stream = "%s/%s" % (test.get("name", "run"),
                                 test.get("start-time", os.getpid()))
        self.session = os.urandom(8).hex()
        store_dir = (store.dir_name(test)
                     if test.get("name") and test.get("start-time")
                     else None)
        self._store_dir = (os.path.abspath(store_dir)
                           if store_dir else None)
        self._wlock = threading.Lock()
        self._rid = 0                       # guarded-by: _reply_evt
        self._replies: dict[int, dict] = {}  # guarded-by: _reply_evt
        self._reply_evt = threading.Condition()
        self._closed = False                # guarded-by: _reply_evt
        self._last_poll = 0.0
        # -- the replay buffer (the client half of the session
        # protocol). _seq is the offering thread's alone.
        self._seq = 0
        self._buf_lock = threading.Lock()
        self._unacked: deque = deque()      # guarded-by: _buf_lock
        self._acked = 0                     # guarded-by: _buf_lock
        # monotonic False->True flags, read lock-free on hot paths
        # (the offer-path noqa discipline): flipped under the
        # wrapper's write lock by the reopen handshake
        self._journal_fed = False
        self._attached = False
        self._dead = False
        self._active: str | None = None     # addr currently attached
        self.reconnects = 0
        self.failovers = 0
        self._wrap = _reconnect.Wrapper(
            self._open_conn, self._close_conn, log=log.info,
            name=f"verification service {self.addr}")
        self._wrap.open()   # first attach; raises on refusal
        log.info("attached to verification service %s as %r "
                 "(targets %s, session %s)", self._active,
                 self.stream, sorted(self.targets), self.session)

    # -- wire --------------------------------------------------------------

    def _open_conn(self) -> _ClientConn:
        """Open + attach one connection, cycling the address list
        under decorrelated-jitter backoff. Landing on a different
        address than last time is a client-side failover."""
        delays = None
        err: Exception | None = None
        for attempt in range(RECONNECT_TRIES):
            if attempt:
                if delays is None:
                    from .control.retry import backoff
                    delays = backoff(0.05, 2.0)
                _time.sleep(next(delays))
            for a in self.addrs:
                try:
                    sock = _connect(a)
                except OSError as e:
                    err = e
                    continue
                try:
                    conn = self._handshake(sock, a)
                except AdmissionRefused:
                    # authoritative refusal: retrying cannot help
                    try:
                        sock.close()
                    except OSError:
                        pass
                    self._dead = True
                    raise
                except (OSError, ValueError) as e:
                    try:
                        sock.close()
                    except OSError:
                        pass
                    err = e
                    continue
                if self._active is not None and a != self._active:
                    self.failovers += 1
                    _M_FAILOVERS.labels(role="client").inc()
                    log.warning("service client %r: failed over "
                                "%s -> %s", self.stream,
                                self._active, a)
                self._active = a
                return conn
        self._dead = True
        raise (err if err is not None else
               OSError(f"no verification service reachable "
                       f"at {self.addrs}"))

    def _handshake(self, sock: _socket.socket,
                   addr: str) -> _ClientConn:
        """Attach on a fresh socket: present the session token, learn
        the server's acked high-water mark, prune the replay buffer
        to it, and re-send whatever the dead connection lost."""
        resume = self._attached
        with self._reply_evt:
            self._rid += 1
            rid = self._rid
        req = {"type": "attach", "stream": self.stream,
               "targets": self.targets, "store-dir": self._store_dir,
               "session": self.session, "resume": resume, "id": rid}
        sock.settimeout(30.0)   # the handshake exchange only
        sock.sendall((json.dumps(req, default=store._json_default)
                      + "\n").encode())
        rf = sock.makefile("r", encoding="utf-8")
        r = None
        while not (isinstance(r, dict) and r.get("id") == rid):
            line = rf.readline()
            if not line:
                raise OSError("connection lost during attach")
            try:
                r = json.loads(line)
            except ValueError:
                r = None
        if not r.get("ok"):
            raise AdmissionRefused(r.get("error") or "attach failed")
        if r.get("journal-fed"):
            # a recovered (or promoted-standby) service tails this
            # run's journal directly: socket ops would double-apply,
            # so the socket feed stops here
            self._journal_fed = True
        acked = int(r.get("acked") or 0)
        with self._buf_lock:
            self._acked = max(self._acked, acked)
            while self._unacked \
                    and self._unacked[0][0] <= self._acked:
                self._unacked.popleft()
            if self._journal_fed:
                self._unacked.clear()
            replay = list(self._unacked)
        for seq, op in replay:
            sock.sendall((json.dumps(
                {"type": "op", "op": op, "seq": seq},
                default=store._json_default) + "\n").encode())
        sock.settimeout(None)
        self._attached = True
        if resume:
            self.reconnects += 1
            _M_RECONNECTS.labels(side="client").inc()
            log.info("service client %r: re-attached to %s "
                     "(acked %d, replayed %d unacked ops%s)",
                     self.stream, addr, acked, len(replay),
                     "; journal-fed" if self._journal_fed else "")
        threading.Thread(target=self._read_loop, args=(rf,),
                         name="jepsen-service-client",
                         daemon=True).start()
        return _ClientConn(sock)

    def _close_conn(self, conn: _ClientConn) -> None:
        try:
            conn.sock.close()   # the reader exits with the socket
        except OSError:
            pass

    def _send(self, msg: dict) -> None:
        data = (json.dumps(msg, default=store._json_default)
                + "\n").encode()

        def _do(conn: _ClientConn) -> None:
            with self._wlock:
                conn.sock.sendall(data)
        # with_conn reopens (re-attach + replay) on failure and
        # re-raises; the callers decide whether that loses anything
        self._wrap.with_conn(_do)

    def _request(self, msg: dict,
                 timeout_s: float = 30.0) -> dict | None:
        with self._reply_evt:
            self._rid += 1
            rid = self._rid
        msg["id"] = rid
        try:
            self._send(msg)
        except (OSError, ValueError):
            return None
        deadline = _time.monotonic() + timeout_s
        with self._reply_evt:
            while rid not in self._replies:
                wait = deadline - _time.monotonic()
                if wait <= 0 or self._closed:
                    return None
                self._reply_evt.wait(wait)
            return self._replies.pop(rid)

    def _note_acked(self, acked) -> None:
        try:
            acked = int(acked)
        except (TypeError, ValueError):
            return
        with self._buf_lock:
            if acked > self._acked:
                self._acked = acked
            while self._unacked \
                    and self._unacked[0][0] <= self._acked:
                self._unacked.popleft()

    def _read_loop(self, rf) -> None:
        try:
            for line in rf:
                line = line.strip()
                if not line:
                    continue
                try:
                    msg = json.loads(line)
                except ValueError:
                    continue
                if not isinstance(msg, dict):
                    continue
                if "acked" in msg:
                    self._note_acked(msg.get("acked"))
                rid = msg.get("id")
                if rid is None:
                    continue
                try:
                    rid = int(rid)
                except (TypeError, ValueError):
                    continue
                with self._reply_evt:
                    self._replies[rid] = msg
                    self._reply_evt.notify_all()
        except (OSError, ValueError):
            pass
        # this socket died, but the wrapper may reopen it under a new
        # reader: wake waiters so requests notice promptly — only
        # _mark_closed flips _closed
        with self._reply_evt:
            self._reply_evt.notify_all()

    # -- OnlineChecker surface ---------------------------------------------

    def offer(self, op: dict) -> None:
        # lock-free reads by design: monotonic flags, and the op hot
        # path must not take the reply lock per op
        if self._closed or self._journal_fed:  # noqa: JTS201
            return
        self._seq += 1
        seq = self._seq
        with self._buf_lock:
            self._unacked.append((seq, op))
        msg = {"type": "op", "op": op, "seq": seq}
        if seq % ACK_EVERY == 0:
            msg["ack"] = True   # bound the replay buffer
        try:
            self._send(msg)
        except OSError:
            # the send failed, but with_conn already re-attached and
            # replayed the buffer — this op included, it was appended
            # before the send. Only a reopen that itself gave up
            # (dead) or was refused ends the session.
            if self._dead:  # noqa: JTS201
                log.warning("verification service connection lost "
                            "and not recoverable; offline checking "
                            "will cover this run")
                self._mark_closed()

    def should_abort(self) -> bool:
        if self.aborted:
            return True
        # monotonic-flag fast path (see offer)
        if not self.abort_on_violation or self._closed:  # noqa: JTS201
            return False
        now = _time.monotonic()
        if now - self._last_poll < POLL_INTERVAL_S:
            return False
        self._last_poll = now
        r = self._request({"type": "poll"}, timeout_s=5.0)
        if r and r.get("violation"):
            self.aborted = True
        return self.aborted

    def finalize(self, timeout_s: float | None = 600.0) -> dict:
        """Seal the stream and collect its verdicts — shaped exactly
        like OnlineChecker.finalize (deferred/drained streams return
        {}, so offline checking covers them)."""
        if self._closed:  # noqa: JTS201 — monotonic-flag fast path
            return {}
        if self._journal_fed:  # noqa: JTS201
            # the recovered service tails the journal and writes
            # streamed results into the run dir itself; analyze
            # reuses them — nothing to collect over this socket
            self._mark_closed()
            return {}
        r = self._request({"type": "finish",
                           "timeout-s": timeout_s},
                          timeout_s=(timeout_s or 600.0) + 30.0)
        if r is None and not self._dead \
                and not self._journal_fed:  # noqa: JTS201
            # the reply (or its socket) was lost mid-wait: finish is
            # idempotent under the session protocol, so ask once more
            # on the reopened connection
            r = self._request({"type": "finish",
                               "timeout-s": timeout_s},
                              timeout_s=(timeout_s or 600.0) + 30.0)
        self._mark_closed()
        if not (r and r.get("ok")):
            log.warning("verification service finish failed; offline "
                        "checking covers this run")
            return {}
        out = r.get("results") or {}
        state = r.get("state")
        if state in (SHED, DRAINED):
            log.warning("verification service %s this run's stream; "
                        "offline checking covers it",
                        "shed" if state == SHED else "drained")
            return {}
        if out.get("deferred"):
            return {}
        return out

    def close(self) -> None:
        try:
            self._send({"type": "close"})
        except OSError:
            pass
        self._mark_closed()

    def _mark_closed(self) -> None:
        with self._reply_evt:
            self._closed = True
            self._reply_evt.notify_all()
        try:
            self._wrap.close()
        except Exception:  # noqa: BLE001 — teardown is best-effort
            pass


def _connect(addr: str) -> _socket.socket:
    if _is_unix_addr(addr):
        s = _socket.socket(_socket.AF_UNIX, _socket.SOCK_STREAM)
        s.connect(addr)
        return s
    host, _, port = addr.rpartition(":")
    s = _socket.create_connection((host or "127.0.0.1", int(port)),
                                  timeout=10.0)
    # the connect timeout must not linger: it would also deadline every
    # later recv, and a run can legitimately go >10s without traffic
    # (request timeouts are enforced at the _request layer instead)
    s.settimeout(None)
    return s


def maybe_attach(test: dict):
    """A ServiceClient for a test with a 'service' address, or None
    (no streamable targets / service unreachable / admission refused
    — the run then falls back to its local online/offline checking).
    Never raises: the service is an optimization."""
    addr = test.get("service")
    if not addr:
        return None
    try:
        spec = targets_spec(test)
        if not spec:
            log.info("--service: no streamable checker targets; "
                     "running without the service")
            return None
        return ServiceClient(addr, test, spec)
    except AdmissionRefused as e:
        log.warning("verification service refused this run (%s); "
                    "falling back to local checking", e)
        return None
    except OSError as e:
        log.warning("verification service %s unreachable (%s); "
                    "falling back to local checking", addr, e)
        return None


# ---------------------------------------------------------------------------
# replica failover (the --standby mode)
# ---------------------------------------------------------------------------

class Standby:
    """A warm replica: watch a primary's health endpoint, and after
    ``failures`` consecutive failed probes fence the (presumed dead)
    primary via the store-level epoch file, `recover()` every
    orphaned stream from its durable checkpoints, and begin serving.
    The fence makes promotion safe against false positives: a merely
    partitioned primary notices the epoch moved past its own at its
    next durable write and stops touching the store (doc/robustness.md
    has the state machine)."""

    def __init__(self, svc: VerificationService, primary: str,
                 store_root: str, bind: str = "127.0.0.1:0",
                 poll_s: float = DEFAULT_STANDBY_POLL_S,
                 failures: int = DEFAULT_STANDBY_FAILURES,
                 spec_fn: Callable[[str], dict | None] | None = None):
        self.svc = svc
        self.primary = primary
        self.store_root = store_root
        self.bind = bind
        self.poll_s = float(poll_s)
        self.failures = int(failures)
        self.spec_fn = spec_fn
        self.promoted = threading.Event()
        self.bound: str | None = None
        self._stop = threading.Event()

    def healthy(self) -> bool:
        """One probe of the primary: its /healthz when given an
        http(s) URL, else the socket ``status`` verb."""
        try:
            if self.primary.startswith(("http://", "https://")):
                from urllib.request import urlopen
                with urlopen(self.primary.rstrip("/") + "/healthz",
                             timeout=5.0) as resp:
                    return 200 <= resp.status < 300
            sock = _connect(self.primary)
            try:
                sock.settimeout(5.0)
                sock.sendall(b'{"type": "poll", "id": 0}\n')
                return bool(sock.recv(1))
            finally:
                sock.close()
        except (OSError, ValueError):
            return False

    def run(self) -> str | None:
        """Block watching the primary; on sustained failure promote
        and return the bound serve address (None if stop()ped
        first)."""
        log.info("standby: watching primary %s (probe every %.1fs, "
                 "promote after %d failures)", self.primary,
                 self.poll_s, self.failures)
        failed = 0
        while not self._stop.is_set():
            failed = 0 if self.healthy() else failed + 1
            if failed >= self.failures:
                return self.promote()
            self._stop.wait(self.poll_s)
        return None

    def promote(self) -> str:
        """Fence the primary, recover its streams, start serving."""
        log.warning("standby: primary %s unhealthy for %d probes — "
                    "fencing and promoting", self.primary,
                    self.failures)
        recovered = self.svc.recover(self.store_root,
                                     spec_fn=self.spec_fn)
        # keep admitting fresh runs appearing under the store too
        self.svc.watch(self.store_root, spec_fn=self.spec_fn)
        self.bound = self.svc.serve(self.bind)
        _M_FAILOVERS.labels(role="standby").inc()
        log.warning("standby: promoted — serving on %s (%d streams "
                    "recovered, epoch %d)", self.bound,
                    len(recovered), self.svc.epoch)
        self.promoted.set()
        return self.bound

    def stop(self) -> None:
        self._stop.set()
