"""Command line interface: a default main for common functions (the web
interface) and utilities for test suites to build their own runners.

Reference: `jepsen/src/jepsen/cli.clj` — the shared test option spec
(:64-111), option post-processing (ssh-map renaming, node-list merging,
`3n` concurrency parsing, :143-254), the `test`/`analyze` commands
(:355-430), `test-all` (:432-518), `serve` (:336-353), and the runner's
exit-code contract (:127-139):

  0     all tests passed
  1     some test failed
  2     some test had unknown validity
  254   invalid arguments
  255   internal error
"""

from __future__ import annotations

import argparse
import logging
import os
import pprint as _pprint
import re
import sys
import time as _time
from typing import Optional

log = logging.getLogger(__name__)

DEFAULT_NODES = ["n1", "n2", "n3", "n4", "n5"]

TEST_USAGE = """Usage: PROG COMMAND [OPTIONS ...]

Runs a test and exits with a status code:

  0     All tests passed
  1     Some test failed
  2     Some test had an :unknown validity
  254   Invalid arguments
  255   Internal error
"""


def one_of(coll) -> str:
    ks = coll.keys() if isinstance(coll, dict) else coll
    return "Must be one of " + ", ".join(sorted(str(k) for k in ks))


# -- option specs -----------------------------------------------------------
#
# An opt-spec is a list of dicts: {'long': '--name', 'short': '-n', plus
# argparse kwargs}. Suites extend the shared spec; merge_opt_specs
# resolves collisions by long name, preferring the latter (the
# reference's merge-opt-specs, cli.clj:52-59).

def opt(long: str, short: Optional[str] = None, **kw) -> dict:
    return {"long": long, "short": short, **kw}


def merge_opt_specs(a: list, b: list) -> list:
    merged: dict = {}
    for o in list(a) + list(b or []):
        merged[o["long"]] = o
    return list(merged.values())


def _comma_list(s: str) -> list[str]:
    return re.split(r",\s*", s)


def test_opt_spec() -> list[dict]:
    """Shared options for testing (`cli.clj:64-111`)."""
    return [
        # default=None, not DEFAULT_NODES: argparse's append mutates a
        # list default in place; parse_nodes applies the default when no
        # node options were given (reference repeated-opt, cli.clj:27-39)
        opt("--node", "-n", action="append", metavar="HOSTNAME",
            help="Node(s) to run test on; repeat for multiple nodes."),
        opt("--nodes", metavar="NODE_LIST", type=_comma_list,
            help="Comma-separated list of node hostnames."),
        opt("--nodes-file", metavar="FILENAME",
            help="File containing node hostnames, one per line."),
        opt("--username", default="root", help="Username for logins"),
        opt("--password", default="root", help="Password for sudo access"),
        opt("--strict-host-key-checking", action="store_true",
            help="Whether to check host keys"),
        opt("--no-ssh", action="store_true",
            help="Don't establish SSH connections to any nodes."),
        opt("--ssh-private-key", metavar="FILE",
            help="Path to an SSH identity file"),
        opt("--concurrency", default="1n", metavar="NUMBER",
            help="How many workers to run: an integer, optionally "
                 "followed by n (e.g. 3n) to multiply by node count."),
        opt("--leave-db-running", action="store_true",
            help="Leave the database running at the end of the test."),
        opt("--logging-json", action="store_true",
            help="Use JSON structured output in the log."),
        opt("--test-count", type=int, default=1, metavar="NUMBER",
            help="How many times to repeat the test"),
        opt("--time-limit", type=int, default=60, metavar="SECONDS",
            help="How long the test should run, excluding setup/"
                 "teardown, in seconds"),
        opt("--store-dir", default="store", metavar="DIR",
            help="Directory to store test results under"),
        opt("--online", action="store_true",
            help="Verify the history online: a streaming checker "
                 "tails the run's journal and advances the device "
                 "search while the run executes, so analysis latency "
                 "collapses to the unchecked tail."),
        opt("--service", metavar="ADDR", default=None,
            help="Attach this run's journal stream to a persistent "
                 "verification service (see the `service` command) "
                 "at ADDR (host:port, or a unix socket path) instead "
                 "of spawning an in-process online checker. A "
                 "refused or unreachable service falls back to local "
                 "checking; a shed (overloaded) stream is verified "
                 "offline from its journal."),
        opt("--abort-on-violation", action="store_true",
            help="With --online: abort the run as soon as the "
                 "streaming checker confirms a nonlinearizable "
                 "prefix, saving the remaining cluster time."),
        opt("--max-recovery-retries", type=int, default=None,
            metavar="N",
            help="Device-fault recovery budget for the checkers: a "
                 "classified backend fault (OOM, device loss, compile "
                 "failure, wedged sync, attestation corruption) is "
                 "absorbed and retried down the recovery ladder at "
                 "most N times per checking entry before falling back "
                 "to the host mirror (default 3)."),
        opt("--tier", default=None, choices=["full", "screen"],
            help="Verification tier: 'screen' runs the O(n) "
                 "invariant screen over every history and escalates "
                 "to the full WGL/Elle device search only on "
                 "suspicion or a sampled fraction (see "
                 "--screen-sample); 'full' (default) always runs the "
                 "full search."),
        opt("--screen-sample", type=float, default=None,
            metavar="FRACTION",
            help="With --tier screen: the fraction of clean "
                 "(suspicion-free) histories that still escalate to "
                 "a full check, auditing the screen's blind spots "
                 "(default 0.05; scaled down for histories whose "
                 "modeled full-check cost is high)."),
    ]


def tarball_opt(default: str) -> dict:
    """--tarball URL option (`cli.clj:113-125`)."""
    return opt("--tarball", metavar="URL", default=default,
               help="URL for the DB package to install (file://, "
                    "http://, or https://, ending .tar/.tgz/.zip).")


class _Parser(argparse.ArgumentParser):
    """argparse, but invalid arguments exit 254 (`cli.clj:324-326`)."""

    def error(self, message):
        self.print_usage(sys.stderr)
        print(f"{self.prog}: error: {message}", file=sys.stderr)
        raise SystemExit(254)


def build_parser(prog: str, spec: list[dict]) -> _Parser:
    p = _Parser(prog=prog)
    for o in spec:
        args = [s for s in (o.get("short"), o["long"]) if s]
        kw = {k: v for k, v in o.items() if k not in ("short", "long")}
        p.add_argument(*args, **kw)
    return p


# -- option post-processing (`cli.clj:150-254`) -----------------------------

def parse_concurrency(opts: dict, key: str = "concurrency") -> dict:
    """'3n' -> 3 * node count; plain integers pass through."""
    c = str(opts[key])
    m = re.fullmatch(r"(\d+)(n?)", c)
    if not m:
        raise ValueError(f"--{key} {c} should be an integer optionally "
                         "followed by n")
    unit = len(opts["nodes"]) if m.group(2) == "n" else 1
    opts[key] = int(m.group(1)) * unit
    return opts


def parse_nodes(opts: dict) -> dict:
    """Merge --node / --nodes / --nodes-file into opts['nodes']
    (`cli.clj:170-205`)."""
    node = opts.pop("node", None)
    nodes = opts.pop("nodes", None)
    nodes_file = opts.pop("nodes_file", None)
    if node is None and not (nodes or nodes_file):
        node = list(DEFAULT_NODES)
    from_file = []
    if nodes_file:
        with open(nodes_file) as f:
            from_file = [ln.strip() for ln in f if ln.strip()]
    merged = list(from_file) + list(nodes or []) + list(node or [])
    dupes = sorted({n for n in merged if merged.count(n) > 1})
    if dupes:
        # complain early: a duplicated node would open two control
        # sessions to the same host and only fail much later as a
        # port-bind error on the node
        raise ValueError(f"node(s) listed more than once: "
                         f"{', '.join(dupes)}")
    opts["nodes"] = merged
    return opts


def rename_ssh_options(opts: dict) -> dict:
    """Move SSH options under opts['ssh'] (`cli.clj:223-242`)."""
    opts["ssh"] = {
        "dummy": bool(opts.pop("no_ssh", False)),
        "username": opts.pop("username", "root"),
        "password": opts.pop("password", "root"),
        "strict-host-key-checking":
            bool(opts.pop("strict_host_key_checking", False)),
        "private-key-path": opts.pop("ssh_private_key", None),
    }
    return opts


def test_opt_fn(opts: dict) -> dict:
    """The standard option pipeline (`cli.clj:245-254`)."""
    opts = rename_ssh_options(opts)
    opts["leave-db-running?"] = bool(opts.pop("leave_db_running", False))
    opts["logging"] = {"json?": bool(opts.pop("logging_json", False))}
    opts["store-dir"] = opts.pop("store_dir", "store")
    if "time_limit" in opts:
        opts["time-limit"] = opts.pop("time_limit")
    if "test_count" in opts:
        opts["test-count"] = opts.pop("test_count")
    parse_nodes(opts)
    parse_concurrency(opts)
    # argparse stores --some-flag as some_flag; test maps use the
    # hyphenated spelling throughout (a test *is* a map, keyed like the
    # reference's :some-flag keywords) — rename every remaining
    # underscore key so suite opt-specs can't silently miss
    renamed = []
    for k in [k for k in opts if isinstance(k, str) and "_" in k]:
        hy = k.replace("_", "-")
        if hy not in opts:
            opts[hy] = opts.pop(k)
            renamed.append(k)
    if renamed:
        # visible at debug level so an opt_fn that deliberately reads
        # an underscore key can see why it stopped matching
        log.debug("renamed underscore option keys to hyphenated: %s",
                  sorted(renamed))
    return opts


# -- runner -----------------------------------------------------------------

def run(subcommands: dict, argv: Optional[list[str]] = None) -> None:
    """Parse argv and dispatch to a subcommand spec: a dict with
    'opt_spec' (list), 'opt_fn', 'usage', and 'run' (fn(options dict))
    (`cli.clj:258-334`). Exits via SystemExit with the documented
    codes."""
    argv = list(sys.argv[1:] if argv is None else argv)
    command = argv[0] if argv else None
    try:
        if command not in subcommands:
            print("Usage: PROG COMMAND [OPTIONS ...]")
            print("Commands:", ", ".join(sorted(subcommands)))
            raise SystemExit(254)
        spec = subcommands[command]
        parser = build_parser(command, spec.get("opt_spec") or [])
        if spec.get("usage"):
            parser.usage = spec["usage"]
        opts = vars(parser.parse_args(argv[1:]))
        opts["argv"] = argv
        opt_fn = spec.get("opt_fn")
        if opt_fn:
            try:
                opts = opt_fn(opts)
            except (ValueError, OSError) as e:
                # option post-processing failures are user errors, not
                # internal crashes: report and exit 254 per the contract
                print(e, file=sys.stderr)
                raise SystemExit(254)
        runner = spec.get("run") or (lambda o: _pprint.pprint(o))
        runner(opts)
        raise SystemExit(0)
    except SystemExit:
        raise
    except Exception:
        log.critical("Oh jeez, I'm sorry, Jepsen broke. Here's why:",
                     exc_info=True)
        raise SystemExit(255)


def _exit_for_validity(valid) -> Optional[int]:
    from .checker import UNKNOWN
    if valid is False:
        return 1
    if valid == UNKNOWN:
        return 2
    return None


def _resolve_opt_fn(opts: dict):
    """Compose the standard pipeline with a suite's opt_fn, or replace
    it entirely via opt_fn_ (`cli.clj:381-387`)."""
    opt_fn = test_opt_fn
    if opts.get("opt_fn"):
        f = opts["opt_fn"]
        opt_fn = (lambda base: lambda o: f(base(o)))(opt_fn)
    return opts.get("opt_fn_") or opt_fn


def _enable_compile_cache(options: dict) -> None:
    """Persistent JAX compilation cache for the CLI runner, under the
    run's store directory (bench.py has used the same lever for its
    per-section subprocesses since r05: the cache is what keeps repeat
    invocations from re-paying every kernel compile). Env-gated via
    JEPSEN_TPU_COMPILE_CACHE=0 / an explicit JAX_COMPILATION_CACHE_DIR
    — see _platform.enable_compilation_cache."""
    import os

    from ._platform import enable_compilation_cache

    store_dir = options.get("store-dir") or options.get("store_dir")
    d = enable_compilation_cache(
        os.path.join(store_dir, ".jax_cache") if store_dir else None)
    if d:
        log.info("JAX persistent compilation cache: %s", d)


def single_test_cmd(opts: dict) -> dict:
    """Builds the `test` and `analyze` commands around a test_fn
    (`cli.clj:355-430`). Options: opt_spec (extra spec entries),
    opt_fn (composed after test_opt_fn), opt_fn_ (replaces it),
    tarball (default URL), usage, test_fn."""
    from . import core

    spec = merge_opt_specs(test_opt_spec(), opts.get("opt_spec") or [])
    if opts.get("tarball"):
        spec = merge_opt_specs(spec, [tarball_opt(opts["tarball"])])
    opt_fn = _resolve_opt_fn(opts)
    test_fn = opts["test_fn"]
    usage = opts.get("usage") or TEST_USAGE

    def run_test(options):
        log.info("Test options:\n%s", _pprint.pformat(options))
        _enable_compile_cache(options)
        # test_count fallback: an opt_fn_ override replaces the pipeline
        # that remaps argparse's test_count to test-count
        for _ in range(options.get("test-count",
                                   options.get("test_count", 1))):
            test = core.run(test_fn(options))
            code = _exit_for_validity(
                (test.get("results") or {}).get("valid?"))
            if code is not None:
                raise SystemExit(code)

    def run_analyze(options):
        from . import store
        log.info("Test options:\n%s", _pprint.pformat(options))
        _enable_compile_cache(options)
        cli_test = test_fn(options)
        latest = store.latest(cli_test.get("store-dir", "store"))
        if latest is None:
            raise RuntimeError("Not sure what the last test was")
        stored = store.load_test(latest)
        if stored.get("name") != cli_test.get("name"):
            raise RuntimeError(
                f"Stored test ({stored.get('name')}) and CLI test "
                f"({cli_test.get('name')}) have different names; aborting")
        if stored.get("salvaged-from-journal"):
            # crashed/killed run: the checkable prefix came from the
            # write-ahead journal; its tail may be pending invocations
            h = stored["history"]  # load_test set it alongside the flag
            log.warning(
                "analyzing a history salvaged from journal.jsonl "
                "(%d ops, %d pending invocations); the run died before "
                "writing history.jsonl.gz", len(h), len(h.pending()))
        stored.pop("results", None)
        test = {**cli_test, **stored}
        core.analyze(test)

    return {
        "test": {"opt_spec": spec, "opt_fn": opt_fn, "usage": usage,
                 "run": run_test},
        "analyze": {"opt_spec": spec, "opt_fn": opt_fn, "usage": usage,
                    "run": run_analyze},
    }


def test_all_run_tests(tests) -> dict:
    """Run tests, returning {outcome: [store paths]} where outcome is
    True/False/'unknown'/'crashed' (`cli.clj:432-448`)."""
    from . import core, store
    out: dict = {}
    for test in tests:
        try:
            # inside the try: a test map prepare_test rejects (e.g.
            # duplicate nodes) records as 'crashed' without aborting
            # the rest of the sweep (dir_name tolerates the missing
            # start-time)
            test = core.prepare_test(test)
            done = core.run(test)
            key = (done.get("results") or {}).get("valid?")
        except Exception:
            log.warning("Test crashed", exc_info=True)
            key = "crashed"
        out.setdefault(key, []).append(store.dir_name(test))
    return out


def test_all_print_summary(results: dict) -> dict:
    """(`cli.clj:450-478`)"""
    from .checker import UNKNOWN
    print("\n")
    for key, heading in ((True, "Successful tests"),
                         (UNKNOWN, "Indeterminate tests"),
                         ("crashed", "Crashed tests"),
                         (False, "Failed tests")):
        if results.get(key):
            print(f"\n# {heading}\n")
            for path in results[key]:
                print(path)
    print()
    print(len(results.get(True, [])), "successes")
    print(len(results.get(UNKNOWN, [])), "unknown")
    print(len(results.get("crashed", [])), "crashed")
    print(len(results.get(False, [])), "failures")
    return results


def test_all_exit(results: dict) -> None:
    """255 if any crashed, 2 if unknown, 1 if invalid, else 0
    (`cli.clj:480-488`)."""
    from .checker import UNKNOWN
    if results.get("crashed"):
        raise SystemExit(255)
    if results.get(UNKNOWN):
        raise SystemExit(2)
    if results.get(False):
        raise SystemExit(1)
    raise SystemExit(0)


def test_all_cmd(opts: dict) -> dict:
    """The `test-all` command around a tests_fn producing a sequence of
    tests (`cli.clj:490-518`)."""
    spec = merge_opt_specs(test_opt_spec(), opts.get("opt_spec") or [])
    opt_fn = _resolve_opt_fn(opts)
    tests_fn = opts["tests_fn"]

    def run_all(options):
        log.info("CLI options:\n%s", _pprint.pformat(options))
        test_all_exit(test_all_print_summary(
            test_all_run_tests(tests_fn(options))))

    return {"test-all": {"opt_spec": spec, "opt_fn": opt_fn,
                         "usage": "Runs all tests", "run": run_all}}


def serve_cmd() -> dict:
    """The `serve` web-server command (`cli.clj:336-353`)."""
    def run_serve(options):
        from . import web
        server = web.serve(options)
        log.info("Listening on http://%s:%s/",
                 options.get("host"), server.server_address[1])
        print(f"Listening on http://{options.get('host')}:"
              f"{server.server_address[1]}/")
        try:
            while True:
                _time.sleep(1)
        except KeyboardInterrupt:
            server.shutdown()

    def serve_opt_fn(o):
        o["store-dir"] = o.pop("store_dir", "store")
        return o

    return {"serve": {
        "opt_spec": [
            opt("--host", "-b", default="0.0.0.0",
                help="Hostname to bind to"),
            opt("--port", "-p", type=int, default=8080,
                help="Port number to bind to"),
            opt("--store-dir", default="store", metavar="DIR",
                help="Store directory to serve"),
        ],
        "opt_fn": serve_opt_fn,
        "run": run_serve,
    }}


def _service_status(addr: str) -> int:
    """`jepsen-tpu service status ADDR`: query a running service's
    `status` socket verb and pretty-print per-stream state, ladder
    tier, budget capacity, and calibration coefficients."""
    import json as _json

    from . import service as _service
    try:
        sock = _service._connect(addr)
    except OSError as e:
        print(f"service {addr}: unreachable ({e})", file=sys.stderr)
        return 1
    try:
        sock.sendall(b'{"type": "status", "id": 1}\n')
        with sock.makefile("r", encoding="utf-8") as rf:
            line = rf.readline()
    finally:
        sock.close()
    try:
        st = (_json.loads(line) or {}).get("status") or {}
    except ValueError:
        print(f"service {addr}: bad reply {line!r}", file=sys.stderr)
        return 1
    print(f"service {st.get('state', '?')}, "
          f"uptime {st.get('uptime_s', 0):g}s, "
          f"{st.get('admitted-total', 0)} admitted, "
          f"{st.get('refused-total', 0)} refused")
    streams = st.get("streams") or {}
    if streams:
        print("streams:")
    for name in sorted(streams):
        s = streams[name]
        extra = ""
        if s.get("violation"):
            extra += "  VIOLATION"
        if s.get("suspicion"):
            extra += f"  suspicion={s['suspicion']:g}"
        if s.get("shed-reason"):
            extra += f"  shed: {s['shed-reason']}"
        print(f"  {name:32s} state={s.get('state', '?'):10s} "
              f"tier={s.get('ladder-tier', 'full'):24s} "
              f"queue={s.get('queue-depth', 0):<6d} "
              f"ops={s.get('ops-fed', 0)}{extra}")
    b = st.get("budget") or {}
    if b:
        line = (f"budget: {b.get('available', 0):.3g}/"
                f"{b.get('capacity', 0):.3g} "
                f"{b.get('unit', 'element-ops')} "
                f"(max {b.get('initial', 0):.3g}")
        if b.get("ooms"):
            line += f", {b['ooms']} ooms"
        if b.get("cuts"):
            line += f", {b['cuts']} cuts"
        if b.get("p95-chunk-latency-s") is not None:
            line += f", p95 {b['p95-chunk-latency-s']:.3g}s"
        print(line + ")")
    lad = st.get("ladder") or {}
    tiers = lad.get("tiers") or {}
    if lad:
        parts = [f"{n} {t}" for t, n in tiers.items() if n]
        print(f"ladder: {', '.join(parts) if parts else 'no streams'}"
              f"; {lad.get('transitions', 0)} transitions"
              + ("" if lad.get("adaptive", True)
                 else " (static budget)"))
    cal = st.get("calibration") or {}
    coeffs = cal.get("coefficients") or {}
    if coeffs:
        parts = [f"{v} {c['seconds-per-elementop']:.3g} s/elementop "
                 f"(n={c['observations']})"
                 for v, c in sorted(coeffs.items())]
        print(f"calibration ({cal.get('platform', '?')}): "
              + ", ".join(parts))
    else:
        print(f"calibration ({cal.get('platform', '?')}): cold "
              "(modeled element-op pricing)")
    return 0


def service_cmd() -> dict:
    """The persistent-verification-service command: a daemon that
    accepts live journal streams from many concurrent runs over a
    local socket (`run --service ADDR`) and/or by tail-following a
    store directory, multiplexing them into per-stream online
    checkers (jepsen_tpu/service.py). SIGTERM drains gracefully:
    every stream's carry is checkpointed and a restarted service
    resumes from the manifests."""
    def run_service(options):
        from . import calibrate as _calibrate, service as _service
        action = list(options.get("action") or [])
        if action:
            if action[0] != "status" or len(action) != 2:
                print("usage: jepsen-tpu service status ADDR",
                      file=sys.stderr)
                raise SystemExit(2)
            raise SystemExit(_service_status(action[1]))
        # the measured cost model: persisted next to the compile
        # cache, loaded at start, saved back at drain — a restarted
        # fleet prices work in measured device-seconds from its
        # first chunk (jepsen_tpu/calibrate.py)
        cal = _calibrate.Calibration.load()
        if cal.coefficients():
            log.info("calibration loaded: %s", cal.coefficients())
        _calibrate.activate(cal)
        svc = _service.VerificationService(
            max_streams=options.get("max_streams", 64),
            budget_elementops=float(
                options.get("budget_elementops") or
                _service.DEFAULT_BUDGET_ELEMENTOPS),
            calibration=cal,
            adaptive=not options.get("static_budget"))
        svc.calibration_path = _calibrate.default_path(cal.platform)
        standby = options.get("standby")
        if standby and not options.get("watch"):
            print("--standby requires --watch DIR (the shared store "
                  "root the replicas fence over)", file=sys.stderr)
            raise SystemExit(2)
        msrv = None
        if options.get("metrics_port") is not None:
            from . import telemetry
            mhost = options.get("metrics_host") or "127.0.0.1"
            msrv = telemetry.serve_metrics(
                int(options["metrics_port"]), host=mhost,
                healthz=svc.status)
            mport = msrv.server_address[1]
            log.info("metrics on http://%s:%d/metrics "
                     "(/healthz = service status)", mhost, mport)
            print(f"Metrics listening on :{mport}/metrics")
        svc.install_sigterm()
        if standby:
            sb = _service.Standby(
                svc, standby, options["watch"],
                bind=options.get("bind") or "127.0.0.1:0")
            print(f"Standby replica watching primary {standby} "
                  f"(store {options['watch']})")
            bound = sb.run()    # blocks until promotion (or drain)
            if bound is None:
                svc.stop()
                if msrv is not None:
                    msrv.shutdown()
                return
        else:
            if options.get("watch"):
                # claim the store and resume any streams a crashed
                # predecessor orphaned — then keep tail-following
                recovered = svc.recover(options["watch"])
                if recovered:
                    print(f"Recovered {len(recovered)} orphaned "
                          f"stream(s) from {options['watch']}")
                svc.watch(options["watch"])
                log.info("watching journals under %s",
                         options["watch"])
            bound = svc.serve(options.get("bind") or "127.0.0.1:0")
        print(f"Verification service listening on {bound}")
        try:
            while not svc.drained.is_set():
                _time.sleep(0.5)
        except KeyboardInterrupt:
            svc.drain()
        svc.stop()
        if msrv is not None:
            msrv.shutdown()

    return {"service": {
        "opt_spec": [
            opt("action", nargs="*", metavar="ACTION",
                help="Optional subaction: `status ADDR` queries a "
                     "running service and pretty-prints per-stream "
                     "state, ladder tier, budget, and calibration."),
            opt("--bind", "-b", default="127.0.0.1:0", metavar="ADDR",
                help="host:port (port 0 picks a free port) or a unix "
                     "socket path to listen on"),
            opt("--watch", metavar="DIR", default=None,
                help="Also tail-follow journals under this store "
                     "directory. On start, recover() resumes any "
                     "orphaned runs from their durable checkpoints "
                     "(crashed or drained predecessors alike)."),
            opt("--standby", metavar="ADDR", default=None,
                help="Run as a warm replica: probe ADDR (a primary's "
                     "socket address or its http://.../healthz), and "
                     "on sustained failure fence it via the store-"
                     "level epoch file, recover its streams, and "
                     "serve. Requires --watch DIR (the shared store)."),
            opt("--max-streams", type=int, default=64, metavar="N",
                help="Admission cap on concurrently attached runs."),
            opt("--budget-elementops", type=float, default=None,
                metavar="N",
                help="Global in-flight chunk budget, expressed in "
                     "cost-model element-ops and priced into device-"
                     "seconds through the calibration (AIMD-tuned at "
                     "runtime unless --static-budget)."),
            opt("--static-budget", action="store_true",
                help="Disable the adaptive controller: no AIMD "
                     "capacity tuning and no degradation ladder (OOM "
                     "halving/restore still applies). The bench A/B "
                     "lever."),
            opt("--metrics-port", type=int, default=None, metavar="P",
                help="Serve Prometheus metrics at :P/metrics and the "
                     "service status() JSON at :P/healthz (port 0 "
                     "picks a free one). Unset = no HTTP listener; "
                     "the socket 'metrics' verb still answers."),
            opt("--metrics-host", default="127.0.0.1", metavar="HOST",
                help="Interface for --metrics-port (default loopback, "
                     "matching --bind's posture; use 0.0.0.0 to let a "
                     "remote Prometheus scrape)."),
        ],
        "usage": "Runs the persistent verification service",
        "run": run_service,
    }}


def staticcheck_cmd() -> dict:
    """`jepsen-tpu staticcheck` — the repo's static-analysis gate
    (tools/staticcheck, doc/static_analysis.md) as a CLI subcommand.
    A thin forwarder to `python -m tools.staticcheck`: same flags,
    same exit codes (0 clean/baselined, 1 with findings). Only
    available from a source checkout — the analyzers check the tree,
    so there is nothing to run against an installed package."""
    def run_staticcheck(options):
        import os

        repo = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        if not os.path.isdir(os.path.join(repo, "tools",
                                          "staticcheck")):
            print("staticcheck: tools/staticcheck not found next to "
                  "the jepsen_tpu package (requires a source "
                  "checkout)", file=sys.stderr)
            raise SystemExit(254)
        if repo not in sys.path:
            sys.path.insert(0, repo)
        from tools.staticcheck.driver import main as sc_main

        argv = list(options.get("targets") or [])
        if options.get("only"):
            argv += ["--only", options["only"]]
        if options.get("baseline"):
            argv += ["--baseline", options["baseline"]]
        if options.get("write_baseline"):
            argv.append("--write-baseline")
        if options.get("summary_json"):
            argv.append("--summary-json")
        raise SystemExit(sc_main(argv))

    return {"staticcheck": {
        "opt_spec": [
            opt("targets", nargs="*", metavar="TARGET",
                help="Files/dirs to check (default: the whole tree)"),
            opt("--only", metavar="ANALYZERS",
                help="Comma-separated analyzer subset (style, "
                     "metrics, device-sync, locks, retrace)"),
            opt("--baseline", metavar="PATH",
                help="Baseline file (default: "
                     "tools/staticcheck/baseline.txt)"),
            opt("--write-baseline", action="store_true",
                help="Rewrite the baseline from current findings"),
            opt("--summary-json", action="store_true",
                help="Emit one machine-readable JSON summary line"),
        ],
        "usage": "Runs the static-analysis gate "
                 "(doc/static_analysis.md)",
        "run": run_staticcheck,
    }}


def search_cmd() -> dict:
    """`jepsen-tpu search` — coverage-guided scenario search over
    generator/nemesis schedules (doc/search.md). Simulates genome
    populations, accumulates schedule coverage, escalates suspicious
    histories to the full checker, and shrinks found violations to a
    minimal reproducing scenario. Exits 0 when the budget ends with no
    violation, 1 when one was found (its minimized genome is in the
    output and the --store-dir artifact)."""
    def run_search_cmd(options):
        import json as _json

        from . import report
        from .search.driver import SearchConfig, run_search
        from .search.scenario import BUGS, SCENARIOS

        if options.get("workload") not in SCENARIOS:
            print(f"unknown workload {options.get('workload')!r}; "
                  f"have {sorted(SCENARIOS)}", file=sys.stderr)
            raise SystemExit(254)
        if options.get("bug") and options["bug"] not in BUGS:
            print(f"unknown bug {options['bug']!r}; "
                  f"have {sorted(BUGS)}", file=sys.stderr)
            raise SystemExit(254)
        resume = options.get("resume")
        if resume and not os.path.exists(
                os.path.join(resume, "search.json")):
            print(f"--resume: no search.json under {resume!r}",
                  file=sys.stderr)
            raise SystemExit(254)
        cfg = SearchConfig(
            workload=options["workload"],
            generations=options["generations"],
            population=options["population"],
            seed=options["seed"],
            workers=options["workers"],
            strategy=options["strategy"],
            escalate=options["escalate"],
            bug=options.get("bug") or None,
            max_sims=options.get("max_sims"),
            sample=options["sample"],
            store_dir=options.get("store_dir") or resume,
            resume_dir=resume,
        )
        results = run_search(cfg)
        print(_json.dumps(results, indent=2, sort_keys=True))
        line = report.search_line(results)
        if line:
            print(line, file=sys.stderr)
        raise SystemExit(1 if results["found"] else 0)

    return {"search": {
        "opt_spec": [
            opt("--workload", "-w", default="register",
                help="Search scenario (jepsen_tpu.search.scenario"
                     ".SCENARIOS)"),
            opt("--generations", "-g", type=int, default=10,
                help="Search generations"),
            opt("--population", "-k", type=int, default=50,
                help="Genomes per generation"),
            opt("--seed", "-s", type=int, default=45100,
                help="Search seed (sampling + mutation)"),
            opt("--workers", type=int, default=4,
                help="Simulation worker threads"),
            opt("--strategy", default="guided",
                choices=["guided", "random"],
                help="guided (coverage feedback) or random "
                     "(uniform draws, the A/B baseline)"),
            opt("--escalate", default="none",
                choices=["none", "host", "batch", "service"],
                help="Full-checker escalation path for suspicious "
                     "histories"),
            opt("--bug", default=None,
                help="Planted executor bug "
                     "(jepsen_tpu.search.scenario.BUGS; demos/tests)"),
            opt("--max-sims", type=int, default=None,
                help="Total simulation budget (default: unlimited "
                     "within generations x population + shrinking)"),
            opt("--sample", type=float, default=0.0,
                help="Clean-history audit escalation fraction"),
            opt("--store-dir", default=None, metavar="DIR",
                help="Write search.json + coverage.bin here"),
            opt("--resume", default=None, metavar="DIR",
                help="Continue a prior search from its store dir "
                     "(reloads search.json + coverage.bin; restored "
                     "simulations keep counting against --max-sims; "
                     "artifacts are rewritten there unless "
                     "--store-dir overrides)"),
        ],
        "usage": "Coverage-guided scenario search (doc/search.md)",
        "run": run_search_cmd,
    }}


def chaos_cmd() -> dict:
    """`jepsen-tpu chaos` — self-chaos: coverage-guided fault-schedule
    fuzzing of the verification pipeline itself (doc/robustness.md,
    "Self-chaos"). Executes mutated backend-fault + lifecycle
    schedules against a live VerificationService running a fixed
    workload and holds every outcome to the chaos oracles; failures
    shrink to a minimal schedule. Exits 0 when all oracles stayed
    green, 1 when a failure was found (its minimized schedule is in
    the output and the --store-dir artifact)."""
    def run_chaos_cmd(options):
        import json as _json

        from . import report
        from .chaos import ChaosConfig, run_chaos
        from .chaos.driver import WORKLOADS

        if options.get("workload") not in WORKLOADS:
            print(f"unknown workload {options.get('workload')!r}; "
                  f"have {sorted(WORKLOADS)}", file=sys.stderr)
            raise SystemExit(254)
        cfg = ChaosConfig(
            workload=options["workload"],
            ops=options["ops"],
            budget=options["budget"],
            seed=options["seed"],
            strategy=options["strategy"],
            deadline_s=options["deadline_s"],
            shrink=not options.get("no_shrink"),
            store_dir=options.get("store_dir"),
        )
        results = run_chaos(cfg)
        print(_json.dumps(results, indent=2, sort_keys=True))
        line = report.chaos_line(results)
        if line:
            print(line, file=sys.stderr)
        raise SystemExit(1 if results["found"] else 0)

    return {"chaos": {
        "opt_spec": [
            opt("--workload", "-w", default="register",
                help="Chaos workload (jepsen_tpu.chaos.driver"
                     ".WORKLOADS)"),
            opt("--ops", type=int, default=256,
                help="Workload ops per schedule"),
            opt("--budget", "-n", type=int, default=40,
                help="Schedule executions (shrink re-runs included)"),
            opt("--seed", "-s", type=int, default=45100,
                help="Chaos seed (sampling + mutation)"),
            opt("--strategy", default="guided",
                choices=["guided", "random"],
                help="guided (coverage feedback) or random "
                     "(uniform draws, the A/B baseline)"),
            opt("--deadline-s", type=float, default=120.0,
                help="Per-schedule verdict deadline (the watchdog "
                     "oracle)"),
            opt("--no-shrink", action="store_true",
                help="Report oracle failures unminimized"),
            opt("--store-dir", default=None, metavar="DIR",
                help="Write chaos.json + coverage.bin here"),
        ],
        "usage": "Self-chaos fault-schedule fuzzing "
                 "(doc/robustness.md)",
        "run": run_chaos_cmd,
    }}


def main(argv: Optional[list[str]] = None) -> None:
    logging.basicConfig(level=logging.INFO)
    run({**serve_cmd(), **service_cmd(), **staticcheck_cmd(),
         **search_cmd(), **chaos_cmd()}, argv)


if __name__ == "__main__":
    main()
