"""Web server frontend for browsing test results.

Reference: `jepsen/src/jepsen/web.clj` — a home page tabulating every
stored run with validity-colored cells (:25-135), a directory/file
browser with content types (:136-352), and whole-run zip downloads
(:253-311). Ring/http-kit become the standard library's threading HTTP
server; the route structure (`/` and `/files/...`, `<run>.zip`) is
preserved so bookmarks from the reference work unchanged.
"""

from __future__ import annotations

import html
import io
import json
import logging
import mimetypes
import os
import threading
import urllib.parse
import zipfile
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from . import store, telemetry

log = logging.getLogger(__name__)

_M_REQUESTS = telemetry.counter(
    "jepsen_tpu_web_requests_total",
    "Results-web requests by route", ("route",))

COLORS = {"ok": "#6DB6FE", "info": "#FFAA26", "fail": "#FEB5DA",
          None: "#eaeaea"}

VALID_COLOR = {True: COLORS["ok"], "unknown": COLORS["info"],
               False: COLORS["fail"]}


def valid_color(valid) -> str:
    return VALID_COLOR.get(valid, COLORS[None])


def url_encode_path_components(p: str) -> str:
    """URL-encode individual path components, leaving / alone
    (`web.clj:41-45`)."""
    return "/".join(urllib.parse.quote(c) for c in p.split("/"))


def fast_tests(base: str) -> list[dict]:
    """Abbreviated test maps: name, start-time, results (or
    {'valid?': 'incomplete'} for unparsable/unfinished runs)
    (`web.clj:47-68`)."""
    out = []
    for name, runs in store.tests(base).items():
        for t, d in runs.items():
            entry = {"name": name, "start-time": t, "dir": d}
            try:
                with open(os.path.join(d, "results.json")) as f:
                    entry["results"] = json.load(f)
            except (OSError, ValueError):
                entry["results"] = {"valid?": "incomplete"}
                # an unfinished run a verification service touched:
                # surface what the service left behind — a deferred
                # (shed) marker, a resume manifest from a drain, or
                # already-streamed verdicts awaiting analyze
                try:
                    sr = store.load_streamed_results(d)
                except (OSError, ValueError):
                    sr = None
                if isinstance(sr, dict) and sr.get("deferred"):
                    entry["results"]["service"] = "deferred"
                elif os.path.exists(os.path.join(
                        d, store.SERVICE_SUBDIR, "resume.json")):
                    entry["results"]["service"] = "drained"
                elif sr:
                    entry["results"]["service"] = "streamed"
            out.append(entry)
    return out


def _file_url(*components) -> str:
    return url_encode_path_components(
        "/files/" + "/".join(str(c) for c in components if c != ""))


def recovery_note(r: dict) -> str:
    """Validity-cell suffix when any checker result in the map carries
    a device-fault or tier-1 trail: '(degraded)' lost a verdict to
    backend faults, '(recovered)' faulted but resumed to a full
    verdict, '(escalated)' the tier-1 screen triggered a full check,
    '(screened)' the verdict came from the O(n) screen alone. Older
    stored results without these fields get no suffix."""
    subs = [r] + [v for v in r.values() if isinstance(v, dict)]
    if any(s.get("degraded") for s in subs):
        return " (degraded)"
    # dict-typed only: workload checkers reuse 'recovered' for their
    # own payloads (e.g. the set checker's recovered-element string)
    if any(isinstance(s.get("recovered"), dict) for s in subs):
        return " (recovered)"
    if any(isinstance(s.get("escalated"), dict) for s in subs):
        return " (escalated)"
    if any(s.get("screened") for s in subs):
        return " (screened)"
    # verification-service outcomes on not-yet-analyzed runs:
    # shed ('deferred' — analyze covers from the journal), drained
    # (a resume manifest awaits a restarted service), or streamed
    # verdicts awaiting adoption
    if r.get("service"):
        return f" (service: {r['service']})"
    return ""


def test_row(t: dict) -> str:
    r = t.get("results") or {}
    u = _file_url(t["name"], t["start-time"])
    valid = r.get("valid?")
    return (
        "<tr>"
        f'<td><a href="{u}">{html.escape(t["name"])}</a></td>'
        f'<td><a href="{u}">{html.escape(t["start-time"])}</a></td>'
        f'<td style="background: {valid_color(valid)}">'
        f'{html.escape(str(valid) + recovery_note(r))}</td>'
        f'<td><a href="{u}/results.json">results.json</a></td>'
        f'<td><a href="{u}/history.jsonl.gz">history</a></td>'
        f'<td><a href="{u}/jepsen.log">jepsen.log</a></td>'
        f'<td><a href="{u}.zip">zip</a></td>'
        "</tr>")


SORT_KEYS = {
    "name": lambda t: t["name"],
    "time": lambda t: t["start-time"],
    "valid": lambda t: str((t.get("results") or {}).get("valid?")),
}


def select_tests(tests: list[dict], params: dict) -> list[dict]:
    """Search/filter/sort the home-page rows (the reference's plan.md
    wants exactly these: search, sorting, filtering).

    params: q (substring match on name), valid (true/false/unknown/
    incomplete), sort (name|time|valid), dir (asc|desc)."""
    q = (params.get("q") or "").strip().lower()
    if q:
        tests = [t for t in tests if q in t["name"].lower()]
    want = (params.get("valid") or "").strip().lower()
    if want:
        tests = [
            t for t in tests
            if str((t.get("results") or {}).get("valid?")).lower() == want]
    key = SORT_KEYS.get(params.get("sort") or "time", SORT_KEYS["time"])
    default_desc = (params.get("sort") or "time") == "time"
    desc = {"asc": False, "desc": True}.get(
        (params.get("dir") or "").lower(), default_desc)
    return sorted(tests, key=key, reverse=desc)


def _sort_link(col: str, params: dict) -> str:
    cur = params.get("sort") or "time"
    cur_desc = (params.get("dir") or
                ("desc" if cur == "time" else "asc")) == "desc"
    nxt = "asc" if (col != cur or cur_desc) else "desc"
    qs = urllib.parse.urlencode(
        {k: v for k, v in {**params, "sort": col, "dir": nxt}.items()
         if v})
    arrow = (" ▼" if cur_desc else " ▲") if col == cur else ""
    return f'<a href="/?{qs}">{col.capitalize()}{arrow}</a>'


def home_page(base: str, params: dict | None = None) -> str:
    params = params or {}
    rows = select_tests(fast_tests(base), params)
    q = html.escape(params.get("q") or "", quote=True)
    valid = params.get("valid") or ""
    options = "".join(
        f'<option value="{v}"{" selected" if v == valid else ""}>'
        f"{label}</option>"
        for v, label in [("", "any validity"), ("true", "valid"),
                         ("false", "invalid"), ("unknown", "unknown"),
                         ("incomplete", "incomplete")])
    return (
        "<html><body><h1>Jepsen</h1>"
        '<p><a href="/metrics">metrics</a> (process telemetry, '
        "Prometheus text)</p>"
        '<form method="get" action="/">'
        f'<input type="text" name="q" value="{q}" '
        'placeholder="search test names">'
        f'<select name="valid">{options}</select>'
        '<input type="submit" value="filter">'
        "</form>"
        '<table cellspacing="3" cellpadding="3"><thead><tr>'
        f"<th>{_sort_link('name', params)}</th>"
        f"<th>{_sort_link('time', params)}</th>"
        f"<th>{_sort_link('valid', params)}</th><th>Results</th>"
        "<th>History</th><th>Log</th><th>Zip</th></tr></thead><tbody>"
        + "".join(test_row(t) for t in rows)
        + f"</tbody></table><p>{len(rows)} run(s)</p></body></html>")


def dir_listing(base: str, rel: str, full: str) -> str:
    """Directory browser page (`web.clj:136-250`). Directories holding a
    results.json get a validity-colored cell."""
    items = []
    for name in sorted(os.listdir(full)):
        p = os.path.join(full, name)
        u = _file_url(*(rel.split("/") if rel else []), name)
        if os.path.isdir(p):
            valid = None
            try:
                with open(os.path.join(p, "results.json")) as f:
                    valid = json.load(f).get("valid?")
                style = f' style="background: {valid_color(valid)}"'
            except (OSError, ValueError):
                style = ""
            items.append(f'<tr><td{style}><a href="{u}">{html.escape(name)}'
                         f"/</a></td></tr>")
        else:
            size = os.path.getsize(p)
            items.append(f'<tr><td><a href="{u}">{html.escape(name)}</a> '
                         f"({size} bytes)</td></tr>")
    up = _file_url(*(rel.split("/")[:-1] if rel else []))
    return ("<html><body>"
            f'<h1>{html.escape("/" + rel)}</h1>'
            f'<p><a href="/">home</a> | <a href="{up}">up</a> | '
            f'<a href="{_file_url(rel).rstrip("/")}.zip">zip</a></p>'
            f"<table>{''.join(items)}</table></body></html>")


def content_type(path: str) -> str:
    """Content types for store artifacts (`web.clj:312-324`)."""
    if path.endswith(".log") or path.endswith(".jsonl"):
        return "text/plain"
    if path.endswith(".svg"):
        return "image/svg+xml"
    guess, enc = mimetypes.guess_type(path)
    if enc == "gzip":
        return "application/gzip"
    return guess or "application/octet-stream"


def zip_dir(full: str) -> bytes:
    """Zip a run directory into memory (`web.clj:253-311`)."""
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
        for root, _dirs, files in os.walk(full):
            for f in files:
                p = os.path.join(root, f)
                z.write(p, os.path.relpath(p, os.path.dirname(full)))
    return buf.getvalue()


class Handler(BaseHTTPRequestHandler):
    base = store.DEFAULT_BASE

    def log_message(self, fmt, *args):  # route through logging, not stderr
        log.debug("web: " + fmt, *args)

    def _send(self, code: int, body: bytes, ctype: str = "text/html"):
        self.send_response(code)
        if ctype.startswith("text/") and "charset" not in ctype:
            # explicit utf-8: the reference serves latin-1-ish bytes
            # and its plan.md wants this fixed
            ctype += "; charset=utf-8"
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _resolve(self, rel: str) -> str | None:
        """Resolve a /files/ path inside the store, refusing traversal
        outside it."""
        full = os.path.realpath(os.path.join(self.base, rel))
        root = os.path.realpath(self.base)
        if full != root and not full.startswith(root + os.sep):
            return None
        return full

    def do_GET(self):  # noqa: N802 — http.server API
        split = urllib.parse.urlsplit(self.path)
        path = urllib.parse.unquote(split.path)
        if path in ("/", ""):
            _M_REQUESTS.labels(route="home").inc()
            params = {k: v[0]
                      for k, v in urllib.parse.parse_qs(split.query).items()}
            return self._send(
                200, home_page(self.base, params).encode())
        if path == "/metrics":
            # the process-wide registry snapshot: when analyze/serve
            # run in this process, its chunk/engine/recovery series
            # are scrapeable straight off the results UI
            _M_REQUESTS.labels(route="metrics").inc()
            return self._send(
                200, telemetry.prometheus_text().encode(),
                "text/plain; version=0.0.4")
        if path.startswith("/files"):
            _M_REQUESTS.labels(route="files").inc()
            rel = path[len("/files"):].strip("/")
            if rel.endswith(".zip"):
                full = self._resolve(rel[:-len(".zip")])
                if full and os.path.isdir(full):
                    return self._send(200, zip_dir(full), "application/zip")
            full = self._resolve(rel)
            if full is None:
                return self._send(403, b"forbidden", "text/plain")
            if os.path.isdir(full):
                return self._send(
                    200, dir_listing(self.base, rel, full).encode())
            if os.path.isfile(full):
                with open(full, "rb") as f:
                    return self._send(200, f.read(), content_type(full))
        return self._send(404, b"not found", "text/plain")


def serve(options: dict | None = None) -> ThreadingHTTPServer:
    """Start the web server in a daemon thread; returns the server
    (`web.clj:361-366`). Options: host, port, store-dir."""
    options = options or {}
    handler = type("BoundHandler", (Handler,),
                   {"base": options.get("store-dir", store.DEFAULT_BASE)})
    server = ThreadingHTTPServer(
        (options.get("host", "0.0.0.0"), int(options.get("port", 8080))),
        handler)
    t = threading.Thread(target=server.serve_forever, daemon=True,
                         name="jepsen web")
    t.start()
    return server
