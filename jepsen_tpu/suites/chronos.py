"""Chronos test suite — does the job scheduler actually run jobs when
it promised to?

Mirrors `/root/reference/chronos/src/jepsen/{chronos,chronos/checker,
mesosphere}.clj`: a Mesos master/slave + Zookeeper substrate, Chronos
on top, jobs submitted over the HTTP ISO8601 API whose shell commands
log their own start/end times into per-run tempfiles, a final read
that collects every run log from every node, and the *job-run
checker*: expand each job's schedule into target windows
[start, start+epsilon+forgiveness) and match runs to targets — every
target must be satisfied by a distinct completed run.

The reference matches runs to targets with a constraint solver
(`checker.clj:78-190`, loco); because the generator spaces targets so
they never overlap (interval > duration + epsilon + forgiveness,
`chronos.clj:196-206`), disjoint-interval greedy matching is exact and
O(n) — the solver generality is only needed for overlapping targets,
which this suite never produces."""

from __future__ import annotations

import datetime
import json
import logging
import time as _time
import urllib.request

from .. import checker, cli, client as jclient, control
from .. import db as jdb
from .. import generator as gen
from ..control import util as cu
from ..control.core import RemoteError
from ..os_ import debian
from . import std_opts, std_test

log = logging.getLogger(__name__)

PORT = 4400
JOB_DIR = "/tmp/chronos-test"
EPSILON_FORGIVENESS = 5   # checker.clj:26-28

DEFAULT_MESOS_VERSION = "0.23.0-1.0.debian81"
DEFAULT_CHRONOS_VERSION = "2.3.4-1.0.81.debian77"


class DB(jdb.DB, jdb.LogFiles):
    """Zookeeper + Mesos master/slave + Chronos
    (`mesosphere.clj:20-150`, `chronos.clj:55-80`)."""

    def __init__(self, mesos_version: str = DEFAULT_MESOS_VERSION,
                 chronos_version: str = DEFAULT_CHRONOS_VERSION):
        self.mesos_version = mesos_version
        self.chronos_version = chronos_version

    def setup(self, test, node):
        zk_connect = "zk://" + ",".join(
            f"{n}:2181" for n in test["nodes"]) + "/mesos"
        with control.su():
            debian.install({"mesos": self.mesos_version,
                            "zookeeper": "3.4.5+dfsg-2",
                            "chronos": self.chronos_version})
            myid = str(test["nodes"].index(node) + 1)
            cu.write_file(myid, "/etc/zookeeper/conf/myid")
            control.exec_("service", "zookeeper", "restart")
            cu.write_file(zk_connect, "/etc/mesos/zk")
            cu.write_file(str(len(test["nodes"]) // 2 + 1),
                          "/etc/mesos-master/quorum")
            control.exec_("service", "mesos-master", "restart")
            control.exec_("service", "mesos-slave", "restart")
            # lower the scheduler horizon so frequent jobs still run
            # (`chronos.clj:44-48`)
            cu.write_file("1", "/etc/chronos/conf/schedule_horizon")
            control.exec_("mkdir", "-p", JOB_DIR)
            control.exec_("service", "chronos", "restart")
            cu.await_tcp_port(PORT)

    def teardown(self, test, node):
        with control.su():
            for svc in ("chronos", "mesos-slave", "mesos-master",
                        "zookeeper"):
                try:
                    control.exec_("service", svc, "stop")
                except RemoteError:
                    pass
            cu.grepkill("chronos")
            try:
                control.exec_("rm", "-rf", JOB_DIR)
            except RemoteError:
                pass

    def log_files(self, test, node):
        return ["/var/log/mesos/mesos-master.INFO",
                "/var/log/messages"]


def db(mesos_version: str = DEFAULT_MESOS_VERSION,
       chronos_version: str = DEFAULT_CHRONOS_VERSION) -> DB:
    return DB(mesos_version, chronos_version)


def interval_str(job: dict) -> str:
    """ISO8601 repeating interval (`chronos.clj:101-107`)."""
    return (f"R{job['count']}/{job['start']}"
            f"/PT{job['interval']}S")


def command_str(job: dict) -> str:
    """The job logs its own name + start/end times to a tempfile
    (`chronos.clj:109-117`)."""
    return (f"MEW=$(mktemp -p {JOB_DIR}); "
            f"echo \"{job['name']}\" >> $MEW; "
            f"date -u +%s.%N >> $MEW; "
            f"sleep {job['duration']}; "
            f"date -u +%s.%N >> $MEW;")


class Client(jclient.Client):
    """Submit jobs over HTTP; read runs by catting every run log on
    every node (`chronos.clj:134-192`)."""

    def __init__(self, timeout_s: float = 10.0):
        self.timeout_s = timeout_s
        self.base: str | None = None
        self.node = None

    def open(self, test, node):
        c = Client(self.timeout_s)
        fn = test.get("chronos-url-fn")
        c.base = fn(node) if fn else f"http://{node}:{PORT}"
        c.node = node
        return c

    def add_job(self, job: dict):
        body = json.dumps({
            "name": str(job["name"]),
            "command": command_str(job),
            "schedule": interval_str(job),
            "scheduleTimeZone": "UTC",
            "owner": "jepsen@jepsen.io",
            "epsilon": f"PT{job['epsilon']}S",
            "mem": 1, "disk": 1, "cpus": 0.001, "async": False,
        }).encode()
        req = urllib.request.Request(
            self.base + "/scheduler/iso8601", data=body,
            headers={"Content-Type": "application/json"})
        urllib.request.urlopen(req, timeout=self.timeout_s).read()

    def read_runs(self, test) -> list:
        """Collect every run log from every node over the control
        sessions (`chronos.clj:161-172`)."""
        runs = []
        sessions = test.get("sessions") or {}
        for node, sess in sessions.items():
            with control.with_session(node, sess):
                try:
                    files = control.exec_("ls", JOB_DIR).split()
                except RemoteError:
                    continue
                for f in files:
                    try:
                        content = control.exec_(
                            "cat", f"{JOB_DIR}/{f}")
                    except RemoteError:
                        continue
                    lines = content.split("\n")
                    if not lines or not lines[0].strip():
                        continue
                    runs.append({
                        "node": node,
                        "name": int(lines[0]),
                        "start": float(lines[1])
                        if len(lines) > 1 and lines[1] else None,
                        "end": float(lines[2])
                        if len(lines) > 2 and lines[2] else None,
                    })
        return runs

    def invoke(self, test, op):
        try:
            if op["f"] == "add-job":
                self.add_job(op["value"])
                return {**op, "type": "ok"}
            if op["f"] == "read":
                return {**op, "type": "ok",
                        "value": self.read_runs(test),
                        "read-time": _time.time()}
            raise ValueError(f"unknown f {op['f']!r}")
        except (OSError, RemoteError) as e:
            return {**op, "type": "fail", "error": str(e)}


# -- the job-run checker (`checker.clj`) -------------------------------------

def job_targets(read_time: float, job: dict) -> list:
    """[start, deadline) windows for runs that must have begun by
    read_time (`checker.clj:30-47`)."""
    out = []
    finish = read_time - job["epsilon"] - job["duration"]
    t = job["start_epoch"]
    for _ in range(job["count"]):
        if t >= finish:
            break
        out.append((t, t + job["epsilon"] + EPSILON_FORGIVENESS))
        t += job["interval"]
    return out


def job_solution(read_time: float, job: dict, runs: list) -> dict:
    """Greedy disjoint-interval matching of completed runs to targets
    (`checker.clj:79-190`; exact here because the generator keeps
    targets disjoint)."""
    complete = sorted((r for r in runs if r.get("end")),
                      key=lambda r: r["start"])
    incomplete = [r for r in runs if not r.get("end")]
    targets = job_targets(read_time, job)
    solution = {}
    used = set()
    ri = 0
    valid = True
    for (start, end) in targets:
        hit = None
        while ri < len(complete):
            r = complete[ri]
            if r["start"] < start:
                ri += 1
                continue
            if r["start"] >= end:
                break
            hit = r
            used.add(id(r))
            ri += 1
            break
        solution[(start, end)] = hit
        if hit is None:
            valid = False
    return {
        "valid?": valid,
        "job": {k: job[k] for k in ("name", "count", "interval",
                                    "epsilon", "duration")},
        "solution": {f"{s:.0f}..{e:.0f}":
                     (None if r is None else r["start"])
                     for (s, e), r in solution.items()},
        "extra": [r["start"] for r in complete
                  if id(r) not in used][:16],
        "complete": len(complete),
        "incomplete": len(incomplete),
    }


class JobRunChecker(checker.Checker):
    """Every job's schedule must be satisfied by distinct completed
    runs (`checker.clj:191-214`)."""

    def check(self, test, hist, opts):
        jobs = [o["value"] for o in hist
                if o.get("type") == "ok" and o.get("f") == "add-job"]
        read = None
        for o in reversed(list(hist)):
            if o.get("type") == "ok" and o.get("f") == "read":
                read = o
                break
        if read is None:
            return {"valid?": "unknown", "error": "no final read"}
        read_time = read.get("read-time")
        if read_time is None:
            # no wall-clock on the read: unknown, never vacuously valid
            return {"valid?": "unknown",
                    "error": "final read carries no read-time"}
        runs_by_name: dict = {}
        for r in read["value"]:
            runs_by_name.setdefault(r["name"], []).append(r)
        solns = {j["name"]: job_solution(read_time, j,
                                         runs_by_name.get(j["name"],
                                                          []))
                 for j in jobs}
        return {
            "valid?": all(s["valid?"] for s in solns.values()),
            "jobs": solns,
            "job-count": len(jobs),
            "read-time": read_time,
        }


def add_job_gen(opts):
    """Jobs spaced so runs never overlap (`chronos.clj:194-216`)."""
    state = {"id": 0}

    def make(test, ctx):
        state["id"] += 1
        duration = gen.rng.randrange(10)
        epsilon = 10 + gen.rng.randrange(20)
        interval = (1 + duration + epsilon + EPSILON_FORGIVENESS
                    + gen.rng.randrange(30))
        # run logs record absolute epoch seconds (`date -u +%s.%N`),
        # so schedules must be absolute wall-clock ISO8601 datetimes
        # too (`chronos.clj:86-107`). Whole seconds: the ISO schedule
        # has second granularity, and a fractional start_epoch would
        # put the checker's windows fractionally *after* the scheduled
        # runs. Negative delays schedule jobs in the past — hermetic
        # tests use that to make run windows due immediately.
        start = float(int(_time.time() + opts.get("job-start-delay", 10)))
        iso = datetime.datetime.fromtimestamp(
            start, datetime.timezone.utc).strftime(
            "%Y-%m-%dT%H:%M:%SZ")
        return {"type": "invoke", "f": "add-job", "value": {
            "name": state["id"],
            "start_epoch": start,
            "start": iso,
            "count": 1 + gen.rng.randrange(99),
            "duration": duration,
            "epsilon": epsilon,
            "interval": interval,
        }}

    return make


def jobs_workload(opts) -> dict:
    return {
        "client": Client(),
        "generator": gen.stagger(
            opts.get("job-interval", 30), add_job_gen(opts)),
        "checker": JobRunChecker(),
        "final-generator": gen.once(
            {"type": "invoke", "f": "read", "value": None}),
    }


WORKLOADS = {"jobs": jobs_workload}


def chronos_test(opts: dict) -> dict:
    workload_name = opts.get("workload", "jobs")
    return std_test(
        opts, name=f"chronos-{workload_name}",
        db=db(opts.get("mesos-version", DEFAULT_MESOS_VERSION),
              opts.get("chronos-version", DEFAULT_CHRONOS_VERSION)),
        workload=WORKLOADS[workload_name](opts))


OPT_SPEC = std_opts(cli, WORKLOADS, "jobs") + [
    cli.opt("--mesos-version", default=DEFAULT_MESOS_VERSION),
    cli.opt("--chronos-version", default=DEFAULT_CHRONOS_VERSION),
    cli.opt("--job-interval", type=float, default=30,
            help="seconds between job submissions"),
]


def main(argv=None):
    cli.run({**cli.single_test_cmd({"test_fn": chronos_test,
                                    "opt_spec": OPT_SPEC}),
             **cli.serve_cmd()}, argv)


if __name__ == "__main__":
    main()
