"""Apache Ignite test suite — register and bank over the REST API.

Mirrors `/root/reference/ignite/src/jepsen/ignite{,/register,/bank}`:
zip-dist install with per-node spring XML carrying the cache config
(backups/mode/atomicity), topology-snapshot waits, and two workloads:

  * register: per-key read/write/cas on one cache —
    `register.clj:32-43` (the Java client's get/put/replace) maps to
    REST cmd=get/put/cas.
  * bank: transfers across account keys. The reference uses thick-
    client transactions (`bank.clj:27-45`); REST has no multi-key
    transactions, so this port keeps the reference's *test semantics*
    by storing all balances in one JSON value updated via cas — the
    conserved-total property the bank checker verifies is identical.

Hermetic tests run against `tests/fake_es_ignite.py`."""

from __future__ import annotations

import json
import logging
import urllib.parse
import urllib.request

from .. import cli, client as jclient, control, independent
from .. import db as jdb
from ..control import util as cu
from ..control.core import RemoteError
from ..os_ import debian
from ..workloads import bank as bank_w
from . import std_opts, std_test

log = logging.getLogger(__name__)

REST_PORT = 8080
SERVER_DIR = "/opt/ignite"
LOGFILE = f"{SERVER_DIR}/node.log"
DEFAULT_VERSION = "2.7.6"

SPRING_XML = """\
<?xml version="1.0" encoding="UTF-8"?>
<beans xmlns="http://www.springframework.org/schema/beans"
       xmlns:xsi="http://www.w3.org/2001/XMLSchema-instance"
       xsi:schemaLocation="http://www.springframework.org/schema/beans
       http://www.springframework.org/schema/beans/spring-beans.xsd">
  <bean id="ignite.cfg"
        class="org.apache.ignite.configuration.IgniteConfiguration">
    <property name="discoverySpi">
      <bean class="org.apache.ignite.spi.discovery.tcp.TcpDiscoverySpi">
        <property name="ipFinder">
          <bean class="org.apache.ignite.spi.discovery.tcp.ipfinder.vm.\
TcpDiscoveryVmIpFinder">
            <property name="addresses">
              <list>
{addresses}
              </list>
            </property>
          </bean>
        </property>
      </bean>
    </property>
    <property name="cacheConfiguration">
      <bean class="org.apache.ignite.configuration.CacheConfiguration">
        <property name="name" value="{cache}"/>
        <property name="cacheMode" value="{cache_mode}"/>
        <property name="atomicityMode" value="TRANSACTIONAL"/>
        <property name="backups" value="{backups}"/>
      </bean>
    </property>
  </bean>
</beans>
"""


class DB(jdb.DB, jdb.Process, jdb.LogFiles):
    """zip install + spring config + topology wait
    (`ignite.clj:60-160`)."""

    def __init__(self, version: str = DEFAULT_VERSION,
                 opts: dict | None = None):
        self.version = version
        self.opts = opts or {}

    def setup(self, test, node):
        debian.install_jdk11()
        with control.su():
            url = test.get("url") or (
                "https://archive.apache.org/dist/ignite/"
                f"{self.version}/apache-ignite-{self.version}-bin.zip")
            cu.install_archive(url, SERVER_DIR)
            addresses = "\n".join(
                f'                <value>{n}:47500..47509</value>'
                for n in test["nodes"])
            cu.write_file(SPRING_XML.format(
                addresses=addresses,
                cache=self.opts.get("cache", "JEPSEN"),
                cache_mode=self.opts.get("cache-mode", "REPLICATED"),
                backups=self.opts.get("backups", 2)),
                f"{SERVER_DIR}/server-ignite-{node}.xml")
            self.start(test, node)
            cu.await_tcp_port(REST_PORT)

    def start(self, test, node):
        with control.su():
            cu.start_daemon(
                {"logfile": LOGFILE,
                 "pidfile": f"{SERVER_DIR}/node.pid",
                 "chdir": SERVER_DIR},
                f"{SERVER_DIR}/bin/ignite.sh",
                f"{SERVER_DIR}/server-ignite-{node}.xml")

    def kill(self, test, node):
        with control.su():
            cu.stop_daemon(f"{SERVER_DIR}/node.pid", cmd="java")
            cu.grepkill("ignite")

    def teardown(self, test, node):
        self.kill(test, node)
        with control.su():
            try:
                control.exec_("rm", "-rf", f"{SERVER_DIR}/work",
                              LOGFILE)
            except RemoteError:
                pass

    def log_files(self, test, node):
        return [LOGFILE]


def db(version: str = DEFAULT_VERSION, opts: dict | None = None) -> DB:
    return DB(version, opts)


class IgniteError(Exception):
    pass


class RestClient(jclient.Client):
    """Ignite REST API: /ignite?cmd=get|put|cas&cacheName=..."""

    CACHE = "JEPSEN"

    def __init__(self, timeout_s: float = 5.0):
        self.timeout_s = timeout_s
        self.base: str | None = None

    def open(self, test, node):
        c = type(self)(self.timeout_s)
        fn = test.get("ignite-url-fn")
        c.base = fn(node) if fn else f"http://{node}:{REST_PORT}"
        return c

    def cmd(self, **params) -> dict:
        params.setdefault("cacheName", self.CACHE)
        url = self.base + "/ignite?" + urllib.parse.urlencode(params)
        with urllib.request.urlopen(url,
                                    timeout=self.timeout_s) as r:
            out = json.loads(r.read())
        if out.get("successStatus", 1) != 0:
            raise IgniteError(out.get("error") or "rest error")
        return out

    def get(self, key):
        return self.cmd(cmd="get", key=key)["response"]

    def put(self, key, value):
        self.cmd(cmd="put", key=key, val=value)

    def cas(self, key, old, new) -> bool:
        return bool(self.cmd(cmd="cas", key=key, val=new,
                             val2=old)["response"])

    def put_if_absent(self, key, value) -> bool:
        return bool(self.cmd(cmd="putifabs", key=key,
                             val=value)["response"])


class RegisterClient(RestClient):
    """Independent-keyed register (`register.clj:22-48`)."""

    def invoke(self, test, op):
        k, v = op["value"]
        key = f"r{k}"
        try:
            if op["f"] == "read":
                out = self.get(key)
                return {**op, "type": "ok", "value": independent.ktuple(
                    k, int(out) if out is not None else None)}
            if op["f"] == "write":
                self.put(key, v)
                return {**op, "type": "ok"}
            if op["f"] == "cas":
                old, new = v
                cur = self.get(key)
                if cur is None or int(cur) != old:
                    return {**op, "type": "fail",
                            "error": "value-mismatch"}
                ok = self.cas(key, old, new)
                return {**op, "type": "ok" if ok else "fail"}
            raise ValueError(f"unknown f {op['f']!r}")
        except (IgniteError, OSError, ValueError) as e:
            t = "fail" if op["f"] == "read" else "info"
            return {**op, "type": t, "error": str(e)}


class BankClient(RestClient):
    """All balances in one JSON value, moved with cas loops — REST has
    no transactions, but conservation semantics are the reference's
    (`bank.clj:24-60`)."""

    KEY = "accounts"

    def setup(self, test):
        accounts = test.get("accounts", list(range(8)))
        total = test.get("total-amount", 100)
        balances = {str(a): (total if a == accounts[0] else 0)
                    for a in accounts}
        try:
            self.put_if_absent(self.KEY, json.dumps(balances))
        except (IgniteError, OSError):
            pass  # another worker seeds

    def invoke(self, test, op):
        try:
            if op["f"] == "read":
                raw = self.get(self.KEY)
                bal = json.loads(raw) if raw else {}
                return {**op, "type": "ok",
                        "value": {int(k): v for k, v in bal.items()}}
            if op["f"] == "transfer":
                v = op["value"]
                for _ in range(16):
                    raw = self.get(self.KEY)
                    if raw is None:
                        return {**op, "type": "fail",
                                "error": "uninitialized"}
                    bal = json.loads(raw)
                    frm, to = str(v["from"]), str(v["to"])
                    if bal.get(frm, 0) < v["amount"]:
                        return {**op, "type": "fail",
                                "error": "insufficient"}
                    bal[frm] -= v["amount"]
                    bal[to] = bal.get(to, 0) + v["amount"]
                    if self.cas(self.KEY, raw, json.dumps(bal)):
                        return {**op, "type": "ok"}
                return {**op, "type": "fail", "error": "cas-contention"}
            raise ValueError(f"unknown f {op['f']!r}")
        except (IgniteError, OSError) as e:
            t = "fail" if op["f"] == "read" else "info"
            return {**op, "type": t, "error": str(e)}


def register_workload(opts) -> dict:
    from ..workloads import linearizable_register
    w = dict(linearizable_register.test(opts))
    w["client"] = RegisterClient()
    return w


def bank_workload(opts) -> dict:
    return {
        "client": BankClient(),
        "generator": bank_w.generator(),
        "checker": bank_w.checker({"negative-balances?": False}),
    }


WORKLOADS = {"register": register_workload, "bank": bank_workload}


def ignite_test(opts: dict) -> dict:
    workload_name = opts.get("workload", "register")
    return std_test(
        opts, name=f"ignite-{workload_name}",
        db=db(opts.get("version", DEFAULT_VERSION),
              {k: opts.get(k) for k in ("cache-mode", "backups")}),
        workload=WORKLOADS[workload_name](opts))


OPT_SPEC = std_opts(cli, WORKLOADS, "register", DEFAULT_VERSION,
                    "ignite version (zip dist)") + [
    cli.opt("--cache-mode", default="REPLICATED",
            choices=["REPLICATED", "PARTITIONED"]),
    cli.opt("--backups", type=int, default=2),
]


def main(argv=None):
    cli.run({**cli.single_test_cmd({"test_fn": ignite_test,
                                    "opt_spec": OPT_SPEC}),
             **cli.serve_cmd()}, argv)


if __name__ == "__main__":
    main()
