"""Aerospike test suite — CAS register, counter, set, and the pause
(lost-writes) workload, under a kill/partition/clock nemesis stack.

Mirrors the reference's aerospike suite
(`/root/reference/aerospike/src/aerospike/`):

  * DB automation: local .deb upload + dpkg install, config templating
    with replication-factor / heartbeat-interval / commit-to-device,
    roster-set + recluster on the primary, migration waits, wipe on
    teardown (`support.clj:215-340`).
  * Clients speak the Aerospike wire protocol directly (`as_proto.py`)
    with the reference's error classification (`support.clj:448-501`):
    timeouts/connection errors are :fail for idempotent ops and :info
    otherwise; generation mismatches are definite fails.
  * Workloads: cas-register (`cas_register.clj`), counter
    (`counter.clj`), set-via-string-append (`set.clj`), and the pause
    state machine that traps in-flight writes on a paused master
    (`pause.clj:180-233`).
  * Nemesis: kill/restart with a cap on simultaneously-dead nodes,
    revive + recluster recovery ops, composed with random-halves
    partitions and the clock nemesis (`nemesis.clj:96-145`).

The membership/roster protocol the nemesis drives is modeled by the
formal spec at `spec/aerospike_roster.tla` (the reference ships
`aerospike/spec/aerospike.tla`)."""

from __future__ import annotations

import itertools
import logging
import threading
import time as _time

from .. import checker, cli, client as jclient, control, independent, models
from .. import db as jdb
from .. import generator as gen
from .. import nemesis as jnemesis
from ..checker import linear, timeline
from ..control import util as cu
from ..control.core import RemoteError
from ..nemesis import partition as npartition, time as ntime
from . import std_opts, std_test
from .as_proto import (ASError, Conn, RC_GENERATION, RC_FORBIDDEN,
                       RC_HOT_KEY,
                       RC_PARTITION_UNAVAILABLE)

log = logging.getLogger(__name__)

NAMESPACE = "jepsen"            # support.clj ans
PORT = 3000
PACKAGE_DIR = "/tmp/packages"   # support.clj remote-package-dir
CONF = "/etc/aerospike/aerospike.conf"
LOGFILE = "/var/log/aerospike/aerospike.log"


def _meh(*cmd):
    """Run a command, swallowing remote failures (the reference's
    `meh` around best-effort cleanup, e.g. `support.clj:312-327`)."""
    try:
        control.exec_(*cmd)
    except RemoteError:
        pass

CONF_TEMPLATE = """\
service {{
    proto-fd-max 15000
    node-id-interface eth0
}}
logging {{
    file {logfile} {{ context any info }}
}}
network {{
    service {{ address any; port {port} }}
    heartbeat {{
        mode mesh
        address any
        mesh-seed-address-port {mesh_address} 3002
        port 3002
        interval {heartbeat_interval}
        timeout 10
    }}
    fabric {{ port 3001 }}
    info {{ port 3003 }}
}}
namespace {namespace} {{
    replication-factor {replication_factor}
    memory-size 1G
    strong-consistency true
    {commit_to_device}
    storage-engine device {{
        file /opt/aerospike/data/{namespace}.dat
        filesize 1G
    }}
}}
"""


class DB(jdb.DB, jdb.Process, jdb.LogFiles):
    """Aerospike server from local .deb packages (`support.clj:215-340`)."""

    def __init__(self, opts: dict | None = None):
        self.opts = opts or {}

    def setup(self, test, node):
        with control.su():
            ntime.reset_time()
            self.install(test, node)
            self.configure(test, node)
            self.start(test, node)

    def install(self, test, node):
        log.info("%s installing aerospike packages", node)
        control.exec_("mkdir", "-p", PACKAGE_DIR)
        control.exec_("chmod", "a+rwx", PACKAGE_DIR)
        for pkg in test.get("packages",
                            ["aerospike-server.deb",
                             "aerospike-tools.deb"]):
            remote = f"{PACKAGE_DIR}/{pkg.rsplit('/', 1)[-1]}"
            control.upload(pkg, remote)
            control.exec_("dpkg", "-i", "--force-confnew", remote)
        control.exec_("systemctl", "daemon-reload")
        for d, owner in (("/var/log/aerospike", "aerospike:aerospike"),
                         ("/var/run/aerospike", "aerospike:aerospike")):
            control.exec_("mkdir", "-p", d)
            control.exec_("chown", owner, d)

    def configure(self, test, node):
        conf = CONF_TEMPLATE.format(
            logfile=LOGFILE, port=PORT, namespace=NAMESPACE,
            mesh_address=test["nodes"][0],
            heartbeat_interval=self.opts.get("heartbeat-interval", 150),
            replication_factor=self.opts.get("replication-factor", 3),
            commit_to_device=("commit-to-device true"
                              if self.opts.get("commit-to-device")
                              else ""))
        cu.write_file(conf, CONF)

    def start(self, test, node):
        with control.su():
            control.exec_("service", "aerospike", "start")
            cu.await_tcp_port(PORT)
            if node == test["nodes"][0]:
                # roster-set every observed node, then recluster
                # (support.clj:282-310 start!)
                control.exec_(
                    "asinfo", "-v",
                    f"roster-set:namespace={NAMESPACE};nodes="
                    + ",".join(test["nodes"]))
                control.exec_("asadm", "-e", "asinfo -v recluster:")

    def kill(self, test, node):
        with control.su():
            _meh("service", "aerospike", "stop")
            cu.grepkill("asd")

    def teardown(self, test, node):
        with control.su():
            self.kill(test, node)
            _meh("truncate", "--size", "0", LOGFILE)
            for d in ("data", "smd", "udf"):
                _meh("rm", "-rf", f"/opt/aerospike/{d}")

    def log_files(self, test, node):
        return [LOGFILE]


def db(opts: dict | None = None) -> DB:
    return DB(opts)


def revive(node=None):
    """asinfo revive — readmit dead partitions (`support.clj:142-148`)."""
    with control.su():
        control.exec_("asinfo", "-v",
                      f"revive:namespace={NAMESPACE}")


def recluster(node=None):
    with control.su():
        control.exec_("asinfo", "-v", "recluster:")


# -- error classification (support.clj with-errors) --------------------------

DEFINITE_FAIL = {RC_GENERATION, RC_PARTITION_UNAVAILABLE, RC_HOT_KEY,
                 RC_FORBIDDEN}


def _capture(op, e: Exception, idempotent: bool) -> dict:
    if isinstance(e, ASError):
        if e.code in DEFINITE_FAIL:
            return {**op, "type": "fail", "error": ["as", e.code, str(e)]}
        t = "fail" if idempotent else "info"
        return {**op, "type": t, "error": ["as", e.code, str(e)]}
    t = "fail" if idempotent else "info"
    return {**op, "type": t, "error": ["conn", str(e)]}


def _connect(test, node) -> Conn:
    fn = test.get("as-conn-fn")
    if fn is not None:
        return fn(node)
    return Conn(node, PORT)


class _Client(jclient.Client):
    SET = "cats"

    def __init__(self):
        self.conn: Conn | None = None

    def open(self, test, node):
        c = type(self)()
        c.__dict__.update({k: v for k, v in self.__dict__.items()
                           if k != "conn"})
        c.conn = _connect(test, node)
        return c

    def close(self, test):
        if self.conn is not None:
            self.conn.close()


class CasRegisterClient(_Client):
    """CAS register over a single bin, keyed independently
    (`cas_register.clj:43-75`). cas = fetch generation, verify value,
    put with EXPECT_GEN_EQUAL."""

    def invoke(self, test, op):
        k, v = op["value"]
        idempotent = op["f"] == "read"
        try:
            if op["f"] == "read":
                r = self.conn.get(NAMESPACE, self.SET, k)
                val = r["bins"].get("value") if r else None
                return {**op, "type": "ok",
                        "value": independent.ktuple(k, val)}
            if op["f"] == "write":
                self.conn.put(NAMESPACE, self.SET, k, {"value": v})
                return {**op, "type": "ok"}
            if op["f"] == "cas":
                old, new = v
                r = self.conn.get(NAMESPACE, self.SET, k)
                if r is None:
                    return {**op, "type": "fail", "error": "not-found"}
                if r["bins"].get("value") != old:
                    return {**op, "type": "fail",
                            "error": "value-mismatch"}
                self.conn.put(NAMESPACE, self.SET, k, {"value": new},
                              generation=r["generation"])
                return {**op, "type": "ok"}
            raise ValueError(f"unknown f {op['f']!r}")
        except (ASError, OSError) as e:
            return _capture(op, e, idempotent)


class CounterClient(_Client):
    """Counter via server-side add (`counter.clj:43-66`)."""

    SET = "counters"
    KEY = "pounce"

    def setup(self, test):
        try:
            self.conn.put(NAMESPACE, self.SET, self.KEY, {"value": 0})
        except (ASError, OSError):
            pass  # another worker's setup may already have seeded it

    def invoke(self, test, op):
        idempotent = op["f"] == "read"
        try:
            if op["f"] == "read":
                r = self.conn.get(NAMESPACE, self.SET, self.KEY)
                return {**op, "type": "ok",
                        "value": r["bins"].get("value") if r else None}
            if op["f"] == "add":
                self.conn.add(NAMESPACE, self.SET, self.KEY,
                              {"value": op["value"]})
                return {**op, "type": "ok"}
            raise ValueError(f"unknown f {op['f']!r}")
        except (ASError, OSError) as e:
            return _capture(op, e, idempotent)


class SetClient(_Client):
    """Set as a string-append bin: add appends " v", read splits
    (`set.clj:12-46`)."""

    def invoke(self, test, op):
        k, v = op["value"]
        try:
            if op["f"] == "read":
                r = self.conn.get(NAMESPACE, self.SET, k)
                raw = (r["bins"].get("value") or "") if r else ""
                vals = sorted(int(x) for x in raw.split() if x)
                return {**op, "type": "ok",
                        "value": independent.ktuple(k, vals)}
            if op["f"] == "add":
                self.conn.append(NAMESPACE, self.SET, k,
                                 {"value": f" {v}"})
                return {**op, "type": "ok"}
            raise ValueError(f"unknown f {op['f']!r}")
        except (ASError, OSError) as e:
            return _capture(op, e, op["f"] == "read")


# -- nemesis (nemesis.clj) ---------------------------------------------------

class KillNemesis(jnemesis.Nemesis):
    """Kills/restarts asd with a cap on simultaneously-dead nodes;
    revive/recluster recovery ops (`nemesis.clj:17-57`)."""

    def __init__(self, signal: int = 9, max_dead: int = 2):
        self.signal = signal
        self.max_dead = max_dead
        self.dead: set = set()
        self.lock = threading.Lock()

    def setup(self, test):
        return self

    def invoke(self, test, op):
        f = op["f"]

        def per_node(test, node):
            if f == "kill":
                with self.lock:
                    if node not in self.dead \
                            and len(self.dead) >= self.max_dead:
                        return "still-alive"
                    self.dead.add(node)
                with control.su():
                    _meh("killall", f"-{self.signal}", "asd")
                return "killed"
            if f == "restart":
                with control.su():
                    control.exec_("service", "aerospike", "restart")
                with self.lock:
                    self.dead.discard(node)
                return "started"
            if f == "revive":
                try:
                    revive(node)
                    return "revived"
                except Exception:  # noqa: BLE001 — dead node
                    return "not-running"
            if f == "recluster":
                try:
                    recluster(node)
                    return "reclustered"
                except Exception:  # noqa: BLE001 — dead node
                    return "not-running"
            raise ValueError(f"unknown nemesis f {f!r}")

        value = control.on_nodes(test, per_node, op["value"])
        return {**op, "value": value}

    def teardown(self, test):
        pass


def _subset(rng, nodes):
    n = rng.randint(1, len(nodes))
    return rng.sample(list(nodes), n)


def kill_gen(test, ctx):
    return {"type": "info", "f": "kill",
            "value": _subset(gen.rng, test["nodes"])}


def restart_gen(test, ctx):
    return {"type": "info", "f": "restart",
            "value": _subset(gen.rng, test["nodes"])}


def revive_gen(test, ctx):
    return {"type": "info", "f": "revive", "value": test["nodes"]}


def recluster_gen(test, ctx):
    return {"type": "info", "f": "recluster", "value": test["nodes"]}


def killer_gen(opts):
    """Mix of kills, restarts, and (unless no-revives) revive+recluster
    pairs (`nemesis.clj:78-94`)."""
    patterns = [[kill_gen], [restart_gen]]
    if not opts.get("no-revives"):
        patterns.append([revive_gen, recluster_gen])

    def stream():
        while True:
            yield from gen.rng.choice(patterns)

    return stream()


def full_nemesis(opts: dict):
    """Partitions + capped kills + clock faults (`nemesis.clj:96-112`)."""
    return jnemesis.compose([
        (frozenset({"start-partition", "stop-partition"}),
         npartition.partition_random_halves()),
        (frozenset({"kill", "restart", "revive", "recluster"}),
         KillNemesis(signal=15 if opts.get("clean-kill") else 9,
                     max_dead=opts.get("max-dead-nodes", 2))),
        (frozenset({"reset", "bump", "strobe", "check-offsets"}),
         ntime.clock_nemesis()),
    ])


def full_gen(opts: dict):
    parts = []
    if not opts.get("no-clocks"):
        parts.append(ntime.clock_gen())
    if not opts.get("no-kills"):
        parts.append(killer_gen(opts))
    if not opts.get("no-partitions"):
        parts.append(itertools.cycle([
            {"type": "info", "f": "start-partition", "value": None},
            {"type": "info", "f": "stop-partition", "value": None}]))
    return gen.mix(parts) if parts else None


def full_package(opts: dict) -> dict:
    """{:nemesis :generator :final-generator} (`nemesis.clj:126-145`)."""
    return {
        "nemesis": full_nemesis(opts),
        "generator": full_gen(opts),
        "final-generator": [
            {"type": "info", "f": "stop-partition", "value": None},
            {"type": "info", "f": "reset", "value": None},
            gen.once(lambda test, ctx: {"type": "info", "f": "restart",
                                        "value": test["nodes"]}),
            gen.sleep(10),
            gen.once(lambda test, ctx: {"type": "info", "f": "revive",
                                        "value": test["nodes"]}),
            gen.once(lambda test, ctx: {"type": "info", "f": "recluster",
                                        "value": test["nodes"]}),
        ],
    }


# -- workloads ---------------------------------------------------------------

def cas_register_workload(opts) -> dict:
    """Independent CAS registers (`cas_register.clj:80-104`)."""
    def r(test, ctx):
        return {"type": "invoke", "f": "read", "value": None}

    def w(test, ctx):
        return {"type": "invoke", "f": "write",
                "value": gen.rng.randrange(5)}

    def cas(test, ctx):
        return {"type": "invoke", "f": "cas",
                "value": (gen.rng.randrange(5), gen.rng.randrange(5))}

    def fgen(k):
        return gen.limit(100 + gen.rng.randrange(100),
                         gen.reserve(5, r, gen.mix([w, cas, cas])))

    return {
        "client": CasRegisterClient(),
        "generator": independent.concurrent_generator(
            _group_size(opts, 10), _naturals(), fgen),
        "checker": independent.checker(checker.compose({
            "linear": linear.linearizable(models.cas_register()),
            "timeline": timeline.html()})),
    }


def counter_workload(opts) -> dict:
    """100:1 add:read mix on one counter key (`counter.clj:68-78`)."""
    def r(test, ctx):
        return {"type": "invoke", "f": "read", "value": None}

    def add(test, ctx):
        return {"type": "invoke", "f": "add", "value": 1}

    return {
        "client": CounterClient(),
        "generator": gen.mix([add] * 100 + [r]),
        # the O(n) bounds checker (reference behavior) plus full
        # linearizability against the device counter model; budgeted —
        # under the kill nemesis, crashed adds accumulate and the
        # search is genuinely exponential past the device slot cap
        "checker": checker.compose({
            "counter": checker.counter(),
            "counter-plot": checker.counter_plot(),
            "linear": linear.linearizable(
                models.counter(),
                budget_s=opts.get("linear-budget-s", 60)),
        }),
    }


def set_workload(opts) -> dict:
    """Independent append-sets with a final read phase
    (`set.clj:48-72`)."""
    state = {"max_key": 0}

    def fgen(k):
        state["max_key"] = max(state["max_key"], k)
        counter = {"n": -1}

        def add(test, ctx):
            counter["n"] += 1
            return {"type": "invoke", "f": "add", "value": counter["n"]}

        return gen.limit(500, add)

    def final(test, ctx):
        ks = range(state["max_key"] + 1)
        return independent.sequential_generator(
            ks, lambda k: gen.once(
                {"type": "invoke", "f": "read", "value": None}))

    return {
        "client": SetClient(),
        "generator": independent.concurrent_generator(
            _group_size(opts, 5), _naturals(), fgen),
        "final-generator": gen.derefer(final),
        "checker": independent.checker(checker.set_checker()),
    }


def pause_workload(opts) -> dict:
    """The lost-writes pause state machine (`pause.clj:180-233`):
    writes flow; a master is paused (SIGSTOP) with writes in flight;
    after a successful write post-pause the cluster idles past the
    commit window; the master resumes and may stomp the accepted
    writes. States: healthy -> paused -> wait -> healthy."""
    state = {"state": "healthy", "masters": [], "keys": [0],
             "next_key": 0, "lock": threading.Lock(), "value": [-1]}

    def next_healthy(test):
        nodes = list(test["nodes"])
        gen.rng.shuffle(nodes)
        k0 = state["keys"][-1] + 1
        per = max(1, test.get("concurrency", 5) // len(nodes))
        state.update(state=("healthy"), masters=nodes[:1],
                     keys=list(range(k0, k0 + per)))

    class PauseNemesis(jnemesis.Nemesis):
        def setup(self, test):
            return self

        def invoke(self, test, op):
            def per_node(test, node):
                with control.su():
                    if op["f"] == "pause":
                        _meh("killall", "-19", "asd")
                        return "paused"
                    _meh("killall", "-18", "asd")
                    return "resumed"

            v = control.on_nodes(test, per_node, op["value"])
            with state["lock"]:
                if op["f"] == "pause":
                    state["state"] = "paused"
                else:
                    next_healthy(test)
            return {**op, "value": v}

        def teardown(self, test):
            pass

    class PauseClient(SetClient):
        SET = "pause"

        def invoke(self, test, op):
            r = super().invoke(test, op)
            if op["f"] == "add" and r["type"] == "ok":
                with state["lock"]:
                    if state["state"] == "paused":
                        state["state"] = "wait"
            return r

    def nemesis_gen(test, ctx):
        with state["lock"]:
            s = state["state"]
        if s == "healthy":
            return gen.delay(
                opts.get("healthy-delay", 0.5),
                [{"type": "info", "f": "pause",
                  "value": list(state["masters"])}])
        if s == "wait":
            return gen.delay(
                opts.get("pause-delay", 1.0),
                [{"type": "info", "f": "resume",
                  "value": list(state["masters"])}])
        return gen.sleep(0.05)

    def client_gen(test, ctx):
        with state["lock"]:
            if state["state"] == "wait":
                return gen.sleep(0.05)
            keys = state["keys"]
            state["value"][0] += 1
            v = state["value"][0]
        return {"type": "invoke", "f": "add",
                "value": independent.ktuple(keys[v % len(keys)], v)}

    def final(test, ctx):
        ks = range(state["keys"][-1] + 1)
        return independent.sequential_generator(
            ks, lambda k: gen.once(
                {"type": "invoke", "f": "read", "value": None}))

    return {
        "client": PauseClient(),
        "generator": client_gen,
        "final-generator": gen.derefer(final),
        "checker": independent.checker(checker.set_checker()),
        "nemesis-package": {
            "nemesis": PauseNemesis(),
            "generator": nemesis_gen,
            "final-generator": gen.once(
                lambda test, ctx: {"type": "info", "f": "resume",
                                   "value": test["nodes"]}),
        },
    }


def _group_size(opts: dict, preferred: int) -> int:
    """The reference pins concurrent-generator group sizes (10 for
    cas-register, 5 for set) and requires thread count divisible by
    them; adapt to the test's actual concurrency."""
    conc = int(opts.get("concurrency", preferred) or preferred)
    for d in range(min(preferred, conc), 0, -1):
        if conc % d == 0:
            return d
    return 1


def _naturals():
    k = 0
    while True:
        yield k
        k += 1


WORKLOADS = {
    "cas-register": cas_register_workload,
    "counter": counter_workload,
    "set": set_workload,
    "pause": pause_workload,
}


def aerospike_test(opts: dict) -> dict:
    workload_name = opts.get("workload", "cas-register")
    workload = WORKLOADS[workload_name](opts)
    d = db({k: opts.get(k) for k in ("replication-factor",
                                     "heartbeat-interval",
                                     "commit-to-device", "clean-kill")})
    if "nemesis-package" in workload:
        # pause couples workload and nemesis (core.clj workload+nemesis)
        pkg = workload.pop("nemesis-package")
    else:
        faults = [f for f in (opts.get("faults") or []) if f != "none"]
        # the reference composes its own full nemesis stack rather
        # than the std packages (`core.clj:40-77`)
        pkg = full_package(opts) if faults else None
    return std_test(opts, name=f"aerospike-{workload_name}", db=d,
                    workload=workload, nemesis_package=pkg,
                    default_faults=())


OPT_SPEC = std_opts(cli, WORKLOADS, "cas-register") + [
    cli.opt("--replication-factor", type=int, default=3,
            help="number of nodes which must store data"),
    cli.opt("--max-dead-nodes", type=int, default=2,
            help="nodes allowed to be down simultaneously"),
    cli.opt("--clean-kill", action="store_true",
            help="SIGTERM instead of SIGKILL"),
    cli.opt("--commit-to-device", action="store_true",
            help="force writes to disk before commit"),
    cli.opt("--heartbeat-interval", type=int, default=150,
            help="heartbeat interval in ms"),
]


def main(argv=None):
    cli.run({**cli.single_test_cmd({"test_fn": aerospike_test,
                                    "opt_spec": OPT_SPEC}),
             **cli.serve_cmd()}, argv)


if __name__ == "__main__":
    main()
