"""MariaDB Galera Cluster test suite.

Mirrors the reference's galera suite
(`/root/reference/galera/src/jepsen/galera.clj` and
`galera/dirty_reads.clj`): mariadb-galera-server install with a wsrep
cluster address over all nodes, first node bootstrapped with
--wsrep-new-cluster (`galera.clj:102-115`), and two workloads — the
signature *dirty reads* test (writers set every row to a unique value
inside a serializable txn, readers scan the table; a failed write's
value visible to any reader is a G1a dirty read, and a mixed-value scan
is a non-atomic read, `dirty_reads.clj:1-96`) — plus the bank test.

Clients reuse the MySQL wire client (`mysql_proto.py`); hermetic tests
run against the in-process MySQL-protocol fake."""

from __future__ import annotations

import itertools
import logging

from .. import cli, client as jclient, control
from .. import db as jdb
from .. import generator as gen
from ..checker import Checker
from ..control import util as cu
from ..history import history as as_history, is_fail, is_ok
from ..os_ import debian
from ..workloads import bank as bank_w
from . import std_opts, std_test
from .mysql_proto import Conn, MySQLError

log = logging.getLogger(__name__)

SQL_PORT = 3306
CONFIG = "/etc/mysql/conf.d/galera.cnf"
LOGFILE = "/var/log/mysql/error.log"

DEFAULT_VERSION = "10.0"

# conflict/abort codes: deadlock, lock-wait timeout, galera certification
DEFINITE_ABORT = {1205, 1213, 1047}


def cluster_address(test: dict) -> str:
    """gcomm://n1,n2,... (`galera.clj:59-72`)."""
    return "gcomm://" + ",".join(test["nodes"])


def config_body(test: dict) -> str:
    return (
        "[mysqld]\n"
        "bind-address=0.0.0.0\n"
        "wsrep_provider=/usr/lib/galera/libgalera_smm.so\n"
        f"wsrep_cluster_address={cluster_address(test)}\n"
        "wsrep_sst_method=rsync\n"
        "binlog_format=ROW\n"
        "default_storage_engine=InnoDB\n"
        "innodb_autoinc_lock_mode=2\n")


class DB(jdb.DB, jdb.Process, jdb.LogFiles):
    def __init__(self, version: str = DEFAULT_VERSION):
        self.version = version

    def setup(self, test, node):
        with control.su():
            log.info("%s installing mariadb-galera %s", node,
                     self.version)
            debian.install(["rsync", "mariadb-galera-server"])
            control.exec_("sh", "-c",
                          f"cat > {CONFIG} <<'EOF'\n"
                          f"{config_body(test)}EOF")
            control.exec_("service", "mysql", "stop")
            if node == test["nodes"][0]:
                # bootstrap the cluster on the first node
                control.exec_("service", "mysql", "start",
                              "--wsrep-new-cluster")
            else:
                control.exec_("service", "mysql", "start")
            cu.await_tcp_port(SQL_PORT)
            # test account for remote clients
            control.exec_(
                "mysql", "-u", "root", "-e",
                "create database if not exists jepsen; "
                "grant all on jepsen.* to 'jepsen'@'%' "
                "identified by 'jepsen'; flush privileges")

    def start(self, test, node):
        with control.su():
            control.exec_("service", "mysql", "start")

    def kill(self, test, node):
        with control.su():
            cu.grepkill("mysqld")

    def teardown(self, test, node):
        with control.su():
            self.kill(test, node)
            control.exec_("rm", "-rf", "/var/lib/mysql/grastate.dat")

    def log_files(self, test, node):
        return [LOGFILE]


def db(version: str = DEFAULT_VERSION) -> DB:
    return DB(version)


def _connect(test, node) -> Conn:
    fn = test.get("sql-conn-fn")
    if fn is not None:
        return fn(node)
    return Conn(node, SQL_PORT, user="jepsen", password="jepsen",
                database="jepsen")


class _SQLClient(jclient.Client):
    def __init__(self):
        self.conn: Conn | None = None

    def open(self, test, node):
        c = type(self).__new__(type(self))
        c.__dict__.update(self.__dict__)
        c.conn = _connect(test, node)
        return c

    def close(self, test):
        if self.conn is not None:
            self.conn.close()

    def _capture(self, op, e: Exception, read_only: bool) -> dict:
        if isinstance(e, MySQLError):
            if e.code in DEFINITE_ABORT or read_only:
                return {**op, "type": "fail",
                        "error": ["sql", e.code, e.message]}
            return {**op, "type": "info",
                    "error": ["sql", e.code, e.message]}
        return {**op, "type": "fail" if read_only else "info",
                "error": ["conn", str(e)]}

    def _txn(self, stmts_fn, op, read_only=False):
        conn = self.conn
        try:
            conn.query("begin")
            out = stmts_fn(conn)
            conn.query("commit")
            return {**op, "type": "ok", **out}
        except Exception as e:  # noqa: BLE001 — classified below
            try:
                conn.query("rollback")
            except Exception:  # noqa: BLE001 — conn may be dead
                pass
            if isinstance(e, (MySQLError, OSError, ConnectionError)):
                return self._capture(op, e, read_only)
            raise


# -- dirty reads (`dirty_reads.clj`) -----------------------------------------

class DirtyReadsClient(_SQLClient):
    """Writers set every row of the `dirty` table to their unique value
    in one serializable txn; readers scan all rows."""

    def __init__(self, n_rows: int = 4):
        super().__init__()
        self.n_rows = n_rows

    def setup(self, test):
        self.conn.query("create table if not exists dirty "
                        "(id int not null primary key, x bigint)")
        for i in range(self.n_rows):
            try:
                self.conn.query(f"insert into dirty (id, x) values "
                                f"({i}, -1)")
            except MySQLError as e:
                if e.code != 1062:
                    raise

    def invoke(self, test, op):
        if op["f"] == "read":
            def read_body(conn):
                rows, _ = conn.query("select x from dirty")
                return {"value": [int(r[0]) for r in rows]}
            return self._txn(read_body, op, read_only=True)

        x = op["value"]

        def write_body(conn):
            for i in range(self.n_rows):
                conn.query(f"select x from dirty where id = {i}")
            for i in range(self.n_rows):
                conn.query(f"update dirty set x = {x} where id = {i}")
            return {}
        return self._txn(write_body, op)


class DirtyReadsChecker(Checker):
    """A failed write's value visible to any reader is a dirty read;
    a scan with mixed values is a non-atomic read
    (`dirty_reads.clj:73-96`)."""

    def check(self, test, hist, opts):
        hist = as_history(hist)
        failed = {o["value"] for o in hist
                  if is_fail(o) and o.get("f") == "write"}
        reads = [o["value"] for o in hist
                 if is_ok(o) and o.get("f") == "read"]
        inconsistent = [r for r in reads if r and len(set(r)) > 1]
        dirty = [r for r in reads if any(v in failed for v in r)]
        return {"valid?": not dirty,
                "read-count": len(reads),
                "inconsistent-reads": inconsistent[:10],
                "dirty-reads": dirty[:10]}


def dirty_reads_workload(opts: dict) -> dict:
    n = opts.get("dirty-rows", 4)

    def read(test, ctx):
        return {"type": "invoke", "f": "read", "value": None}

    writes = ({"type": "invoke", "f": "write", "value": v}
              for v in itertools.count())
    return {
        "client": DirtyReadsClient(n),
        "generator": gen.mix([read, writes]),
        "checker": DirtyReadsChecker(),
    }


# -- bank --------------------------------------------------------------------

class BankClient(_SQLClient):
    def setup(self, test):
        self.conn.query("create table if not exists accounts "
                        "(id int not null primary key, "
                        "balance bigint not null)")
        accounts = test.get("accounts", list(range(8)))
        total = test.get("total-amount", 100)
        for a in accounts:
            try:
                self.conn.query(
                    f"insert into accounts (id, balance) values "
                    f"({a}, {total if a == accounts[0] else 0})")
            except MySQLError as e:
                if e.code != 1062:
                    raise

    def invoke(self, test, op):
        if op["f"] == "read":
            def read_body(conn):
                rows, _ = conn.query("select id, balance from accounts")
                return {"value": {int(r[0]): int(r[1]) for r in rows}}
            return self._txn(read_body, op, read_only=True)

        v = op["value"]
        frm, to, amount = v["from"], v["to"], v["amount"]

        def transfer_body(conn):
            rows, _ = conn.query(
                f"select balance from accounts where id = {frm} "
                f"for update")
            b1 = int(rows[0][0]) - amount
            rows, _ = conn.query(
                f"select balance from accounts where id = {to} "
                f"for update")
            b2 = int(rows[0][0]) + amount
            if b1 < 0:
                raise _InsufficientFunds()
            conn.query(f"update accounts set balance = {b1} "
                       f"where id = {frm}")
            conn.query(f"update accounts set balance = {b2} "
                       f"where id = {to}")
            return {}

        try:
            return self._txn(transfer_body, op)
        except _InsufficientFunds:
            return {**op, "type": "fail", "error": "negative"}


class _InsufficientFunds(Exception):
    pass


def bank_workload(opts: dict) -> dict:
    w = bank_w.test(opts)
    w["client"] = BankClient()
    return w


WORKLOADS = {
    "dirty-reads": dirty_reads_workload,
    "bank": bank_workload,
}


def galera_test(opts: dict) -> dict:
    workload_name = opts.get("workload", "dirty-reads")
    return std_test(
        opts, name=f"galera-{workload_name}",
        db=db(opts.get("version", DEFAULT_VERSION)),
        workload=WORKLOADS[workload_name](opts))


OPT_SPEC = std_opts(cli, WORKLOADS, "dirty-reads", DEFAULT_VERSION,
                    "mariadb-galera version") + [
    cli.opt("--dirty-rows", type=int, default=4,
            help="rows in the dirty-reads table"),
]


def main(argv=None):
    cli.run({**cli.single_test_cmd({"test_fn": galera_test,
                                    "opt_spec": OPT_SPEC}),
             **cli.serve_cmd()}, argv)


if __name__ == "__main__":
    main()
