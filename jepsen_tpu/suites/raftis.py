"""Raftis test suite — a linearizable register over redis+raft.

Mirrors the reference's raftis suite
(`/root/reference/raftis/src/jepsen/raftis.clj`): a single GET/SET
register at key "r" (`:38-48`), error classification where no-leader
and socket-closed writes are definite fails and other write errors are
indeterminate (`:46-59`), knossos-linearizable checking + timeline.
The register has no CAS, so the model is a plain read/write register —
checked on device with the 'register' kernel.

The client speaks RESP (`resp_proto.py`); hermetic tests run against an
in-process fake redis (tests/fake_resp.py)."""

from __future__ import annotations

import logging

from .. import cli, client as jclient, control, models
from .. import db as jdb
from .. import generator as gen
from ..checker import linear
from ..control import util as cu
from . import std_opts, std_test
from .resp_proto import Conn, RESPError

log = logging.getLogger(__name__)

DIR = "/opt/raftis"
LOGFILE = f"{DIR}/raftis.log"
PIDFILE = f"{DIR}/raftis.pid"
BINARY = "raftis"
CLIENT_PORT = 6379
RAFT_PORT = 8901

DEFAULT_VERSION = "latest"


def initial_cluster(test: dict) -> str:
    """n1:8901,n2:8901,... (`raftis.clj:70-77`)."""
    return ",".join(f"{n}:{RAFT_PORT}" for n in test["nodes"])


class DB(jdb.DB, jdb.Process, jdb.LogFiles):
    def __init__(self, version: str = DEFAULT_VERSION):
        self.version = version

    def setup(self, test, node):
        with control.su():
            log.info("%s installing raftis %s", node, self.version)
            tarball = test.get("tarball")
            if tarball:
                cu.install_archive(tarball, DIR)
            control.exec_("mkdir", "-p", f"{DIR}/data")
            self.start(test, node)
            cu.await_tcp_port(CLIENT_PORT)

    def start(self, test, node):
        i = test["nodes"].index(node)
        with control.su():
            cu.start_daemon(
                {"logfile": LOGFILE, "pidfile": PIDFILE, "chdir": DIR},
                f"{DIR}/{BINARY}",
                "-addr", f"{node}:{CLIENT_PORT}",
                "-raft", f"{node}:{RAFT_PORT}",
                "-id", str(i),
                "-cluster", initial_cluster(test))

    def kill(self, test, node):
        with control.su():
            cu.stop_daemon(PIDFILE, cmd=BINARY)
            cu.grepkill(BINARY)

    def teardown(self, test, node):
        with control.su():
            self.kill(test, node)
            control.exec_("rm", "-rf", f"{DIR}/data", LOGFILE, PIDFILE)

    def log_files(self, test, node):
        return [LOGFILE]


def db(version: str = DEFAULT_VERSION) -> DB:
    return DB(version)


def _connect(test, node) -> Conn:
    fn = test.get("resp-conn-fn")
    if fn is not None:
        return fn(node)
    return Conn(node, CLIENT_PORT)


class RegisterClient(jclient.Client):
    """GET/SET on key "r" with the reference's error classification
    (`raftis.clj:38-59`)."""

    KEY = "r"

    def __init__(self):
        self.conn: Conn | None = None

    def open(self, test, node):
        c = RegisterClient()
        c.conn = _connect(test, node)
        return c

    def close(self, test):
        if self.conn is not None:
            self.conn.close()

    def invoke(self, test, op):
        try:
            if op["f"] == "read":
                v = self.conn.call("GET", self.KEY)
                return {**op, "type": "ok",
                        "value": int(v) if v is not None else None}
            if op["f"] == "write":
                self.conn.call("SET", self.KEY, op["value"])
                return {**op, "type": "ok"}
            raise ValueError(f"unknown f {op['f']!r}")
        except RESPError as e:
            msg = str(e)
            definite = op["f"] == "read" or "no leader" in msg \
                or "socket closed" in msg
            return {**op, "type": "fail" if definite else "info",
                    "error": msg}
        except OSError as e:
            return {**op,
                    "type": "fail" if op["f"] == "read" else "info",
                    "error": str(e)}


def register_workload(opts: dict) -> dict:
    def r(test, ctx):
        return {"type": "invoke", "f": "read", "value": None}

    def w(test, ctx):
        return {"type": "invoke", "f": "write",
                "value": gen.rng.randrange(5)}

    return {
        "client": RegisterClient(),
        "generator": gen.mix([r, w]),
        "checker": linear.linearizable(models.register()),
    }


WORKLOADS = {"register": register_workload}


def raftis_test(opts: dict) -> dict:
    workload_name = opts.get("workload", "register")
    return std_test(
        opts, name=f"raftis-{workload_name}",
        db=db(opts.get("version", DEFAULT_VERSION)),
        workload=WORKLOADS[workload_name](opts))


OPT_SPEC = std_opts(cli, WORKLOADS, "register", DEFAULT_VERSION,
                    "raftis version (tarball install)")


def main(argv=None):
    cli.run({**cli.single_test_cmd({"test_fn": raftis_test,
                                    "opt_spec": OPT_SPEC}),
             **cli.serve_cmd()}, argv)


if __name__ == "__main__":
    main()
