"""FaunaDB query-language AST builders.

The reference drives FaunaDB through the official JVM driver's
expression tree (`faunadb/src/jepsen/faunadb/query.clj:18-330` wraps
`com.faunadb.client.query.Language`). FaunaDB's wire protocol is HTTP
POST of the JSON-serialized expression; this module builds that JSON
directly — each function mirrors one `q/...` builder — so the suite
client (`faunadb.py`) needs no driver. Literal maps are wrapped in
``{"object": ...}`` exactly like the real wire format, so data keyed
"get"/"if"/... can't be misparsed as function calls.

Evaluation semantics live in the test fake (`tests/fake_fauna.py`),
which interprets the same encoding over a versioned store (FaunaDB is
a temporal database: `at` reads past snapshots, `query.clj:187-195`).
"""

from __future__ import annotations

import functools
from typing import Any


class Expr(dict):
    """A built query expression. The marker lets wrap() distinguish
    expression dicts (pass through) from literal data maps (encode as
    {"object": ...}) — the JVM driver gets this from its typed Value
    tree (`query.clj:18-51`)."""


def wrap(v: Any):
    """Encode a literal Python value: plain dicts become {"object":
    ...} so data keys can't collide with function forms; Expr values
    pass through unchanged."""
    if isinstance(v, Expr):
        return v
    if isinstance(v, dict):
        return Expr({"object": {k: wrap(x) for k, x in v.items()}})
    if isinstance(v, (list, tuple)):
        return [wrap(x) for x in v]
    return v


def class_(name: str) -> dict:
    """A class ref (`query.clj:63-69`)."""
    return {"class": name}


def index(name: str) -> dict:
    """An index ref (`query.clj:77-80`)."""
    return {"index": name}


def ref(cls, id) -> dict:
    """An instance ref within a class (`query.clj:71-75`). The id may
    itself be an expression (e.g. a select over an index page)."""
    if isinstance(cls, str):
        cls = class_(cls)
    return {"ref": cls, "id": id if isinstance(id, dict) else str(id)}


def var(name: str) -> dict:
    """A let-bound variable (`query.clj:58-61`)."""
    return {"var": name}


def let(bindings: dict, in_) -> dict:
    """Sequential let bindings (`query.clj:121-156`)."""
    return {"let": [{k: v} for k, v in bindings.items()], "in": in_}


def if_(cond, then, else_) -> dict:
    return {"if": cond, "then": then, "else": else_}


def when(cond, then) -> dict:
    """if without an else branch (`query.clj:169-172`)."""
    return if_(cond, then, False)


def do(*exprs) -> dict:
    """Sequence expressions, returning the last (`query.clj:88-102`)."""
    return {"do": list(exprs)}


def fn(params: list[str], expr) -> dict:
    """An anonymous function (`query.clj:104-119`)."""
    return {"lambda": params, "expr": expr}


def map_(coll, f) -> dict:
    return {"map": f, "collection": coll}


def foreach(coll, f) -> dict:
    return {"foreach": f, "collection": coll}


def create(ref_or_cls, params: dict) -> dict:
    """Create an instance (`query.clj:207-210`); creating against a
    class ref allocates a fresh id."""
    return {"create": ref_or_cls, "params": wrap(params)}


def update(r, params: dict) -> dict:
    return {"update": r, "params": wrap(params)}


def delete(r) -> dict:
    return {"delete": r}


def get(r) -> dict:
    return {"get": r}


def exists(r) -> dict:
    return {"exists": r}


def select(path: list, from_, default=None) -> dict:
    out = {"select": list(path), "from": from_}
    if default is not None:
        out["default"] = default
    return out


def create_class(params: dict) -> dict:
    return {"create_class": wrap(params)}


def create_index(params: dict) -> dict:
    return {"create_index": wrap(params)}


def match(idx, terms=None) -> dict:
    """The set of instances matching an index (`query.clj:229-234`)."""
    out = {"match": idx}
    if terms is not None:
        out["terms"] = wrap(terms)
    return out


def paginate(set_, size: int = 64, after=None) -> dict:
    out = {"paginate": set_, "size": size}
    if after is not None:
        out["after"] = after
    return out


def events(r) -> dict:
    """The instance's version history (`query.clj:323-326`)."""
    return {"events": r}


def time(s: str) -> dict:
    """A timestamp; "now" is the transaction time (`query.clj:192-195`)."""
    return {"time": s}


def at(ts, expr) -> dict:
    """Run expr against the snapshot at ts (`query.clj:187-190`)."""
    return {"at": ts, "expr": expr}


def abort(msg: str) -> dict:
    """Abort the transaction with a message (`query.clj:158-160`)."""
    return {"abort": msg}


def add(*xs) -> dict:
    return {"add": list(xs)}


def subtract(*xs) -> dict:
    return {"subtract": list(xs)}


def lt(*xs) -> dict:
    return {"lt": list(xs)}


def eq(*xs) -> dict:
    return {"equals": list(xs)}


def not_(x) -> dict:
    return {"not": x}


def and_(*xs) -> dict:
    return {"and": list(xs)}


def or_(*xs) -> dict:
    return {"or": list(xs)}


def non_empty(x) -> dict:
    """True iff a page/array has elements (`query.clj:253-255`)."""
    return {"non_empty": x}


def union(*sets) -> dict:
    """Set union over index matches (`query.clj:275-282`)."""
    return {"union": list(sets)}


def intersection(*sets) -> dict:
    """Set intersection over index matches (`query.clj:284-291`)."""
    return {"intersection": list(sets)}


def singleton(r) -> dict:
    """Lift a ref into a one-element set (`query.clj:328-330`)."""
    return {"singleton": r}


def cond(*clauses) -> dict:
    """cond-style chain: pairs of (test, expr) with an optional final
    default (`query.clj:174-185`)."""
    if len(clauses) == 1:
        return clauses[0]
    test, expr, *rest = clauses
    return if_(test, expr, cond(*rest) if rest else False)


def _mark(fn):
    @functools.wraps(fn)
    def g(*a, **k):
        out = fn(*a, **k)
        return Expr(out) if isinstance(out, dict) \
            and not isinstance(out, Expr) else out
    return g


for _name in ("class_", "index", "ref", "var", "let", "if_", "when", "do",
              "fn", "map_", "foreach", "create", "update", "delete", "get",
              "exists", "select", "create_class", "create_index", "match",
              "paginate", "events", "time", "at", "abort", "add",
              "subtract", "lt", "eq", "not_", "and_", "or_", "non_empty",
              "union", "intersection", "singleton", "cond"):
    globals()[_name] = _mark(globals()[_name])

NOW = Expr({"time": "now"})
