"""Minimal pure-Python RESP (REdis Serialization Protocol) client —
the wire protocol spoken by Disque (reference
`disque/src/jepsen/disque.clj`, via the Jedisque Java driver) and by
redis-family systems like raftis
(`raftis/src/jepsen/system/raftis.clj`).

Commands go as RESP arrays of bulk strings; replies parse into
str | int | None | list | RESPError.
"""

from __future__ import annotations

import socket

from .netutil import nodelay


class RESPError(Exception):
    pass


class Conn:
    def __init__(self, host: str, port: int, timeout_s: float = 5.0):
        self.sock = socket.create_connection((host, port), timeout_s)
        nodelay(self.sock)
        self.buf = b""

    def _line(self) -> bytes:
        while b"\r\n" not in self.buf:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise RESPError("connection closed by server")
            self.buf += chunk
        line, self.buf = self.buf.split(b"\r\n", 1)
        return line

    def _exact(self, n: int) -> bytes:
        while len(self.buf) < n:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise RESPError("connection closed by server")
            self.buf += chunk
        out, self.buf = self.buf[:n], self.buf[n:]
        return out

    def _reply(self):
        line = self._line()
        t, rest = line[:1], line[1:]
        if t == b"+":
            return rest.decode()
        if t == b"-":
            raise RESPError(rest.decode())
        if t == b":":
            return int(rest)
        if t == b"$":
            n = int(rest)
            if n < 0:
                return None
            data = self._exact(n)
            self._exact(2)  # trailing \r\n
            return data.decode()
        if t == b"*":
            n = int(rest)
            if n < 0:
                return None
            return [self._reply() for _ in range(n)]
        raise RESPError(f"bad reply type {t!r}")

    def call(self, *args):
        """Send one command, return its parsed reply."""
        out = b"*%d\r\n" % len(args)
        for a in args:
            b = a if isinstance(a, bytes) else str(a).encode()
            out += b"$%d\r\n%s\r\n" % (len(b), b)
        self.sock.sendall(out)
        return self._reply()

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass
