"""CockroachDB test suite.

Mirrors the reference's cockroachdb suite
(`/root/reference/cockroachdb/src/jepsen/cockroach{,.clj}/`): cluster
automation over the official binary tarball in insecure mode
(`auto.clj:60-140`), a Postgres-wire SQL layer with the reference's
retry/abort classification — SQLSTATE 40001 serialization conflicts are
definite aborts (`client.clj:150-210`) — and the workload menu:
bank (`bank.clj`), elle rw-register (BASELINE config 3 at 10k txns),
independent linearizable register (`register.clj`), grow-only set
(`sets.clj`), the Adya G2 predicate probe (`adya.clj`), and the
additional-graphs consumers: monotonic (`monotonic.clj`), sequential
(`sequential.clj`), and the realtime-gap comments probe
(`comments.clj`).

The clock-skew nemesis family (`nemesis.clj:201-270`, driving the
suite-local bumptime/adjtime C tools) maps to the framework clock
package, which compiles and runs the native C++ time tools on each node
(jepsen_tpu/native/{bump_time,strobe_time,adj_time}.cpp).

Clients speak the wire protocol directly (`pg_proto.py`); hermetic
tests run against an in-process Postgres-protocol fake
(tests/fake_pg.py), the reference's dummy tier.
"""

from __future__ import annotations

import itertools
import logging

from .. import checker, cli, client as jclient, control
from .. import db as jdb
from .. import generator as gen
from .. import independent
from ..control import util as cu
from ..workloads import adya as adya_w, bank as bank_w, \
    comments as comments_w, linearizable_register, \
    monotonic as monotonic_w, sequential as sequential_w, wr as wr_w
from . import std_opts, std_test
from .pg_proto import Conn, PGError

log = logging.getLogger(__name__)

DIR = "/opt/cockroach"
BINARY = f"{DIR}/cockroach"
LOGFILE = f"{DIR}/cockroach.log"
PIDFILE = f"{DIR}/cockroach.pid"
STORE = f"{DIR}/data"

SQL_PORT = 26257
HTTP_PORT = 8080

DEFAULT_VERSION = "2.1.6"

# SQLSTATEs that mean the txn definitely rolled back: serialization
# conflicts CockroachDB asks clients to retry (`client.clj:150-210`).
# 40003 (statement_completion_unknown / "result is ambiguous") is NOT
# here: the commit may have applied, so it must classify as :info.
DEFINITE_ABORT = {"40001", "40P01"}


def tarball_url(version: str) -> str:
    return (f"https://binaries.cockroachdb.com/"
            f"cockroach-v{version}.linux-amd64.tgz")


class DB(jdb.DB, jdb.Process, jdb.Pause, jdb.LogFiles):
    """cockroach start --insecure on every node, joined to the full
    node list (`auto.clj:60-140`)."""

    def __init__(self, version: str = DEFAULT_VERSION):
        self.version = version

    def setup(self, test, node):
        with control.su():
            log.info("%s installing cockroach %s", node, self.version)
            url = test.get("tarball") or tarball_url(self.version)
            cu.install_archive(url, DIR)
            control.exec_("mkdir", "-p", STORE)
            self.start(test, node)
            cu.await_tcp_port(SQL_PORT)
            if node == test["nodes"][0]:
                control.exec_(BINARY, "init", "--insecure",
                              f"--host={node}:{SQL_PORT}")

    def start(self, test, node):
        join = ",".join(f"{n}:{SQL_PORT}" for n in test["nodes"])
        with control.su():
            cu.start_daemon(
                {"logfile": LOGFILE, "pidfile": PIDFILE, "chdir": DIR},
                BINARY, "start", "--insecure",
                f"--store={STORE}",
                f"--listen-addr=0.0.0.0:{SQL_PORT}",
                f"--advertise-addr={node}:{SQL_PORT}",
                f"--http-addr=0.0.0.0:{HTTP_PORT}",
                f"--join={join}",
                "--background")

    def teardown(self, test, node):
        log.info("%s tearing down cockroach", node)
        with control.su():
            self.kill(test, node)
            control.exec_("rm", "-rf", STORE, LOGFILE, PIDFILE)

    def kill(self, test, node):
        with control.su():
            cu.stop_daemon(PIDFILE, cmd="cockroach")
            cu.grepkill("cockroach")

    def pause(self, test, node):
        with control.su():
            cu.signal("cockroach", "STOP")

    def resume(self, test, node):
        with control.su():
            cu.signal("cockroach", "CONT")

    def log_files(self, test, node):
        return [LOGFILE]


def db(version: str = DEFAULT_VERSION) -> DB:
    return DB(version)


# -- SQL layer (`client.clj`) ------------------------------------------------

def _connect(test, node) -> Conn:
    fn = test.get("sql-conn-fn")
    if fn is not None:
        return fn(node)
    return Conn(node, SQL_PORT, user="root", database="jepsen",
                timeout_s=10.0)


def _q(s) -> str:
    if isinstance(s, bool):
        raise ValueError("no boolean literals here")
    if isinstance(s, int):
        return str(s)
    s = str(s)
    if "'" in s or "\\" in s:
        raise ValueError(f"unquotable literal {s!r}")
    return f"'{s}'"


class _SQLClient(jclient.Client):
    """Shared open/close and the reference's error classification:
    DEFINITE_ABORT SQLSTATEs -> fail; other errors -> info unless the
    op was read-only (`client.clj:150-210`)."""

    def __init__(self):
        self.conn: Conn | None = None

    def open(self, test, node):
        c = type(self).__new__(type(self))
        c.__dict__.update(self.__dict__)
        c.conn = _connect(test, node)
        return c

    def close(self, test):
        if self.conn is not None:
            self.conn.close()

    def _capture(self, op, e: Exception, read_only: bool) -> dict:
        if isinstance(e, PGError):
            if e.code in DEFINITE_ABORT or read_only:
                return {**op, "type": "fail",
                        "error": ["sql", e.code, e.message]}
            return {**op, "type": "info",
                    "error": ["sql", e.code, e.message]}
        return {**op, "type": "fail" if read_only else "info",
                "error": ["conn", str(e)]}

    def _txn(self, stmts_fn, op, read_only=False):
        conn = self.conn
        try:
            conn.query("begin")
            out = stmts_fn(conn)
            conn.query("commit")
            return {**op, "type": "ok", **out}
        except Exception as e:  # noqa: BLE001 — classified below
            try:
                conn.query("rollback")
            except Exception:  # noqa: BLE001 — conn may be dead
                pass
            if isinstance(e, (PGError, OSError, ConnectionError)):
                return self._capture(op, e, read_only)
            raise


# -- bank (`bank.clj`) -------------------------------------------------------

class BankClient(_SQLClient):
    def setup(self, test):
        self.conn.query("create table if not exists accounts "
                        "(id int primary key, balance bigint)")
        accounts = test.get("accounts", list(range(8)))
        total = test.get("total-amount", 100)
        for a in accounts:
            self.conn.query(
                f"upsert into accounts (id, balance) values "
                f"({_q(a)}, {_q(total if a == accounts[0] else 0)})")

    def invoke(self, test, op):
        if op["f"] == "read":
            def read_body(conn):
                rows, _ = conn.query("select id, balance from accounts")
                return {"value": {int(r[0]): int(r[1]) for r in rows}}
            return self._txn(read_body, op, read_only=True)

        v = op["value"]
        frm, to, amount = v["from"], v["to"], v["amount"]

        def transfer_body(conn):
            rows, _ = conn.query(
                f"select balance from accounts where id = {_q(frm)}")
            b1 = int(rows[0][0]) - amount
            rows, _ = conn.query(
                f"select balance from accounts where id = {_q(to)}")
            b2 = int(rows[0][0]) + amount
            if b1 < 0:
                raise _InsufficientFunds(frm, b1)
            conn.query(f"update accounts set balance = {_q(b1)} "
                       f"where id = {_q(frm)}")
            conn.query(f"update accounts set balance = {_q(b2)} "
                       f"where id = {_q(to)}")
            return {}

        try:
            return self._txn(transfer_body, op)
        except _InsufficientFunds as e:
            return {**op, "type": "fail",
                    "value": ["negative", e.account, e.balance]}


class _InsufficientFunds(Exception):
    def __init__(self, account, balance):
        super().__init__(f"{account} would go to {balance}")
        self.account = account
        self.balance = balance


# -- rw-register txns (`register.clj` + elle wr) -----------------------------

class WrTxnClient(_SQLClient):
    """[f k v] micro-op transactions over a single striped table."""

    def setup(self, test):
        self.conn.query("create table if not exists txns "
                        "(id int primary key, val int)")

    def _mop(self, conn, m):
        f, k, v = m[0], m[1], m[2]
        if f == "r":
            rows, _ = conn.query(
                f"select val from txns where id = {_q(k)}")
            val = None if not rows or rows[0][0] is None \
                else int(rows[0][0])
            return ["r", k, val]
        conn.query(f"upsert into txns (id, val) values "
                   f"({_q(k)}, {_q(v)})")
        return ["w", k, v]

    def invoke(self, test, op):
        txn = op["value"]

        def body(conn):
            return {"value": [self._mop(conn, m) for m in txn]}
        return self._txn(body, op,
                         read_only=all(m[0] == "r" for m in txn))


# -- monotonic (`monotonic.clj`) ---------------------------------------------

class MonotonicClient(_SQLClient):
    """Read-increment-write registers (`monotonic.clj:33-88`): a 'w'
    micro-op with a nil value writes its key's just-read value + 1;
    CockroachDB's serializable default makes the read-modify-write
    atomic without explicit locks."""

    def setup(self, test):
        self.conn.query("create table if not exists mono "
                        "(id int primary key, val int)")

    def invoke(self, test, op):
        txn = op["value"]

        def body(conn):
            out = []
            cur: dict = {}
            for m in txn:
                f, k, v = m[0], m[1], m[2]
                if f == "r":
                    rows, _ = conn.query(
                        f"select val from mono where id = {_q(k)}")
                    val = None if not rows or rows[0][0] is None \
                        else int(rows[0][0])
                    cur[k] = val
                    out.append(["r", k, val])
                else:
                    val = v if v is not None else (cur.get(k) or 0) + 1
                    conn.query(f"upsert into mono (id, val) values "
                               f"({_q(k)}, {_q(val)})")
                    cur[k] = val
                    out.append(["w", k, val])
            return {"value": out}

        return self._txn(body, op,
                         read_only=all(m[0] == "r" for m in txn))


# -- comments (`comments.clj`) -----------------------------------------------

class CommentsClient(_SQLClient):
    """Insert numbered rows, read all of them back (`comments.clj:
    20-63` — the suite's realtime-gap probe)."""

    def setup(self, test):
        self.conn.query("create table if not exists comments "
                        "(id int primary key, val int)")

    def invoke(self, test, op):
        if op["f"] == "write":
            def write_body(conn):
                conn.query(f"insert into comments (id, val) values "
                           f"({_q(op['value'])}, 1)")
                return {}
            return self._txn(write_body, op)

        def read_body(conn):
            rows, _ = conn.query("select id from comments")
            return {"value": sorted(int(r[0]) for r in rows)}
        return self._txn(read_body, op, read_only=True)


# -- linearizable register (`register.clj`) ----------------------------------

class RegisterClient(_SQLClient):
    def setup(self, test):
        self.conn.query("create table if not exists test "
                        "(id int primary key, val int)")

    def invoke(self, test, op):
        v = op["value"]
        if independent.is_tuple(v):
            k, inner = v

            def wrap(x):
                return independent.ktuple(k, x)
        else:
            k, inner = 0, v

            def wrap(x):
                return x

        if op["f"] == "read":
            try:
                rows, _ = self.conn.query(
                    f"select val from test where id = {_q(k)}")
                val = None if not rows or rows[0][0] is None \
                    else int(rows[0][0])
                return {**op, "type": "ok", "value": wrap(val)}
            except Exception as e:  # noqa: BLE001 — classified
                return self._capture(op, e, read_only=True)

        if op["f"] == "write":
            def write_body(conn):
                conn.query(f"upsert into test (id, val) values "
                           f"({_q(k)}, {_q(inner)})")
                return {}
            return self._txn(write_body, op)

        old, new = inner

        def cas_body(conn):
            rows, _ = conn.query(
                f"select val from test where id = {_q(k)}")
            cur = None if not rows or rows[0][0] is None \
                else int(rows[0][0])
            if cur != old:
                raise _CasFail()
            conn.query(f"update test set val = {_q(new)} "
                       f"where id = {_q(k)}")
            return {}

        try:
            return self._txn(cas_body, op)
        except _CasFail:
            return {**op, "type": "fail"}


class _CasFail(Exception):
    pass


# -- grow-only set (`sets.clj`) ----------------------------------------------

class SetClient(_SQLClient):
    def setup(self, test):
        self.conn.query("create table if not exists sets "
                        "(id serial primary key, val bigint)")

    def invoke(self, test, op):
        if op["f"] == "add":
            def add_body(conn):
                conn.query(
                    f"insert into sets (val) values ({_q(op['value'])})")
                return {}
            return self._txn(add_body, op)

        def read_body(conn):
            rows, _ = conn.query("select val from sets")
            return {"value": sorted(int(r[0]) for r in rows)}
        return self._txn(read_body, op, read_only=True)


# -- Adya G2 predicate probe (`adya.clj`) ------------------------------------

class G2Client(_SQLClient):
    """Each insert txn reads both tables by key predicate and inserts
    its row only if both are empty — serializability allows at most one
    success per key."""

    def setup(self, test):
        self.conn.query("create table if not exists a "
                        "(id int primary key, k int, val int)")
        self.conn.query("create table if not exists b "
                        "(id int primary key, k int, val int)")

    def invoke(self, test, op):
        v = op["value"]
        k, ids = (v.key, v.value) if independent.is_tuple(v) else (0, v)
        a_id, b_id = ids

        def body(conn):
            ra, _ = conn.query(f"select id from a where k = {_q(k)}")
            rb, _ = conn.query(f"select id from b where k = {_q(k)}")
            if ra or rb:
                raise _G2Blocked()
            if a_id is not None:
                conn.query(f"insert into a (id, k, val) values "
                           f"({_q(a_id)}, {_q(k)}, 1)")
            else:
                conn.query(f"insert into b (id, k, val) values "
                           f"({_q(b_id)}, {_q(k)}, 1)")
            return {}

        try:
            return self._txn(body, op)
        except _G2Blocked:
            return {**op, "type": "fail", "error": "already-inserted"}


class _G2Blocked(Exception):
    pass


# -- workloads ---------------------------------------------------------------

def bank_workload(opts: dict) -> dict:
    w = bank_w.test(opts)
    w["client"] = BankClient()
    return w


def wr_workload(opts: dict) -> dict:
    w = wr_w.workload(opts)
    w["client"] = WrTxnClient()
    return w


def register_workload(opts: dict) -> dict:
    w = linearizable_register.test({
        "nodes": opts["nodes"],
        "per-key-limit": opts.get("ops-per-key", 100),
    })
    w["client"] = RegisterClient()
    return w


def set_workload(opts: dict) -> dict:
    adds = ({"type": "invoke", "f": "add", "value": i}
            for i in itertools.count())
    return {
        "client": SetClient(),
        "checker": checker.set_checker(),
        "generator": adds,
        "final-generator": gen.each_thread(gen.once(
            {"type": "invoke", "f": "read", "value": None})),
    }


def g2_workload(opts: dict) -> dict:
    w = adya_w.workload()
    w["client"] = G2Client()
    return w


def monotonic_workload(opts: dict) -> dict:
    w = monotonic_w.workload(opts)
    w["client"] = MonotonicClient()
    return w


def sequential_workload(opts: dict) -> dict:
    w = sequential_w.workload(opts)
    w["client"] = WrTxnClient()
    return w


def comments_workload(opts: dict) -> dict:
    w = comments_w.workload(opts)
    w["client"] = CommentsClient()
    return w


WORKLOADS = {
    "bank": bank_workload,
    "wr": wr_workload,
    "register": register_workload,
    "set": set_workload,
    "g2": g2_workload,
    "monotonic": monotonic_workload,
    "sequential": sequential_workload,
    "comments": comments_workload,
}


def cockroach_test(opts: dict) -> dict:
    workload_name = opts.get("workload", "bank")
    return std_test(
        opts, name=f"cockroach-{workload_name}",
        db=db(opts.get("version", DEFAULT_VERSION)),
        workload=WORKLOADS[workload_name](opts))


OPT_SPEC = std_opts(cli, WORKLOADS, "bank", DEFAULT_VERSION,
                    "CockroachDB version to install") + [
    cli.opt("--ops-per-key", type=int, default=100,
            help="ops per independent key (register workload)"),
]


def main(argv=None):
    cli.run({**cli.single_test_cmd({"test_fn": cockroach_test,
                                    "opt_spec": OPT_SPEC}),
             **cli.serve_cmd()}, argv)


if __name__ == "__main__":
    main()
