"""Consul test suite: a CAS register over the HTTP KV store.

Mirrors the reference's consul suite (`consul/src/jepsen/consul/
{db,client,register}.clj`): single-binary install, bootstrap-mode
primary with retry-join followers, and a KV client whose CAS is
*index*-based — consul has no value CAS, so the client reads the key's
ModifyIndex and conditions the write on it (`client.clj:66-80`),
classifying errors with the usual read-fail/write-info discipline.
"""

from __future__ import annotations

import base64
import json
import logging
import urllib.error
import urllib.request

from .. import checker, cli, client as jclient, control
from .. import db as jdb
from .. import generator as gen
from .. import models, testkit
from ..checker import timeline
from ..control import util as cu
from ..nemesis import partition
from ..os_ import debian

log = logging.getLogger(__name__)

DIR = "/opt/consul"
BINARY = f"{DIR}/consul"
PIDFILE = f"{DIR}/consul.pid"
LOGFILE = f"{DIR}/consul.log"
DATA_DIR = f"{DIR}/data"
HTTP_PORT = 8500

DEFAULT_VERSION = "1.17.0"


def zip_url(version: str) -> str:
    return (f"https://releases.hashicorp.com/consul/{version}/"
            f"consul_{version}_linux_amd64.zip")


class DB(jdb.DB, jdb.Process, jdb.LogFiles):
    """Single-binary consul cluster: first node bootstraps, the rest
    retry-join it (`consul/db.clj:23-51`)."""

    def __init__(self, version: str = DEFAULT_VERSION):
        self.version = version

    def setup(self, test, node):
        with control.su():
            log.info("%s installing consul %s", node, self.version)
            cu.install_archive(test.get("tarball")
                               or zip_url(self.version), DIR)
            self.start(test, node)
            cu.await_tcp_port(HTTP_PORT)

    def start(self, test, node):
        primary = test["nodes"][0]
        args = ["agent", "-server", "-log-level", "debug",
                "-client", "0.0.0.0", "-bind", node,
                "-data-dir", DATA_DIR, "-node", node,
                "-retry-interval", "5s"]
        if node == primary:
            args.append("-bootstrap")
        else:
            args += ["-retry-join", primary]
        with control.su():
            cu.start_daemon({"logfile": LOGFILE, "pidfile": PIDFILE,
                             "chdir": DIR}, BINARY, *args)

    def kill(self, test, node):
        with control.su():
            cu.stop_daemon(PIDFILE, cmd="consul")
            cu.grepkill("consul")

    def teardown(self, test, node):
        with control.su():
            self.kill(test, node)
            control.exec_("rm", "-rf", DATA_DIR, LOGFILE, PIDFILE)

    def log_files(self, test, node):
        return [LOGFILE]


def db(version: str = DEFAULT_VERSION) -> DB:
    return DB(version)


class ConsulClient(jclient.Client):
    """CAS register over /v1/kv. Reads parse the base64 Value and
    ModifyIndex; CAS conditions a PUT on ?cas=<index>
    (`consul/client.clj`)."""

    KEY = "jepsen"

    def __init__(self, timeout_s: float = 5.0, url: str | None = None):
        self.timeout_s = timeout_s
        self.url = url

    def open(self, test, node):
        url = test.get("consul-url-fn",
                       lambda n: f"http://{n}:{HTTP_PORT}")(node)
        return ConsulClient(self.timeout_s, url)

    def _kv(self, params: str = "") -> str:
        return f"{self.url}/v1/kv/{self.KEY}{params}"

    def get(self):
        """-> (value | None, modify_index)."""
        try:
            with urllib.request.urlopen(self._kv(),
                                        timeout=self.timeout_s) as r:
                body = json.loads(r.read())
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None, 0
            raise
        ent = body[0]
        raw = ent.get("Value")
        val = int(base64.b64decode(raw)) if raw is not None else None
        return val, ent["ModifyIndex"]

    def put(self, value, cas_index: int | None = None) -> bool:
        params = f"?cas={cas_index}" if cas_index is not None else ""
        req = urllib.request.Request(self._kv(params),
                                     data=str(value).encode(),
                                     method="PUT")
        with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
            return r.read().strip() == b"true"

    def invoke(self, test, op):
        f = op["f"]
        if f not in ("read", "write", "cas"):
            raise ValueError(f"unknown f {f!r}")
        try:
            if f == "read":
                val, _ = self.get()
                return {**op, "type": "ok", "value": val}
            if f == "write":
                ok = self.put(op["value"])
                return {**op, "type": "ok" if ok else "fail"}
            old, new = op["value"]
            val, index = self.get()
            if val != old:
                return {**op, "type": "fail"}
            ok = self.put(new, cas_index=index)
            return {**op, "type": "ok" if ok else "fail"}
        except urllib.error.HTTPError as e:
            return {**op, "type": "fail" if f == "read" else "info",
                    "error": ["http", e.code]}
        except (urllib.error.URLError, OSError, ValueError) as e:
            if "refused" in str(e):
                return {**op, "type": "fail",
                        "error": "connection-refused"}
            return {**op, "type": "fail" if f == "read" else "info",
                    "error": ["indeterminate", str(e)]}


def r(test, ctx):
    return {"type": "invoke", "f": "read", "value": None}


def w(test, ctx):
    return {"type": "invoke", "f": "write", "value": gen.rng.randrange(5)}


def cas(test, ctx):
    return {"type": "invoke", "f": "cas",
            "value": [gen.rng.randrange(5), gen.rng.randrange(5)]}


def consul_test(opts: dict) -> dict:
    """Register test over consul KV (`consul/register.clj`)."""
    time_limit = opts.get("time-limit", opts.get("time_limit", 60))
    rate = float(opts.get("rate", 10))
    return {
        **testkit.noop_test(),
        **{k: v for k, v in opts.items() if isinstance(k, str)},
        "name": "consul",
        "os": debian.os,
        "db": db(opts.get("version", DEFAULT_VERSION)),
        "client": ConsulClient(),
        "nemesis": partition.partition_random_halves(),
        "generator": gen.time_limit(time_limit, gen.nemesis(
            gen.cycle(gen.phases(
                gen.sleep(5),
                gen.once({"type": "info", "f": "start", "value": None}),
                gen.sleep(5),
                gen.once({"type": "info", "f": "stop", "value": None}))),
            gen.stagger(1 / rate, gen.mix([r, w, cas])))),
        "checker": checker.compose({
            "linear": checker.linearizable(models.cas_register()),
            "timeline": timeline.html(),
            "perf": checker.perf_checker(),
        }),
    }


OPT_SPEC = [
    cli.opt("--version", default=DEFAULT_VERSION,
            help="Consul version to install"),
    cli.opt("--rate", type=float, default=10,
            help="approximate ops per second"),
]


def main(argv=None):
    cli.run({**cli.single_test_cmd({"test_fn": consul_test,
                                    "opt_spec": OPT_SPEC}),
             **cli.serve_cmd()}, argv)


if __name__ == "__main__":
    main()
