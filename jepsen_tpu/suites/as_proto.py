"""Minimal Aerospike binary wire client.

The reference's aerospike suite speaks to the cluster through the Java
`AerospikeClient` (`aerospike/src/aerospike/support.clj:340-445`:
put!/put-if-absent!/append!/add!/fetch/cas! over Policy /
GenerationPolicy / linearize-read). This module implements the same
operations directly on Aerospike's wire protocol — an 8-byte proto
header (version 2; type 1 = info, 3 = message) followed by a 22-byte
message header, fields, and ops — so the framework needs no driver
dependency. Hermetic tests run against `tests/fake_aerospike.py`,
which serves the same format.

Divergence note: the real protocol addresses records by the
RIPEMD-160 digest of (set, key); we send the user key field (which the
real protocol also carries) and the fake resolves on it. The suite's
semantics — generation CAS, append, add, linearized reads — are
identical.
"""

from __future__ import annotations

import socket

from .netutil import nodelay
import struct
import threading

# proto header types
T_INFO = 1
T_MESSAGE = 3

# info1/2/3 bits (subset used by the suite)
INFO1_READ = 0x01
INFO1_GET_ALL = 0x02
INFO2_WRITE = 0x01
INFO2_GENERATION = 0x04        # EXPECT_GEN_EQUAL
INFO2_CREATE_ONLY = 0x20       # RecordExistsAction/CREATE_ONLY
INFO3_LINEARIZE_READ = 0x40    # strong-consistency linearized read

# field types
FIELD_NAMESPACE = 0
FIELD_SET = 1
FIELD_KEY = 2

# op types
OP_READ = 1
OP_WRITE = 2
OP_INCR = 5
OP_APPEND = 9

# particle types
PT_INTEGER = 1
PT_STRING = 3

# result codes (support.clj:453-501 classifies these)
RC_OK = 0
RC_KEY_NOT_FOUND = 2
RC_GENERATION = 3
RC_PARAMETER = 4
RC_KEY_EXISTS = 5
RC_SERVER_NOT_AVAILABLE = -8
RC_PARTITION_UNAVAILABLE = 11
RC_HOT_KEY = 14
RC_FORBIDDEN = 22


class ASError(Exception):
    def __init__(self, code: int, message: str = ""):
        super().__init__(f"aerospike error {code}: {message}")
        self.code = code
        self.message = message


def _encode_value(v) -> tuple[int, bytes]:
    if isinstance(v, bool):
        raise ASError(RC_PARAMETER, "bool bins unsupported")
    if isinstance(v, int):
        return PT_INTEGER, struct.pack(">q", v)
    if isinstance(v, str):
        return PT_STRING, v.encode()
    raise ASError(RC_PARAMETER, f"unsupported bin value {v!r}")


def _decode_value(pt: int, data: bytes):
    if pt == PT_INTEGER:
        return struct.unpack(">q", data)[0]
    if pt == PT_STRING:
        return data.decode()
    raise ASError(RC_PARAMETER, f"unsupported particle type {pt}")


def _field(ftype: int, data: bytes) -> bytes:
    return struct.pack(">IB", len(data) + 1, ftype) + data


def _op(op_type: int, name: str, value=None) -> bytes:
    nb = name.encode()
    if value is None:
        body = struct.pack(">BBBB", op_type, 0, 0, len(nb)) + nb
    else:
        pt, vb = _encode_value(value)
        body = struct.pack(">BBBB", op_type, pt, 0, len(nb)) + nb + vb
    return struct.pack(">I", len(body)) + body


def key_fields(namespace: str, set_name: str, key) -> list[bytes]:
    pt, kb = _encode_value(key)
    return [_field(FIELD_NAMESPACE, namespace.encode()),
            _field(FIELD_SET, set_name.encode()),
            _field(FIELD_KEY, bytes([pt]) + kb)]


def encode_message(info1: int, info2: int, info3: int, generation: int,
                   fields: list[bytes], ops: list[bytes],
                   result_code: int = 0) -> bytes:
    body = b"".join(fields) + b"".join(ops)
    hdr = struct.pack(">BBBBBBIIIHH", 22, info1, info2, info3, 0,
                      result_code & 0xFF, generation, 0, 1000,
                      len(fields), len(ops))
    msg = hdr + body
    proto = struct.pack(">Q", (2 << 56) | (T_MESSAGE << 48) | len(msg))
    return proto + msg


def decode_message(payload: bytes):
    """-> (result_code, generation, fields: list[(ftype, data)],
    bins: dict)."""
    (hsz, i1, i2, i3, _u, rc, gen, _exp, _ttl,
     n_fields, n_ops) = struct.unpack(">BBBBBBIIIHH", payload[:22])
    rc = rc - 256 if rc > 127 else rc  # signed result codes
    off = hsz
    fields = []
    for _ in range(n_fields):
        sz, ftype = struct.unpack(">IB", payload[off:off + 5])
        fields.append((ftype, payload[off + 5:off + 4 + sz]))
        off += 4 + sz
    bins = {}
    for _ in range(n_ops):
        sz, = struct.unpack(">I", payload[off:off + 4])
        op_type, pt, _ver, nlen = struct.unpack(
            ">BBBB", payload[off + 4:off + 8])
        name = payload[off + 8:off + 8 + nlen].decode()
        vdata = payload[off + 8 + nlen:off + 4 + sz]
        bins[name] = _decode_value(pt, vdata) if vdata else None
        off += 4 + sz
    return rc, gen, fields, (i1, i2, i3), bins


class Conn:
    """One Aerospike node connection."""

    def __init__(self, host: str, port: int = 3000,
                 timeout_s: float = 10.0):
        self.sock = socket.create_connection((host, port), timeout_s)
        nodelay(self.sock)
        self.lock = threading.Lock()

    def _read_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ASError(RC_SERVER_NOT_AVAILABLE,
                              "connection closed by server")
            buf += chunk
        return buf

    def _roundtrip(self, msg: bytes):
        with self.lock:
            self.sock.sendall(msg)
            proto, = struct.unpack(">Q", self._read_exact(8))
            size = proto & ((1 << 48) - 1)
            ptype = (proto >> 48) & 0xFF
            payload = self._read_exact(size)
        return ptype, payload

    # -- message commands ---------------------------------------------------

    def _command(self, info1, info2, info3, generation, fields, ops):
        ptype, payload = self._roundtrip(
            encode_message(info1, info2, info3, generation, fields, ops))
        if ptype != T_MESSAGE:
            raise ASError(RC_SERVER_NOT_AVAILABLE,
                          f"unexpected proto type {ptype}")
        rc, gen, _fields, _info, bins = decode_message(payload)
        return rc, gen, bins

    def get(self, namespace: str, set_name: str, key,
            linearize: bool = True) -> dict | None:
        """-> {'generation': g, 'bins': {...}} or None if absent
        (support.clj fetch, with the linearize-read policy)."""
        rc, gen, bins = self._command(
            INFO1_READ | INFO1_GET_ALL, 0,
            INFO3_LINEARIZE_READ if linearize else 0, 0,
            key_fields(namespace, set_name, key), [])
        if rc == RC_KEY_NOT_FOUND:
            return None
        if rc != RC_OK:
            raise ASError(rc)
        return {"generation": gen, "bins": bins}

    def put(self, namespace: str, set_name: str, key, bins: dict,
            generation: int | None = None,
            create_only: bool = False) -> None:
        info2 = INFO2_WRITE
        if generation is not None:
            info2 |= INFO2_GENERATION
        if create_only:
            info2 |= INFO2_CREATE_ONLY
        rc, _g, _b = self._command(
            0, info2, 0, generation or 0,
            key_fields(namespace, set_name, key),
            [_op(OP_WRITE, k, v) for k, v in bins.items()])
        if rc != RC_OK:
            raise ASError(rc)

    def append(self, namespace: str, set_name: str, key,
               bins: dict) -> None:
        rc, _g, _b = self._command(
            0, INFO2_WRITE, 0, 0,
            key_fields(namespace, set_name, key),
            [_op(OP_APPEND, k, v) for k, v in bins.items()])
        if rc != RC_OK:
            raise ASError(rc)

    def add(self, namespace: str, set_name: str, key, bins: dict) -> None:
        rc, _g, _b = self._command(
            0, INFO2_WRITE, 0, 0,
            key_fields(namespace, set_name, key),
            [_op(OP_INCR, k, v) for k, v in bins.items()])
        if rc != RC_OK:
            raise ASError(rc)

    # -- info protocol --------------------------------------------------------

    def info(self, *commands: str) -> dict[str, str]:
        """Text info protocol: newline-separated commands, tab-separated
        replies (support.clj server-info)."""
        payload = ("\n".join(commands) + "\n").encode()
        proto = struct.pack(">Q", (2 << 56) | (T_INFO << 48)
                            | len(payload))
        ptype, reply = self._roundtrip(proto + payload)
        if ptype != T_INFO:
            raise ASError(RC_SERVER_NOT_AVAILABLE,
                          f"unexpected proto type {ptype}")
        out = {}
        for line in reply.decode().splitlines():
            if not line:
                continue
            k, _, v = line.partition("\t")
            out[k] = v
        return out

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


