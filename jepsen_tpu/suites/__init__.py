"""Per-database test suites (the reference's L7 layer).

Each suite module exposes `test_fn(opts) -> test-map`, a workload menu,
and `main(argv)` wired through `jepsen_tpu.cli` — mirroring how every
reference suite exposes `-main` via `jepsen.cli` (e.g.
`zookeeper/src/jepsen/zookeeper.clj:131-137`)."""

from __future__ import annotations

import importlib
import json
import urllib.request

SUITES = ("etcd", "zookeeper", "hazelcast", "consul", "tidb",
          "cockroach")


def suite(name: str):
    """Load a suite module by name."""
    if name not in SUITES:
        raise ValueError(f"unknown suite {name!r}; known: {SUITES}")
    return importlib.import_module(f".{name}", __name__)


def http_post(url: str, body: dict, timeout: float = 5.0) -> dict:
    """POST a JSON body, parse a JSON response — the shared transport
    for HTTP-spoken data planes (etcd's v3 gateway, the CP shim)."""
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())
