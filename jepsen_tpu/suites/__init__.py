"""Per-database test suites (the reference's L7 layer).

Each suite module exposes `test_fn(opts) -> test-map`, a workload menu,
and `main(argv)` wired through `jepsen_tpu.cli` — mirroring how every
reference suite exposes `-main` via `jepsen.cli` (e.g.
`zookeeper/src/jepsen/zookeeper.clj:131-137`)."""

from __future__ import annotations

import importlib
import json
import urllib.request

SUITES = ("etcd", "zookeeper", "hazelcast", "consul", "tidb",
          "cockroach", "disque", "rabbitmq", "galera", "percona",
          "stolon", "postgres_rds", "raftis", "mongodb", "aerospike",
          "mongodb_smartos", "logcabin", "robustirc",
          "mysql_cluster", "rethinkdb", "elasticsearch", "crate",
          "ignite", "chronos", "yugabyte", "faunadb", "dgraph")


def suite(name: str):
    """Load a suite module by name."""
    name = name.replace("-", "_")
    if name not in SUITES:
        raise ValueError(f"unknown suite {name!r}; known: {SUITES}")
    return importlib.import_module(f".{name}", __name__)


def std_test(opts: dict, *, name: str, db, workload: dict,
             os=None, default_faults=("partition",),
             nemesis_package: dict | None = None,
             extra: dict | None = None) -> dict:
    """Assemble the standard suite test map: workload client/checker +
    nemesis package from opts['faults'] + staggered client generator
    under a time limit, then nemesis-final and workload-final phases,
    with the perf/timeline/stats/exceptions checker stack every
    reference suite composes. Mirrors the per-suite test-map builders
    (e.g. `zookeeper.clj:106-129`)."""
    from .. import checker, generator as gen, testkit
    from ..checker import timeline
    from ..nemesis import combined
    from ..os_ import debian

    faults = [f for f in (opts.get("faults") or list(default_faults))
              if f != "none"]
    if nemesis_package is not None:
        # suites with bespoke nemesis stacks (e.g. aerospike's capped
        # kill + revive/recluster) supply the package whole
        pkg = nemesis_package
    elif faults:
        pkg = combined.nemesis_package({
            "db": db, "faults": faults,
            "interval": opts.get("nemesis-interval", 10)})
    else:
        pkg = combined.noop

    rate = float(opts.get("rate", 10))
    time_limit = opts.get("time-limit", opts.get("time_limit", 60))
    client_gen = gen.clients(gen.stagger(1 / rate,
                                         workload["generator"]))
    main_gen = gen.time_limit(
        time_limit,
        gen.any(client_gen, gen.nemesis(pkg["generator"]))
        if pkg.get("generator") else client_gen)
    phases = [main_gen]
    if pkg.get("final-generator"):
        phases.append(gen.nemesis(pkg["final-generator"]))
    if workload.get("final-generator"):
        phases.append(gen.clients(workload["final-generator"]))
    generator = gen.phases(*phases) if len(phases) > 1 else main_gen

    return {
        **testkit.noop_test(),
        **{k: v for k, v in opts.items() if isinstance(k, str)},
        "name": name,
        "os": os if os is not None else debian.os,
        "db": db,
        "client": workload["client"],
        "nemesis": pkg["nemesis"],
        "plot": {"nemeses": pkg.get("perf")},
        "generator": generator,
        "checker": checker.compose({
            "perf": checker.perf_checker(),
            "timeline": timeline.html(),
            "workload": workload["checker"],
            "stats": checker.stats(),
            "exceptions": checker.unhandled_exceptions(),
        }),
        **(extra or {}),
    }


STD_FAULT_CHOICES = ["partition", "kill", "pause", "clock", "none"]


def std_opts(cli, workloads: dict, default_workload: str,
             version_default: str | None = None,
             version_help: str = "version to install") -> list:
    """The shared option spec every suite CLI extends."""
    spec = [
        cli.opt("--workload", "-w", default=default_workload,
                choices=sorted(workloads), help="Which workload to run"),
        cli.opt("--rate", type=float, default=10,
                help="approximate op rate per second"),
        cli.opt("--faults", action="append", choices=STD_FAULT_CHOICES,
                help="faults to inject (repeatable)"),
        cli.opt("--nemesis-interval", type=float, default=10,
                help="seconds between nemesis operations"),
    ]
    if version_default is not None:
        spec.append(cli.opt("--version", default=version_default,
                            help=version_help))
    return spec


def http_post(url: str, body: dict, timeout: float = 5.0) -> dict:
    """POST a JSON body, parse a JSON response — the shared transport
    for HTTP-spoken data planes (etcd's v3 gateway, the CP shim)."""
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())
