"""A CP-service HTTP shim: the data plane for the hazelcast-style
suite.

The reference's hazelcast suite ships its own in-repo server component
(`hazelcast/server/`) wrapping the DB's client API for the harness to
drive; this module plays that role as a self-contained HTTP service
exposing the CP-subsystem primitives the workload menu exercises —
locks, semaphores, atomic (CAS) references, unique-id generation, and
queues. `serve()` runs it in-process for hermetic tests;
`SCRIPT`+`deploy` let the DB protocol upload and run it on real nodes
via the control layer.
"""

from __future__ import annotations

import json
import random as _random
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class CPState:
    """Linearizable in-memory CP primitives behind one lock."""

    def __init__(self):
        self.lock = threading.Lock()
        self.locks: dict[str, str | None] = {}     # name -> owner
        self.semaphores: dict[str, dict] = {}      # name -> {n, holders}
        self.refs: dict[str, object] = {}          # name -> value
        self.counter = 0
        self.queues: dict[str, list] = {}

    def handle(self, path: str, req: dict) -> dict:
        with self.lock:
            return getattr(self, "op_" + path.strip("/").replace("/", "_")
                           )(req)

    # locks ----------------------------------------------------------------

    def op_lock_acquire(self, req):
        name, owner = req["name"], req["owner"]
        if self.locks.get(name) is None:
            self.locks[name] = owner
            return {"ok": True}
        return {"ok": False}

    def op_lock_release(self, req):
        name, owner = req["name"], req["owner"]
        if self.locks.get(name) != owner:
            return {"ok": False, "error": "not-lock-owner"}
        self.locks[name] = None
        return {"ok": True}

    # semaphores -----------------------------------------------------------

    def op_semaphore_acquire(self, req):
        s = self.semaphores.setdefault(
            req["name"], {"n": int(req.get("permits", 2)), "holders": []})
        if len(s["holders"]) < s["n"]:
            s["holders"].append(req["owner"])
            return {"ok": True}
        return {"ok": False}

    def op_semaphore_release(self, req):
        s = self.semaphores.get(req["name"])
        if s and req["owner"] in s["holders"]:
            s["holders"].remove(req["owner"])
            return {"ok": True}
        return {"ok": False, "error": "not-a-holder"}

    # atomic refs ----------------------------------------------------------

    def op_ref_read(self, req):
        return {"ok": True, "value": self.refs.get(req["name"])}

    def op_ref_write(self, req):
        self.refs[req["name"]] = req["value"]
        return {"ok": True}

    def op_ref_cas(self, req):
        if self.refs.get(req["name"]) == req["old"]:
            self.refs[req["name"]] = req["new"]
            return {"ok": True}
        return {"ok": False}

    # ids / queues ---------------------------------------------------------

    def op_id(self, req):
        self.counter += 1
        return {"ok": True, "value": self.counter}

    def op_queue_offer(self, req):
        self.queues.setdefault(req["name"], []).append(req["value"])
        return {"ok": True}

    def op_queue_poll(self, req):
        q = self.queues.get(req["name"]) or []
        return {"ok": True, "value": q.pop(0) if q else None}

    def op_queue_poll_value(self, req):
        """Remove one arbitrary (non-FIFO) element — the unordered
        dequeue for the queue-linear workload."""
        q = self.queues.get(req["name"]) or []
        if not q:
            return {"ok": True, "value": None}
        v = _random.choice(q)
        q.remove(v)
        return {"ok": True, "value": v}

    # maps (the reference's map / crdt-map workloads,
    # `hazelcast.clj:440-507`: a set stored under one map key) --------------

    def op_map_add(self, req):
        m = self.queues.setdefault("map:" + req["name"], [])
        if req["value"] not in m:
            m.append(req["value"])
        return {"ok": True}

    def op_map_read(self, req):
        return {"ok": True,
                "value": sorted(self.queues.get("map:" + req["name"])
                                or [])}


def serve(host: str = "127.0.0.1", port: int = 0):
    """Run the shim in a daemon thread; returns (server, port)."""
    state = CPState()

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_POST(self):  # noqa: N802
            n = int(self.headers.get("Content-Length", 0))
            req = json.loads(self.rfile.read(n) or b"{}")
            try:
                out = state.handle(self.path, req)
            except AttributeError:
                self.send_response(404)
                self.end_headers()
                return
            body = json.dumps(out).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    server = ThreadingHTTPServer((host, port), Handler)
    server.state = state
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server, server.server_address[1]


DIR = "/opt/cp-shim"
SCRIPT_PATH = f"{DIR}/cp_shim.py"
PORT = 7171


def deploy(port: int = PORT) -> None:
    """Upload this module to the current node and run it under the
    daemon helpers — the suite DB's setup path."""
    import os

    from .. import control
    from ..control import util as cu

    with control.su():
        control.exec_("mkdir", "-p", DIR)
        with open(os.path.abspath(__file__)) as f:
            src = f.read()
        src += (f"\n\nif __name__ == '__main__':\n"
                f"    s, p = serve('0.0.0.0', {port})\n"
                f"    import time\n"
                f"    while True:\n"
                f"        time.sleep(3600)\n")
        control.upload_str(src, SCRIPT_PATH)
        cu.start_daemon({"logfile": f"{DIR}/shim.log",
                         "pidfile": f"{DIR}/shim.pid", "chdir": DIR},
                        "/usr/bin/python3", SCRIPT_PATH)
        cu.await_tcp_port(port)
