"""LogCabin test suite — a CAS register over the Raft reference
implementation.

Mirrors `/root/reference/logcabin/src/jepsen/logcabin.clj`: build from
source (git clone + scons), per-node serverId/listenAddresses config,
bootstrap on the first node, cluster formation via the Reconfigure
tool, and a CAS register client that drives the TreeOps example binary
*through the control layer* (`logcabin.clj:163-208` — ops are remote
shell invocations, not a wire protocol). CAS conflicts and timeouts
are recognized from TreeOps' error text (`logcabin.clj:152-160`)."""

from __future__ import annotations

import json
import logging
import re

from .. import cli, client as jclient, control, core, models
from .. import db as jdb
from ..checker import linear
from ..control import util as cu
from ..control.core import RemoteError
from ..os_ import debian
from . import std_opts, std_test

log = logging.getLogger(__name__)

CONFIG_FILE = "/root/logcabin.conf"
LOG_FILE = "/root/logcabin.log"
PID_FILE = "/root/logcabin.pid"
STORE_DIR = "/root/storage"
BIN = "/root/LogCabin"
RECONFIGURE_BIN = "/root/Reconfigure"
TREEOPS_BIN = "/root/TreeOps"
PORT = 5254
OP_TIMEOUT_S = 3

CAS_FAIL_RE = re.compile(
    r"Exiting due to LogCabin::Client::Exception: Path '.*' has value "
    r"'.*', not '.*' as required")
TIMEOUT_RE = re.compile(
    r"Exiting due to LogCabin::Client::Exception: Client-specified "
    r"timeout elapsed")


def server_addrs(test: dict) -> str:
    return ",".join(f"{n}:{PORT}" for n in test["nodes"])


class DB(jdb.DB, jdb.LogFiles):
    """Build-from-source install + bootstrap/reconfigure cluster
    formation (`logcabin.clj:23-150`)."""

    def setup(self, test, node):
        debian.install(["git-core", "protobuf-compiler",
                        "libprotobuf-dev", "libcrypto++-dev", "g++",
                        "scons"])
        with control.su():
            try:
                control.exec_("test", "-d", "/logcabin")
            except RemoteError:
                control.exec_(
                    "git", "clone", "--depth", "1",
                    "https://github.com/logcabin/logcabin.git",
                    "/logcabin")
                with control.cd("/logcabin"):
                    control.exec_("git", "submodule", "update",
                                  "--init")
            with control.cd("/logcabin"):
                control.exec_("scons")
            for src, dst in (("build/LogCabin", BIN),
                             ("build/Examples/Reconfigure",
                              RECONFIGURE_BIN),
                             ("build/Examples/TreeOps", TREEOPS_BIN)):
                control.exec_("cp", "-f", f"/logcabin/{src}", dst)
            server_id = str(test["nodes"].index(node) + 1)
            cu.write_file(f"serverId = {server_id}\n"
                          f"listenAddresses = {node}:{PORT}\n",
                          CONFIG_FILE)
            control.exec_("rm", "-rf", LOG_FILE)
            if node == test["nodes"][0]:
                control.exec_(BIN, "-c", CONFIG_FILE, "-l", LOG_FILE,
                              "--bootstrap")
        # barriers between bootstrap / start / reconfigure: Reconfigure
        # needs every peer built and listening (`logcabin.clj:133-141`)
        core.synchronize(test)
        with control.su():
            control.exec_(BIN, "-c", CONFIG_FILE, "-d", "-l", LOG_FILE,
                          "-p", PID_FILE)
        core.synchronize(test)
        if node == test["nodes"][0]:
            with control.su():
                control.exec_(RECONFIGURE_BIN, "-c",
                              server_addrs(test), "set",
                              *[f"{n}:{PORT}" for n in test["nodes"]])
        core.synchronize(test)

    def teardown(self, test, node):
        with control.su():
            cu.grepkill("LogCabin")
            try:
                control.exec_("rm", "-rf", PID_FILE, STORE_DIR)
            except RemoteError:
                pass

    def log_files(self, test, node):
        return [LOG_FILE]


def db() -> DB:
    return DB()


class CASClient(jclient.Client):
    """CAS register at /jepsen via the TreeOps binary, invoked over the
    node's control session (`logcabin.clj:210-262`). Values round-trip
    as JSON text."""

    PATH = "/jepsen"

    def __init__(self):
        self.node = None

    def open(self, test, node):
        c = CASClient()
        c.node = node
        return c

    def _on_node(self, test, fn):
        sess = (test.get("sessions") or {}).get(self.node)
        if sess is None:
            raise RemoteError(f"no session for {self.node!r}")
        with control.with_session(self.node, sess):
            with control.su():
                return fn()

    def setup(self, test):
        try:
            self._on_node(test, lambda: self._write(test, None))
        except RemoteError:
            pass  # another node's client seeds the register

    def _read(self, test):
        return control.exec_(TREEOPS_BIN, "-c", server_addrs(test),
                             "-q", "-t", str(OP_TIMEOUT_S), "read",
                             self.PATH)

    def _run_with_stdin(self, cmd: str, stdin: str) -> str:
        res = control.ssh_star({"cmd": cmd, "in": stdin})
        control.throw_on_nonzero_exit(res)
        return res.get("out", "")

    def _write(self, test, value):
        return self._run_with_stdin(
            f"{TREEOPS_BIN} -c {server_addrs(test)} -q "
            f"-t {OP_TIMEOUT_S} write {self.PATH}",
            json.dumps(value))

    def _cas(self, test, old, new):
        return self._run_with_stdin(
            f"{TREEOPS_BIN} -c {server_addrs(test)} -q "
            f"-p {self.PATH}:{json.dumps(old)} "
            f"-t {OP_TIMEOUT_S} write {self.PATH}",
            json.dumps(new))

    def invoke(self, test, op):
        f = op["f"]
        try:
            if f == "read":
                out = self._on_node(test, lambda: self._read(test))
                v = json.loads(out) if out.strip() else None
                return {**op, "type": "ok", "value": v}
            if f == "write":
                self._on_node(test,
                              lambda: self._write(test, op["value"]))
                return {**op, "type": "ok"}
            if f == "cas":
                old, new = op["value"]
                self._on_node(test,
                              lambda: self._cas(test, old, new))
                return {**op, "type": "ok"}
            raise ValueError(f"unknown f {f!r}")
        except RemoteError as e:
            msg = str(e).strip()
            if f == "cas" and CAS_FAIL_RE.search(msg):
                return {**op, "type": "fail", "error": "cas-mismatch"}
            if TIMEOUT_RE.search(msg):
                t = "fail" if f == "read" else "info"
                return {**op, "type": t, "error": "timed-out"}
            t = "fail" if f == "read" else "info"
            return {**op, "type": t, "error": msg[:200]}


def register_workload(opts: dict) -> dict:
    from .. import generator as gen

    def r(test, ctx):
        return {"type": "invoke", "f": "read", "value": None}

    def w(test, ctx):
        return {"type": "invoke", "f": "write",
                "value": gen.rng.randrange(5)}

    def cas(test, ctx):
        return {"type": "invoke", "f": "cas",
                "value": (gen.rng.randrange(5), gen.rng.randrange(5))}

    return {
        "client": CASClient(),
        "generator": gen.mix([r, w, cas]),
        "checker": linear.linearizable(models.cas_register()),
    }


WORKLOADS = {"register": register_workload}


def logcabin_test(opts: dict) -> dict:
    workload_name = opts.get("workload", "register")
    return std_test(
        opts, name=f"logcabin-{workload_name}", db=db(),
        workload=WORKLOADS[workload_name](opts))


OPT_SPEC = std_opts(cli, WORKLOADS, "register")


def main(argv=None):
    cli.run({**cli.single_test_cmd({"test_fn": logcabin_test,
                                    "opt_spec": OPT_SPEC}),
             **cli.serve_cmd()}, argv)


if __name__ == "__main__":
    main()
