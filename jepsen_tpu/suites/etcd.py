"""etcd test suite — the canonical tutorial exemplar.

Mirrors the capabilities of the reference's etcd tutorial
(`doc/tutorial/01-scaffolding.md` … `08-set.md`): cluster install from a
release tarball, daemon lifecycle, a CAS-register client with careful
error/timeout classification, independent-key register and set
workloads, partition nemesis, and a CLI entry point. The client speaks
etcd v3's JSON gateway (`/v3/kv/{range,put,txn}`) over plain urllib —
no driver dependency; CAS is a server-side txn compare on value.
"""

from __future__ import annotations

import base64
import binascii
import itertools
import logging
import urllib.error
import urllib.request

from .. import checker, cli, client as jclient, control
from .. import db as jdb
from .. import generator as gen
from .. import independent, nemesis as jnemesis, testkit
from ..checker import timeline
from ..control import util as cu
from ..nemesis import partition
from . import http_post
from ..os_ import debian
from ..workloads import linearizable_register

log = logging.getLogger(__name__)

DIR = "/opt/etcd"
BINARY = f"{DIR}/etcd"
LOGFILE = f"{DIR}/etcd.log"
PIDFILE = f"{DIR}/etcd.pid"
DATA_DIR = f"{DIR}/data"

CLIENT_PORT = 2379
PEER_PORT = 2380

DEFAULT_VERSION = "3.5.9"


def node_url(node: str, port: int) -> str:
    return f"http://{node}:{port}"


def peer_url(node: str) -> str:
    return node_url(node, PEER_PORT)


def client_url(node: str) -> str:
    return node_url(node, CLIENT_PORT)


def initial_cluster(test: dict) -> str:
    """n1=http://n1:2380,n2=... (tutorial 02-db.md)."""
    return ",".join(f"{n}={peer_url(n)}" for n in test["nodes"])


def tarball_url(version: str) -> str:
    return (f"https://github.com/etcd-io/etcd/releases/download/"
            f"v{version}/etcd-v{version}-linux-amd64.tar.gz")


class DB(jdb.DB, jdb.Process, jdb.Pause, jdb.Primary, jdb.LogFiles):
    """etcd cluster automation for a particular version."""

    def __init__(self, version: str = DEFAULT_VERSION):
        self.version = version

    def node_args(self, test, node):
        return [
            "--name", node,
            "--listen-peer-urls", node_url("0.0.0.0", PEER_PORT),
            "--listen-client-urls", node_url("0.0.0.0", CLIENT_PORT),
            "--advertise-client-urls", client_url(node),
            "--initial-cluster-state", "new",
            "--initial-advertise-peer-urls", peer_url(node),
            "--initial-cluster", initial_cluster(test),
            "--data-dir", DATA_DIR,
            "--snapshot-count", "100",
        ]

    def setup(self, test, node):
        with control.su():
            log.info("%s installing etcd %s", node, self.version)
            url = test.get("tarball") or tarball_url(self.version)
            cu.install_archive(url, DIR)
            self.start(test, node)
            cu.await_tcp_port(CLIENT_PORT)

    def teardown(self, test, node):
        log.info("%s tearing down etcd", node)
        with control.su():
            self.kill(test, node)
            control.exec_("rm", "-rf", DATA_DIR, LOGFILE, PIDFILE)

    def start(self, test, node):
        with control.su():
            cu.start_daemon(
                {"logfile": LOGFILE, "pidfile": PIDFILE, "chdir": DIR},
                BINARY, *self.node_args(test, node))

    def kill(self, test, node):
        with control.su():
            cu.stop_daemon(PIDFILE, cmd="etcd")
            cu.grepkill("etcd")

    def pause(self, test, node):
        with control.su():
            cu.signal("etcd", "STOP")

    def resume(self, test, node):
        with control.su():
            cu.signal("etcd", "CONT")

    def primaries(self, test):
        """Nodes whose member id equals the cluster's leader id, per
        /v3/maintenance/status — asked from the control node."""
        out = []
        for node in test["nodes"]:
            try:
                s = http_post(client_url(node) + "/v3/maintenance/status",
                              {}, timeout=2)
                if s.get("leader") and \
                        s.get("header", {}).get("member_id") == s["leader"]:
                    out.append(node)
            except OSError:
                pass
        return out

    def setup_primary(self, test, node):
        pass

    def log_files(self, test, node):
        return [LOGFILE]


def db(version: str = DEFAULT_VERSION) -> DB:
    return DB(version)


# -- v3 JSON gateway client -------------------------------------------------

def b64(s) -> str:
    return base64.b64encode(str(s).encode()).decode()


def unb64(s: str) -> str:
    return base64.b64decode(s).decode()


class EtcdClient(jclient.Client):
    """CAS-register client over the v3 JSON gateway.

    Error classification follows the tutorial (06-refining.md): reads
    that fail are safe to call 'fail' (they didn't change anything);
    indeterminate write/cas errors become 'info'. Timeouts on reads →
    fail, on writes/cas → info.
    """

    def __init__(self, timeout_s: float = 5.0, url: str | None = None):
        self.timeout_s = timeout_s
        self.url = url

    def open(self, test, node):
        c = EtcdClient(self.timeout_s,
                       test.get("client-url-fn", client_url)(node))
        return c

    # single-key kv ops ----------------------------------------------------

    def read(self, k):
        r = http_post(self.url + "/v3/kv/range", {"key": b64(k)},
                      self.timeout_s)
        kvs = r.get("kvs") or []
        return unb64(kvs[0]["value"]) if kvs else None

    def write(self, k, v):
        http_post(self.url + "/v3/kv/put",
                  {"key": b64(k), "value": b64(v)}, self.timeout_s)

    def cas(self, k, old, new) -> bool:
        r = http_post(self.url + "/v3/kv/txn", {
            "compare": [{"key": b64(k), "target": "VALUE",
                         "result": "EQUAL", "value": b64(old)}],
            "success": [{"requestPut": {"key": b64(k),
                                        "value": b64(new)}}],
        }, self.timeout_s)
        return bool(r.get("succeeded"))

    def invoke(self, test, op):
        v = op.get("value")
        if independent.is_tuple(v):
            # independent-keyed ops arrive as (k, v) tuples
            k, inner = v

            def wrap(x):
                return independent.ktuple(k, x)
        else:
            k, inner = "r", v

            def wrap(x):
                return x
        if op["f"] not in ("read", "write", "cas"):
            raise ValueError(f"unknown f {op['f']!r}")
        definite_fail = (op["f"] == "read")
        try:
            if op["f"] == "read":
                val = self.read(k)
                val = int(val) if val is not None else None
                return {**op, "type": "ok", "value": wrap(val)}
            if op["f"] == "write":
                self.write(k, inner)
                return {**op, "type": "ok"}
            else:
                old, new = inner
                ok = self.cas(k, old, new)
                return {**op, "type": "ok" if ok else "fail"}
        except urllib.error.HTTPError as e:
            return {**op, "type": "fail" if definite_fail else "info",
                    "error": ["http", e.code]}
        except (urllib.error.URLError, OSError,
                binascii.Error, ValueError) as e:
            err = str(e)
            if "refused" in err:
                # connection refused: the request never started
                return {**op, "type": "fail", "error": "connection-refused"}
            return {**op, "type": "fail" if definite_fail else "info",
                    "error": ["indeterminate", err]}


class EtcdSetClient(EtcdClient):
    """Set workload client (tutorial 08-set.md): 'add' puts a unique
    key under a prefix; 'read' ranges over the prefix."""

    PREFIX = "set/"

    def open(self, test, node):
        return EtcdSetClient(self.timeout_s,
                             test.get("client-url-fn", client_url)(node))

    def invoke(self, test, op):
        try:
            if op["f"] == "add":
                self.write(self.PREFIX + str(op["value"]), op["value"])
                return {**op, "type": "ok"}
            if op["f"] == "read":
                r = http_post(self.url + "/v3/kv/range", {
                    "key": b64(self.PREFIX),
                    "range_end": b64(self.PREFIX + "\xff"),
                }, self.timeout_s)
                vals = sorted(int(unb64(kv["value"]))
                              for kv in r.get("kvs") or [])
                return {**op, "type": "ok", "value": vals}
            raise ValueError(f"unknown f {op['f']!r}")
        except urllib.error.HTTPError as e:
            return {**op, "type": "fail" if op["f"] == "read" else "info",
                    "error": ["http", e.code]}
        except (urllib.error.URLError, OSError) as e:
            return {**op,
                    "type": "fail" if op["f"] == "read" else "info",
                    "error": ["indeterminate", str(e)]}


# -- workloads --------------------------------------------------------------

def register_workload(opts: dict) -> dict:
    """Independent linearizable CAS registers, checked on device
    (tutorial 07-parameters.md shape)."""
    w = linearizable_register.test({
        "nodes": opts["nodes"],
        "per-key-limit": opts.get("ops-per-key", 100),
    })
    w["client"] = EtcdClient()
    return w


def set_workload(opts: dict) -> dict:
    """Grow-only set via unique keys (tutorial 08-set.md)."""
    adds = ({"type": "invoke", "f": "add", "value": i}
            for i in itertools.count())
    return {
        "client": EtcdSetClient(),
        "checker": checker.set_checker(),
        "generator": adds,
        "final-generator": gen.each_thread(gen.once(
            {"type": "invoke", "f": "read", "value": None})),
    }


WORKLOADS = {
    "register": register_workload,
    "set": set_workload,
}


def etcd_test(opts: dict) -> dict:
    """Construct a test map from CLI options (tutorial 01-scaffolding
    through 07-parameters)."""
    workload_name = opts.get("workload", "register")
    workload = WORKLOADS[workload_name](opts)
    nemesis = partition.partition_random_halves() \
        if opts.get("nemesis", "partition") == "partition" \
        else jnemesis.noop
    rate = float(opts.get("rate", 10))
    time_limit = opts.get("time-limit", opts.get("time_limit", 60))

    main_gen = gen.nemesis(
        gen.cycle(gen.phases(
            gen.sleep(5),
            gen.once({"type": "info", "f": "start", "value": None}),
            gen.sleep(5),
            gen.once({"type": "info", "f": "stop", "value": None}))),
        gen.stagger(1 / rate, workload["generator"]))
    main_gen = gen.time_limit(time_limit, main_gen)
    final = workload.get("final-generator")
    generator = gen.phases(
        main_gen,
        gen.nemesis(gen.once({"type": "info", "f": "stop", "value": None})),
        gen.clients(final)) if final else main_gen

    return {
        **testkit.noop_test(),
        **{k: v for k, v in opts.items() if isinstance(k, str)},
        "name": f"etcd-{workload_name}",
        "os": debian.os,
        "db": db(opts.get("version", DEFAULT_VERSION)),
        "client": workload["client"],
        "nemesis": nemesis,
        "generator": generator,
        "checker": checker.compose({
            "perf": checker.perf_checker(),
            "timeline": timeline.html(),
            "workload": workload["checker"],
            "stats": checker.stats(),
            "exceptions": checker.unhandled_exceptions(),
        }),
    }


OPT_SPEC = [
    cli.opt("--workload", "-w", default="register",
            choices=sorted(WORKLOADS),
            help="Which workload to run"),
    cli.opt("--version", default=DEFAULT_VERSION,
            help="etcd version to install"),
    cli.opt("--rate", type=float, default=10,
            help="approximate op rate per second"),
    cli.opt("--ops-per-key", type=int, default=100,
            help="ops per independent key (register workload)"),
    cli.opt("--nemesis", default="partition",
            choices=["partition", "none"], help="fault to inject"),
]


def main(argv=None):
    """CLI entry: run an etcd test or serve the store
    (zookeeper.clj:131-137 shape)."""
    cli.run({**cli.single_test_cmd({"test_fn": etcd_test,
                                    "opt_spec": OPT_SPEC}),
             **cli.serve_cmd()}, argv)


if __name__ == "__main__":
    main()
