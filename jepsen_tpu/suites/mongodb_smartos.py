"""MongoDB-on-SmartOS suite: the reference's only non-Linux-hosted
database test.

Mirrors `/root/reference/mongodb-smartos/src/jepsen/mongodb_smartos/`:

  * DB automation over the SmartOS OS layer: versioned pkgin installs
    of mongodb + mongo-tools, config to /opt/local/etc/mongod.conf,
    SMF service management (`svcadm clear/enable/disable mongodb`),
    replica-set initiation from the first node (`core.clj:40-290`).
  * document-cas: CAS against a single document with configurable
    write concern (`document_cas.clj`), checked linearizably on the
    device register kernel.
  * transfer: the classic two-phase "transactions by hand" bank —
    txn documents move initial -> pending -> applied -> done while
    account updates guard on pendingTxns membership
    (`transfer.clj:43-140`); checked by the bank checker.

Clients speak the wire protocol from `bson_proto.py`; hermetic tests
run against `tests/fake_mongo.py`."""

from __future__ import annotations

import itertools
import logging
import threading

from .. import checker, cli, client as jclient, control
from .. import db as jdb
from .. import generator as gen
from ..control import util as cu
from ..control.core import RemoteError
from ..os_ import smartos
from ..workloads import bank as bank_w, linearizable_register
from . import std_opts, std_test
from .bson_proto import Conn, MongoError, WriteConcernError
from .mongodb import DEFINITE_FAIL, _connect

log = logging.getLogger(__name__)

PORT = 27017
CONF = "/opt/local/etc/mongod.conf"
DATA_DIR = "/var/lib/mongodb"
LOG_DIR = "/var/log/mongodb"
REPL_SET = "jepsen"

DEFAULT_VERSION = "3.4.4"
DEFAULT_TOOLS_VERSION = "3.4.4"

MONGOD_CONF = """\
systemLog:
  destination: file
  path: {log_dir}/mongod.log
  logAppend: true
storage:
  dbPath: {data_dir}
replication:
  replSetName: {repl_set}
net:
  bindIp: 0.0.0.0
  port: {port}
"""


def _meh(*cmd):
    try:
        control.exec_(*cmd)
    except RemoteError:
        pass


class DB(jdb.DB, jdb.Process, jdb.LogFiles):
    """mongod via pkgin + SMF (`core.clj:40-86`)."""

    def __init__(self, version: str = DEFAULT_VERSION,
                 tools_version: str = DEFAULT_TOOLS_VERSION):
        self.version = version
        self.tools_version = tools_version

    def setup(self, test, node):
        with control.su():
            log.info("%s installing mongodb %s via pkgin", node,
                     self.version)
            smartos.install({"mongodb": self.version,
                             "mongo-tools": self.tools_version})
            control.exec_("mkdir", "-p", DATA_DIR, LOG_DIR)
            control.exec_("chown", "-R", "mongodb:mongodb", DATA_DIR)
            cu.write_file(MONGOD_CONF.format(
                log_dir=LOG_DIR, data_dir=DATA_DIR,
                repl_set=REPL_SET, port=PORT), CONF)
            self.start(test, node)
            cu.await_tcp_port(PORT)
        if node == test["nodes"][0]:
            conn_fn = test.get("mongo-conn-fn")
            conn = conn_fn(node) if conn_fn else Conn(node, PORT)
            try:
                conn.command("admin", {"replSetInitiate": {
                    "_id": REPL_SET,
                    "members": [{"_id": i, "host": f"{n}:{PORT}"}
                                for i, n in enumerate(test["nodes"])],
                }})
            except MongoError as e:
                if "already initialized" not in str(e):
                    raise
            finally:
                conn.close()

    def start(self, test, node):
        with control.su():
            _meh("svcadm", "clear", "mongodb")
            control.exec_("svcadm", "enable", "-r", "mongodb")

    def kill(self, test, node):
        with control.su():
            _meh("svcadm", "disable", "mongodb")
            _meh("pkill", "-9", "mongod")

    def teardown(self, test, node):
        self.kill(test, node)
        with control.su():
            _meh("rm", "-rf", f"{LOG_DIR}/mongod.log")
            _meh("rm", "-rf", DATA_DIR)

    def log_files(self, test, node):
        return [f"{LOG_DIR}/mongod.log"]


def db(version: str = DEFAULT_VERSION,
       tools_version: str = DEFAULT_TOOLS_VERSION) -> DB:
    return DB(version, tools_version)


class TransferClient(jclient.Client):
    """Bank transfers via the by-hand two-phase protocol
    (`transfer.clj:43-180`): a txn document advances initial ->
    pending -> applied -> done; the two account updates are guarded by
    pendingTxns membership so a re-applied phase is a no-op."""

    DB_NAME = "jepsen"
    ACCTS = "accts"
    TXNS = "txns"
    _ids = itertools.count()
    _id_lock = threading.Lock()

    def __init__(self, write_concern: str = "majority"):
        self.write_concern = write_concern
        self.conn: Conn | None = None

    def open(self, test, node):
        c = TransferClient(self.write_concern)
        c.conn = _connect(test, node)
        return c

    def close(self, test):
        if self.conn is not None:
            self.conn.close()

    def setup(self, test):
        accounts = test.get("accounts", list(range(8)))
        total = test.get("total-amount", 100)
        try:
            for a in accounts:
                self.conn.command(self.DB_NAME, {
                    "update": self.ACCTS,
                    "updates": [{
                        "q": {"_id": a},
                        "u": {"$set": {
                            "balance": total if a == accounts[0] else 0,
                            "pendingTxns": []}},
                        "upsert": True}],
                })
        except (MongoError, OSError):
            # setup runs on every node's client: secondaries reject
            # the writes (NotWritablePrimary) — the primary's client
            # seeds the idempotent upserts
            pass

    def _update(self, coll, q, u):
        return self.conn.command(self.DB_NAME, {
            "update": coll, "updates": [{"q": q, "u": u}],
            "writeConcern": {"w": self.write_concern}})

    def _transfer(self, frm, to, amount):
        with TransferClient._id_lock:
            txn_id = next(TransferClient._ids)
        # p0: create in state initial; p2: begin (initial -> pending)
        self.conn.command(self.DB_NAME, {
            "insert": self.TXNS,
            "documents": [{"_id": txn_id, "state": "initial",
                           "from": frm, "to": to, "amount": amount}],
            "writeConcern": {"w": self.write_concern}})
        self._update(self.TXNS, {"_id": txn_id, "state": "initial"},
                     {"$set": {"state": "pending"}})
        # p3: apply to both accounts, guarded on pendingTxns
        self._update(self.ACCTS,
                     {"_id": frm, "pendingTxns": {"$ne": txn_id}},
                     {"$inc": {"balance": -amount},
                      "$push": {"pendingTxns": txn_id}})
        self._update(self.ACCTS,
                     {"_id": to, "pendingTxns": {"$ne": txn_id}},
                     {"$inc": {"balance": amount},
                      "$push": {"pendingTxns": txn_id}})
        # p4: applied; p5: clear pending; p6: done
        self._update(self.TXNS, {"_id": txn_id, "state": "pending"},
                     {"$set": {"state": "applied"}})
        self._update(self.ACCTS, {"_id": frm, "pendingTxns": txn_id},
                     {"$pull": {"pendingTxns": txn_id}})
        self._update(self.ACCTS, {"_id": to, "pendingTxns": txn_id},
                     {"$pull": {"pendingTxns": txn_id}})
        self._update(self.TXNS, {"_id": txn_id, "state": "applied"},
                     {"$set": {"state": "done"}})

    def invoke(self, test, op):
        try:
            if op["f"] == "read":
                r = self.conn.command(self.DB_NAME, {
                    "find": self.ACCTS, "filter": {}})
                docs = r.get("cursor", {}).get("firstBatch", [])
                return {**op, "type": "ok",
                        "value": {d["_id"]: d.get("balance", 0)
                                  for d in docs}}
            if op["f"] == "partial-read":
                # accounts with no transaction in flight: these
                # balances ARE consistent (`transfer.clj:159-165`)
                r = self.conn.command(self.DB_NAME, {
                    "find": self.ACCTS,
                    "filter": {"pendingTxns": {"$size": 0}}})
                docs = r.get("cursor", {}).get("firstBatch", [])
                return {**op, "type": "ok",
                        "value": {d["_id"]: d.get("balance", 0)
                                  for d in docs}}
            if op["f"] == "transfer":
                v = op["value"]
                self._transfer(v["from"], v["to"], v["amount"])
                return {**op, "type": "ok"}
            raise ValueError(f"unknown f {op['f']!r}")
        except WriteConcernError as e:
            return {**op, "type": "info",
                    "error": ["mongo-write-concern", e.code, str(e)]}
        except MongoError as e:
            definite = op["f"] == "read" or e.code in DEFINITE_FAIL
            return {**op, "type": "fail" if definite else "info",
                    "error": ["mongo", e.code, str(e)]}
        except OSError as e:
            return {**op,
                    "type": "fail" if op["f"] == "read" else "info",
                    "error": str(e)}


class PartialReadChecker(checker.Checker):
    """Settled accounts (pendingTxns empty) carry consistent balances:
    keys must be known accounts and each balance must stay within
    [-total, 2*total] — the bound any interleaving of conserved
    transfers can reach (`transfer.clj:199-206` checks these reads
    against the account model)."""

    def check(self, test, hist, opts):
        accounts = set(test.get("accounts", list(range(8))))
        total = test.get("total-amount", 100)
        errors = []
        for o in hist:
            if o.get("type") != "ok" or o.get("f") != "partial-read":
                continue
            for acct, balance in (o.get("value") or {}).items():
                if acct not in accounts:
                    errors.append({"type": "unexpected-account",
                                   "op": dict(o), "account": acct})
                elif not isinstance(balance, int) \
                        or not -total <= balance <= 2 * total:
                    errors.append({"type": "impossible-balance",
                                   "op": dict(o), "account": acct,
                                   "balance": balance})
        return {"valid?": not errors, "errors": errors[:16]}


def document_cas_workload(opts: dict) -> dict:
    """Single-document CAS per key (`document_cas.clj`), reusing the
    mongodb suite's wire client over the SmartOS deployment."""
    from .mongodb import DocumentCASClient
    w = linearizable_register.test(opts)
    return {"client": DocumentCASClient(), **w}


def transfer_workload(opts: dict) -> dict:
    # transfers may interleave non-atomically (the two-phase protocol
    # is applied without transactions), so negative balances are legal
    # mid-flight, as in the reference's transfer test; partial-reads
    # (pendingTxns empty) mix in as the consistent-read probe
    def partial_read(test, ctx):
        return {"type": "invoke", "f": "partial-read", "value": None}

    return {
        "client": TransferClient(opts.get("write-concern", "majority")),
        "generator": gen.mix([bank_w.generator(), partial_read]),
        "checker": checker.compose({
            "bank": bank_w.checker({"negative-balances?": True}),
            "partial-reads": PartialReadChecker(),
        }),
    }


WORKLOADS = {
    "document-cas": document_cas_workload,
    "transfer": transfer_workload,
}


def mongodb_smartos_test(opts: dict) -> dict:
    workload_name = opts.get("workload", "document-cas")
    return std_test(
        opts, name=f"mongodb-smartos-{workload_name}",
        db=db(opts.get("version", DEFAULT_VERSION),
              opts.get("tools-version", DEFAULT_TOOLS_VERSION)),
        os=smartos.os,
        workload=WORKLOADS[workload_name](opts))


OPT_SPEC = std_opts(cli, WORKLOADS, "document-cas", DEFAULT_VERSION,
                    "mongodb pkgin version") + [
    cli.opt("--tools-version", default=DEFAULT_TOOLS_VERSION,
            help="mongo-tools pkgin version"),
    cli.opt("--write-concern", default="majority",
            help="write concern for transfers"),
]


def main(argv=None):
    cli.run({**cli.single_test_cmd({"test_fn": mongodb_smartos_test,
                                    "opt_spec": OPT_SPEC}),
             **cli.serve_cmd()}, argv)


if __name__ == "__main__":
    main()
