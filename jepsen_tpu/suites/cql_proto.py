"""Minimal CQL binary-protocol (v4) client.

The reference's YCQL layer drives YugaByte's Cassandra-compatible API
through the DataStax Java driver + Cassaforte
(`/root/reference/yugabyte/src/yugabyte/ycql/client.clj:75-127`). We
speak the wire protocol directly instead — same design as the suite
catalog's other hand-rolled clients (`mysql_proto.py`, `pg_proto.py`):
no driver dependency, and hermetic tests can run against an in-process
protocol fake (`tests/fake_cql.py`).

Scope: STARTUP/READY handshake, QUERY with a consistency level and no
bound values (statements carry inline literals, as the reference's
string-munged transactions do, `ycql/bank.clj:47-58`), RESULT parsing
for void / rows / set-keyspace / schema-change, and ERROR frames. No
prepared statements, paging, events, or compression — the suites don't
need them.
"""

from __future__ import annotations

import socket
import time as _time

from .netutil import nodelay
import struct

# request/response opcodes (protocol spec §2.4)
OP_ERROR = 0x00
OP_STARTUP = 0x01
OP_READY = 0x02
OP_AUTHENTICATE = 0x03
OP_QUERY = 0x07
OP_RESULT = 0x08

RESULT_VOID = 0x0001
RESULT_ROWS = 0x0002
RESULT_SET_KEYSPACE = 0x0003
RESULT_SCHEMA_CHANGE = 0x0005

CONSISTENCY = {
    "ANY": 0x0000, "ONE": 0x0001, "TWO": 0x0002, "THREE": 0x0003,
    "QUORUM": 0x0004, "ALL": 0x0005, "LOCAL_QUORUM": 0x0006,
    "EACH_QUORUM": 0x0007, "SERIAL": 0x0008, "LOCAL_SERIAL": 0x0009,
    "LOCAL_ONE": 0x000A,
}

# error codes we classify on (§9)
ERR_SERVER = 0x0000
ERR_UNAVAILABLE = 0x1000
ERR_OVERLOADED = 0x1001
ERR_WRITE_TIMEOUT = 0x1100
ERR_READ_TIMEOUT = 0x1200
ERR_SYNTAX = 0x2000
ERR_INVALID = 0x2200
ERR_ALREADY_EXISTS = 0x2400

# column type option ids (§4.2.5.2) we decode
TYPE_ASCII = 0x0001
TYPE_BIGINT = 0x0002
TYPE_BLOB = 0x0003
TYPE_BOOLEAN = 0x0004
TYPE_COUNTER = 0x0005
TYPE_DOUBLE = 0x0007
TYPE_INT = 0x0009
TYPE_TEXT = 0x000A
TYPE_VARCHAR = 0x000D


class CQLError(Exception):
    """An ERROR frame: code + server message."""

    def __init__(self, code: int, message: str):
        self.code = code
        self.message = message
        super().__init__(f"[{code:#06x}] {message}")

    @property
    def timeout(self) -> bool:
        return self.code in (ERR_WRITE_TIMEOUT, ERR_READ_TIMEOUT)

    @property
    def unavailable(self) -> bool:
        return self.code in (ERR_UNAVAILABLE, ERR_OVERLOADED)


def _string(s: str) -> bytes:
    b = s.encode()
    return struct.pack("!H", len(b)) + b


def _long_string(s: str) -> bytes:
    b = s.encode()
    return struct.pack("!i", len(b)) + b


def _string_map(m: dict) -> bytes:
    out = struct.pack("!H", len(m))
    for k, v in m.items():
        out += _string(k) + _string(v)
    return out


def decode_value(type_id: int, raw: bytes | None):
    """Decode one [bytes] cell by its column-spec type."""
    if raw is None:
        return None
    if type_id == TYPE_INT:
        return struct.unpack("!i", raw)[0]
    if type_id in (TYPE_BIGINT, TYPE_COUNTER):
        return struct.unpack("!q", raw)[0]
    if type_id == TYPE_BOOLEAN:
        return raw != b"\x00"
    if type_id == TYPE_DOUBLE:
        return struct.unpack("!d", raw)[0]
    if type_id in (TYPE_ASCII, TYPE_VARCHAR, TYPE_TEXT):
        return raw.decode()
    return raw  # blob / unknown: raw bytes


class Conn:
    """One CQL connection. `query` returns (rows, cols) for row
    results — rows are lists of decoded Python values — and (None,
    None) for void/DDL results."""

    def __init__(self, host: str, port: int = 9042,
                 keyspace: str | None = None, timeout_s: float = 10.0,
                 connect_timeout_s: float | None = None):
        self.host, self.port = host, port
        self.timeout_s = timeout_s
        self.sock = socket.create_connection(
            (host, port), timeout=connect_timeout_s or timeout_s)
        nodelay(self.sock)
        self.sock.settimeout(timeout_s)
        self._stream = 0
        self._startup()
        if keyspace:
            self.query(f"USE {keyspace}")

    # -- framing -------------------------------------------------------------

    def _send(self, opcode: int, body: bytes) -> None:
        self._stream = (self._stream + 1) % 32768
        hdr = struct.pack("!BBhBI", 0x04, 0x00, self._stream, opcode,
                          len(body))
        self.sock.sendall(hdr + body)

    def _recv_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("server closed connection")
            buf += chunk
        return buf

    def _recv_frame(self) -> tuple[int, bytes]:
        """Read frames until one matches the request's stream id.

        Stale frames (late responses to an earlier, abandoned request)
        and EVENT pushes (stream -1) are discarded rather than being
        misread as the current query's result — the correlation the
        reference gets for free from the DataStax driver. Flag bits
        that prepend sections to the body (tracing 0x02, custom
        payload 0x04, warning 0x08) are stripped so result offsets
        stay correct."""
        # time-bounded drain: a long stale backlog must not turn a
        # recoverable read into connection churn. The bound is on the
        # INTER-frame gap (reset after every frame), not the total
        # drain — a response that is still flowing in behind stale
        # frames must be delivered, however long the backlog. A large
        # absolute frame cap backstops a server looping stale frames.
        window = self.sock.gettimeout() or 5.0
        deadline = _time.monotonic() + window
        for _ in range(4096):
            if _time.monotonic() >= deadline:
                break
            hdr = self._recv_exact(9)
            deadline = _time.monotonic() + window
            _ver, flags, stream, opcode, length = struct.unpack(
                "!BBhBI", hdr)
            body = self._recv_exact(length)
            if stream != self._stream:
                continue  # EVENT (-1) or stale response: drop
            if flags & 0x01:
                raise ConnectionError("unexpected compressed frame")
            pos = 0
            if flags & 0x02:  # tracing id: [uuid]
                pos += 16
            if flags & 0x08:  # warnings: [string list] (before payload)
                (n,) = struct.unpack("!H", body[pos:pos + 2])
                pos += 2
                for _i in range(n):
                    (slen,) = struct.unpack("!H", body[pos:pos + 2])
                    pos += 2 + slen
            if flags & 0x04:  # custom payload: [bytes map]
                (n,) = struct.unpack("!H", body[pos:pos + 2])
                pos += 2
                for _i in range(n):
                    (klen,) = struct.unpack("!H", body[pos:pos + 2])
                    pos += 2 + klen
                    (vlen,) = struct.unpack("!i", body[pos:pos + 4])
                    pos += 4 + max(vlen, 0)
            return opcode, body[pos:]
        raise ConnectionError(
            "no frame for current stream id within the timeout window")

    # -- handshake -----------------------------------------------------------

    def _startup(self) -> None:
        self._send(OP_STARTUP, _string_map({"CQL_VERSION": "3.0.0"}))
        opcode, body = self._recv_frame()
        if opcode == OP_ERROR:
            raise self._error(body)
        if opcode != OP_READY:
            raise ConnectionError(f"expected READY, got opcode {opcode}")

    @staticmethod
    def _error(body: bytes) -> CQLError:
        code = struct.unpack("!i", body[:4])[0]
        (mlen,) = struct.unpack("!H", body[4:6])
        return CQLError(code, body[6:6 + mlen].decode())

    # -- queries -------------------------------------------------------------

    def query(self, cql: str, consistency: str = "QUORUM",
              timeout_s: float | None = None) -> tuple:
        """Run one statement; inline literals only (flags byte 0x00 —
        no bound values)."""
        if timeout_s is not None:
            self.sock.settimeout(timeout_s)
        try:
            body = (_long_string(cql)
                    + struct.pack("!H", CONSISTENCY[consistency])
                    + b"\x00")
            self._send(OP_QUERY, body)
            opcode, rbody = self._recv_frame()
        finally:
            if timeout_s is not None:
                self.sock.settimeout(self.timeout_s)
        if opcode == OP_ERROR:
            raise self._error(rbody)
        if opcode != OP_RESULT:
            raise ConnectionError(f"expected RESULT, got opcode {opcode}")
        return self._parse_result(rbody)

    def _parse_result(self, body: bytes) -> tuple:
        (kind,) = struct.unpack("!i", body[:4])
        if kind != RESULT_ROWS:
            return None, None
        pos = 4
        flags, col_count = struct.unpack("!ii", body[pos:pos + 8])
        pos += 8
        global_spec = bool(flags & 0x0001)

        def read_string():
            nonlocal pos
            (n,) = struct.unpack("!H", body[pos:pos + 2])
            pos += 2
            s = body[pos:pos + n].decode()
            pos += n
            return s

        if global_spec:
            read_string()  # keyspace
            read_string()  # table
        cols, types = [], []
        for _ in range(col_count):
            if not global_spec:
                read_string()
                read_string()
            cols.append(read_string())
            (tid,) = struct.unpack("!H", body[pos:pos + 2])
            pos += 2
            types.append(tid)
            # no nested type params for the scalar types we use
        (row_count,) = struct.unpack("!i", body[pos:pos + 4])
        pos += 4
        rows = []
        for _ in range(row_count):
            row = []
            for tid in types:
                (n,) = struct.unpack("!i", body[pos:pos + 4])
                pos += 4
                if n < 0:
                    row.append(None)
                else:
                    row.append(decode_value(tid, body[pos:pos + n]))
                    pos += n
            rows.append(row)
        return rows, cols

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


def rows_as_dicts(result: tuple) -> list[dict]:
    """(rows, cols) -> list of {col: value} maps."""
    rows, cols = result
    return [dict(zip(cols, r)) for r in (rows or [])]
