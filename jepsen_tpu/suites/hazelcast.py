"""Hazelcast-style CP-subsystem suite: a workload *menu* over locks,
semaphores, CAS references, unique ids, and queues.

Mirrors the reference's hazelcast suite (`hazelcast/src/jepsen/
hazelcast.clj:652-816`): a `--workload` flag selects one of several
CP-subsystem tests, each pairing a client against the right checker —
locks against a linearizable mutex model (checked on device), id-gen
against `unique_ids`, queues against `total_queue`. The data plane is
the suite's CP service shim (`cp_shim.py`), playing the role of the
reference's in-repo `hazelcast/server/` component.
"""

from __future__ import annotations

import itertools
import logging
import urllib.error
import urllib.request

from .. import checker, cli, client as jclient, control
from .. import db as jdb
from .. import generator as gen
from .. import models, testkit
from ..checker import timeline
from ..nemesis import partition
from ..os_ import debian
from . import cp_shim, http_post

log = logging.getLogger(__name__)


def shim_url(node: str) -> str:
    return f"http://{node}:{cp_shim.PORT}"


class DB(jdb.DB, jdb.LogFiles):
    """Deploys the CP service shim on each node — the hermetic tier.
    The shim is linearizable by construction; use ServerDB (--deploy
    server) to test actual Hazelcast members."""

    def setup(self, test, node):
        cp_shim.deploy(test.get("shim-port", cp_shim.PORT))

    def teardown(self, test, node):
        from ..control import util as cu
        with control.su():
            cu.stop_daemon(f"{cp_shim.DIR}/shim.pid", cmd="python3")
            control.exec_("rm", "-rf", cp_shim.DIR)

    def log_files(self, test, node):
        return [f"{cp_shim.DIR}/shim.log"]


SERVER_DIR = "/opt/hazelcast"
SERVER_JAR = f"{SERVER_DIR}/server.jar"
SERVER_PID = f"{SERVER_DIR}/server.pid"
SERVER_LOG = f"{SERVER_DIR}/server.log"
MEMBER_PORT = 5701


class ServerDB(jdb.DB, jdb.Process, jdb.LogFiles):
    """Real Hazelcast members: upload the server fat jar (the
    reference builds `hazelcast/server/target/hazelcast-server.jar`
    via lein and uploads it, `hazelcast.clj:57-96`), install a JDK,
    and run `java -jar server.jar --members ip,ip,...` as a daemon."""

    def __init__(self, server_jar: str | None = None):
        self.server_jar = server_jar

    def setup(self, test, node):
        from ..control import util as cu
        jar = test.get("server-jar") or self.server_jar
        assert jar, "ServerDB needs a server jar (--server-jar)"
        with control.su():
            debian.install_jdk11()
            control.exec_("mkdir", "-p", SERVER_DIR)
            control.upload(jar, SERVER_JAR)
            self.start(test, node)
            cu.await_tcp_port(MEMBER_PORT)

    def start(self, test, node):
        from ..control import util as cu
        with control.su():
            cu.start_daemon(
                {"chdir": SERVER_DIR, "logfile": SERVER_LOG,
                 "pidfile": SERVER_PID},
                "/usr/bin/java", "-jar", SERVER_JAR,
                "--members", ",".join(test["nodes"]))

    def kill(self, test, node):
        from ..control import util as cu
        with control.su():
            cu.stop_daemon(SERVER_PID, cmd="java")

    def teardown(self, test, node):
        self.kill(test, node)
        with control.su():
            control.exec_("rm", "-rf", SERVER_LOG, SERVER_PID)

    def log_files(self, test, node):
        return [SERVER_LOG]


class CPClient(jclient.Client):
    """Base client: POSTs ops to the node's shim; network errors become
    info (indeterminate) except on pure reads."""

    READS: tuple = ()

    def __init__(self, timeout_s: float = 5.0, url: str | None = None,
                 owner: str | None = None):
        self.timeout_s = timeout_s
        self.url = url
        self.owner = owner

    def open(self, test, node):
        url = test.get("shim-url-fn", shim_url)(node)
        c = type(self)(self.timeout_s, url, owner=f"{node}-{id(self)}")
        return c

    def post(self, path: str, body: dict) -> dict:
        return http_post(self.url + path, body, self.timeout_s)

    def invoke(self, test, op):
        try:
            return self.apply_op(test, op)
        except (urllib.error.URLError, OSError) as e:
            t = "fail" if op["f"] in self.READS else "info"
            return {**op, "type": t, "error": str(e)}

    def apply_op(self, test, op):
        raise NotImplementedError


class LockClient(CPClient):
    """acquire/release over one named lock; checked against the mutex
    model (`hazelcast.clj` lock workloads)."""

    def apply_op(self, test, op):
        owner = str(op["process"])
        if op["f"] == "acquire":
            r = self.post("/lock/acquire", {"name": "jepsen",
                                            "owner": owner})
            return {**op, "type": "ok" if r["ok"] else "fail"}
        if op["f"] == "release":
            r = self.post("/lock/release", {"name": "jepsen",
                                            "owner": owner})
            return {**op, "type": "ok" if r["ok"] else "fail"}
        raise ValueError(op["f"])


class SemaphoreClient(CPClient):
    def apply_op(self, test, op):
        owner = str(op["process"])
        path = "/semaphore/" + op["f"]
        r = self.post(path, {"name": "jepsen", "owner": owner,
                             "permits": test.get("semaphore-permits", 2)})
        return {**op, "type": "ok" if r["ok"] else "fail"}


class CasClient(CPClient):
    READS = ("read",)

    def apply_op(self, test, op):
        if op["f"] == "read":
            r = self.post("/ref/read", {"name": "jepsen"})
            return {**op, "type": "ok", "value": r["value"]}
        if op["f"] == "write":
            self.post("/ref/write", {"name": "jepsen",
                                     "value": op["value"]})
            return {**op, "type": "ok"}
        if op["f"] == "cas":
            old, new = op["value"]
            r = self.post("/ref/cas", {"name": "jepsen", "old": old,
                                       "new": new})
            return {**op, "type": "ok" if r["ok"] else "fail"}
        raise ValueError(op["f"])


class IdClient(CPClient):
    def apply_op(self, test, op):
        r = self.post("/id", {})
        return {**op, "type": "ok", "value": r["value"]}


class QueueClient(CPClient):
    POLL = "/queue/poll"

    def apply_op(self, test, op):
        if op["f"] == "enqueue":
            self.post("/queue/offer", {"name": "jepsen",
                                       "value": op["value"]})
            return {**op, "type": "ok"}
        if op["f"] == "dequeue":
            r = self.post(self.POLL, {"name": "jepsen"})
            if r["value"] is None:
                return {**op, "type": "fail", "error": "empty"}
            return {**op, "type": "ok", "value": r["value"]}
        if op["f"] == "drain":
            # poll until empty; total_queue expands the collected value
            # back into dequeue pairs (checker.clj:594-626)
            out = []
            while True:
                r = self.post("/queue/poll", {"name": "jepsen"})
                if r["value"] is None:
                    return {**op, "type": "ok", "value": out}
                out.append(r["value"])
        raise ValueError(op["f"])


# -- semaphore checker (suite-local, like the reference's) -------------------

class SemaphoreChecker(checker.Checker):
    """At most N permits *certainly* held at once.

    A permit is certainly held from the acquire's completion until the
    holder's next release *invocation*: the release takes effect
    somewhere between its invoke and its completion, so a concurrent
    acquire granted against the freed permit can journal its ok before
    the release's ok. Counting releases at completion (the naive
    replay) therefore flags that legal interleaving as over-capacity.
    Ending intervals at release-invoke is conservative — only genuine
    overlaps of > N certain-hold intervals are flagged."""

    def __init__(self, permits: int = 2):
        self.permits = permits

    def check(self, test, hist, opts):
        holds: dict = {}          # process -> certainly-held permits
        tentative: set = set()    # processes with an in-flight release
        over = []

        def flag(o):
            over.append({"op": dict(o),
                         "holders": {str(p): n for p, n
                                     in sorted(holds.items()) if n}})

        for o in hist:
            p = o.get("process")
            f = o.get("f")
            t = o.get("type")
            if f == "release":
                if t == "invoke":
                    if holds.get(p, 0) > 0:
                        holds[p] -= 1
                        tentative.add(p)
                elif t == "fail" and p in tentative:
                    # the release definitely didn't free: the permit
                    # was held throughout, so restore and re-check
                    tentative.discard(p)
                    holds[p] = holds.get(p, 0) + 1
                    if sum(holds.values()) > self.permits:
                        flag(o)
                elif t in ("ok", "info"):
                    tentative.discard(p)
            elif f == "acquire" and t == "ok":
                holds[p] = holds.get(p, 0) + 1
                if sum(holds.values()) > self.permits:
                    flag(o)
        return {"valid?": not over, "over-capacity": over[:16]}


# -- workload menu ----------------------------------------------------------

def _acquire_release(test, ctx):
    return {"type": "invoke",
            "f": "acquire" if gen.rng.random() < 0.5 else "release",
            "value": None}


def lock_workload(opts):
    return {"client": LockClient(),
            "generator": gen.repeat(_acquire_release),
            "checker": checker.linearizable(models.mutex()),
            "final-generator": None}


def semaphore_workload(opts):
    permits = opts.get("semaphore-permits", 2)
    return {"client": SemaphoreClient(),
            "generator": gen.repeat(_acquire_release),
            "checker": SemaphoreChecker(permits),
            "final-generator": None}


def cas_workload(opts):
    def r(test, ctx):
        return {"type": "invoke", "f": "read", "value": None}

    def w(test, ctx):
        return {"type": "invoke", "f": "write",
                "value": gen.rng.randrange(5)}

    def cas(test, ctx):
        return {"type": "invoke", "f": "cas",
                "value": [gen.rng.randrange(5), gen.rng.randrange(5)]}

    return {"client": CasClient(),
            "generator": gen.mix([r, w, cas]),
            "checker": checker.linearizable(models.cas_register()),
            "final-generator": None}


def ids_workload(opts):
    return {"client": IdClient(),
            "generator": gen.repeat({"f": "generate"}),
            "checker": checker.unique_ids(),
            "final-generator": None}


def queue_workload(opts):
    values = itertools.count()

    def enq(test, ctx):
        return {"type": "invoke", "f": "enqueue", "value": next(values)}

    def deq(test, ctx):
        return {"type": "invoke", "f": "dequeue", "value": None}

    return {"client": QueueClient(),
            "generator": gen.mix([enq, deq]),
            "checker": checker.total_queue(),
            "final-generator": gen.each_thread(gen.once(
                {"type": "invoke", "f": "drain", "value": None}))}


class UnorderedQueueClient(QueueClient):
    """Dequeues any element (not FIFO head) so the history is judged
    against the unordered-queue model."""
    POLL = "/queue/poll/value"


def queue_linear_workload(opts):
    """Queue over a small value domain, checked as full
    linearizability against the unordered-queue device model — the
    knossos-model usage the reference gets from hazelcast's queue
    tests (`hazelcast.clj` queue + knossos models)."""
    def enq(test, ctx):
        return {"type": "invoke", "f": "enqueue",
                "value": gen.rng.randrange(5)}

    def deq(test, ctx):
        return {"type": "invoke", "f": "dequeue", "value": None}

    return {"client": UnorderedQueueClient(),
            "generator": gen.mix([enq, deq]),
            "checker": checker.linearizable(models.unordered_queue()),
            "final-generator": None}


class MapClient(CPClient):
    """The reference's map / crdt-map workloads: a set stored under
    one map key; `add` merges an element, the final `read` fetches the
    set (`hazelcast.clj:440-507`). crdt toggles which map the server
    uses (PN-counter-backed CRDT vs plain)."""

    READS = ("read",)
    NAME = "jepsen-map"

    def __init__(self, timeout_s: float = 5.0, url: str | None = None,
                 owner: str | None = None, crdt: bool = True):
        super().__init__(timeout_s, url, owner)
        self.crdt = crdt

    def open(self, test, node):
        c = super().open(test, node)
        c.crdt = self.crdt
        return c

    def _name(self):
        return ("crdt:" if self.crdt else "") + self.NAME

    def apply_op(self, test, op):
        if op["f"] == "add":
            self.post("/map/add", {"name": self._name(),
                                   "value": op["value"]})
            return {**op, "type": "ok"}
        if op["f"] == "read":
            r = self.post("/map/read", {"name": self._name()})
            return {**op, "type": "ok", "value": r["value"]}
        raise ValueError(op["f"])


def map_workload(opts, crdt: bool):
    values = itertools.count()

    def add(test, ctx):
        return {"type": "invoke", "f": "add", "value": next(values)}

    return {"client": MapClient(crdt=crdt),
            "generator": add,
            "checker": checker.set_checker(),
            "final-generator": gen.each_thread(gen.once(
                {"type": "invoke", "f": "read", "value": None}))}


def gset_linear_workload(opts):
    """CRDT map over a bounded element domain, checked as full
    linearizability against the g-set device model (duplicate adds are
    idempotent and legal)."""
    def add(test, ctx):
        return {"type": "invoke", "f": "add",
                "value": gen.rng.randrange(16)}

    def read(test, ctx):
        return {"type": "invoke", "f": "read", "value": None}

    return {"client": MapClient(crdt=True),
            "generator": gen.mix([add, add, read]),
            "checker": checker.linearizable(models.gset()),
            "final-generator": None}


WORKLOADS = {
    "lock": lock_workload,
    "semaphore": semaphore_workload,
    "cas-register": cas_workload,
    "unique-ids": ids_workload,
    "queue": queue_workload,
    "queue-linear": queue_linear_workload,
    "map": lambda opts: map_workload(opts, crdt=False),
    "crdt-map": lambda opts: map_workload(opts, crdt=True),
    "crdt-map-linear": gset_linear_workload,
}


def hazelcast_test(opts: dict) -> dict:
    """Menu-driven test construction (`hazelcast.clj:769-816`)."""
    name = opts.get("workload", "cas-register")
    workload = WORKLOADS[name](opts)
    time_limit = opts.get("time-limit", opts.get("time_limit", 60))
    rate = float(opts.get("rate", 10))

    main = gen.time_limit(time_limit, gen.nemesis(
        gen.cycle(gen.phases(
            gen.sleep(5),
            gen.once({"type": "info", "f": "start", "value": None}),
            gen.sleep(5),
            gen.once({"type": "info", "f": "stop", "value": None}))),
        gen.stagger(1 / rate, workload["generator"])))
    final = workload.get("final-generator")
    generator = gen.phases(
        main,
        gen.nemesis(gen.once({"type": "info", "f": "stop",
                              "value": None})),
        gen.clients(final)) if final else main

    return {
        **testkit.noop_test(),
        **{k: v for k, v in opts.items() if isinstance(k, str)},
        "name": f"hazelcast-{name}",
        "os": debian.os,
        "db": (ServerDB(opts.get("server-jar"))
               if opts.get("deploy") == "server" else DB()),
        "client": workload["client"],
        "nemesis": partition.partition_majorities_ring()
        if opts.get("nemesis", "partition") == "partition"
        else __import__("jepsen_tpu").nemesis.noop,
        "generator": generator,
        "checker": checker.compose({
            "workload": workload["checker"],
            "timeline": timeline.html(),
            "perf": checker.perf_checker(),
            "stats": checker.stats(),
        }),
    }


OPT_SPEC = [
    cli.opt("--workload", "-w", default="cas-register",
            choices=sorted(WORKLOADS), help="Which workload to run"),
    cli.opt("--rate", type=float, default=10,
            help="approximate op rate per second"),
    cli.opt("--semaphore-permits", type=int, default=2,
            help="semaphore capacity"),
    cli.opt("--nemesis", default="partition",
            choices=["partition", "none"], help="fault to inject"),
    cli.opt("--deploy", default="shim", choices=["shim", "server"],
            help="shim = hermetic CP service; server = real Hazelcast "
                 "members from --server-jar"),
    cli.opt("--server-jar", default=None,
            help="path to the Hazelcast server fat jar to upload"),
]


def main(argv=None):
    cli.run({**cli.single_test_cmd({"test_fn": hazelcast_test,
                                    "opt_spec": OPT_SPEC}),
             **cli.serve_cmd()}, argv)


if __name__ == "__main__":
    main()
