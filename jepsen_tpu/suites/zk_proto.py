"""A minimal pure-Python ZooKeeper wire-protocol client.

The reference's zookeeper suite drives ZK through avout/the Java client
(`zookeeper/src/jepsen/zookeeper.clj:78-104`); this environment has no
ZK driver, so we speak the stable v3 client protocol directly: 4-byte
length-framed packets of jute-encoded records. Only the five ops a CAS
register needs are implemented — connect, create, getData, setData
(with version: the CAS primitive), exists, close.

Jute wire primitives: int32/int64 big-endian, boolean as one byte,
buffer as int32 length + bytes (-1 = null), string as UTF-8 buffer.
"""

from __future__ import annotations

import socket

from .netutil import nodelay
import struct
from dataclasses import dataclass
from typing import Optional

# op codes
CREATE = 1
DELETE = 2
EXISTS = 3
GET_DATA = 4
SET_DATA = 5
PING = 11
CLOSE = -11

# error codes
OK = 0
NONODE = -101
BADVERSION = -103
NODEEXISTS = -110

# ACL: world:anyone, all perms
OPEN_ACL_UNSAFE = [(0x1F, "world", "anyone")]


class ZkError(Exception):
    def __init__(self, code: int, op: str):
        self.code = code
        super().__init__(f"zookeeper error {code} in {op}")


# -- jute encoding ----------------------------------------------------------

def enc_int(v: int) -> bytes:
    return struct.pack(">i", v)


def enc_long(v: int) -> bytes:
    return struct.pack(">q", v)


def enc_bool(v: bool) -> bytes:
    return b"\x01" if v else b"\x00"


def enc_buffer(b: Optional[bytes]) -> bytes:
    if b is None:
        return enc_int(-1)
    return enc_int(len(b)) + b


def enc_string(s: str) -> bytes:
    return enc_buffer(s.encode())


class Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def _take(self, n: int) -> bytes:
        b = self.data[self.pos:self.pos + n]
        if len(b) < n:
            raise ZkError(-4, "short read")
        self.pos += n
        return b

    def int(self) -> int:
        return struct.unpack(">i", self._take(4))[0]

    def long(self) -> int:
        return struct.unpack(">q", self._take(8))[0]

    def buffer(self) -> Optional[bytes]:
        n = self.int()
        return None if n < 0 else self._take(n)

    def string(self) -> str:
        b = self.buffer()
        return "" if b is None else b.decode()


@dataclass
class Stat:
    czxid: int
    mzxid: int
    ctime: int
    mtime: int
    version: int
    cversion: int
    aversion: int
    ephemeral_owner: int
    data_length: int
    num_children: int
    pzxid: int

    @classmethod
    def read(cls, r: Reader) -> "Stat":
        return cls(r.long(), r.long(), r.long(), r.long(), r.int(),
                   r.int(), r.int(), r.long(), r.int(), r.int(), r.long())


def enc_acls(acls) -> bytes:
    out = enc_int(len(acls))
    for perms, scheme, ident in acls:
        out += enc_int(perms) + enc_string(scheme) + enc_string(ident)
    return out


# -- client -----------------------------------------------------------------

class ZooKeeper:
    """One session to one server. Not thread-safe; each test worker
    owns its own connection, matching the interpreter's
    one-client-per-process model."""

    def __init__(self, host: str, port: int = 2181,
                 timeout: float = 5.0, session_timeout_ms: int = 10_000):
        self.sock = socket.create_connection((host, port), timeout)
        nodelay(self.sock)
        self.sock.settimeout(timeout)
        self.xid = 0
        self._handshake(session_timeout_ms)

    # framing --------------------------------------------------------------

    def _send(self, payload: bytes) -> None:
        self.sock.sendall(enc_int(len(payload)) + payload)

    def _recv(self) -> bytes:
        hdr = self._recv_n(4)
        (n,) = struct.unpack(">i", hdr)
        return self._recv_n(n)

    def _recv_n(self, n: int) -> bytes:
        out = b""
        while len(out) < n:
            chunk = self.sock.recv(n - len(out))
            if not chunk:
                raise ZkError(-4, "connection closed")
            out += chunk
        return out

    # session --------------------------------------------------------------

    def _handshake(self, session_timeout_ms: int) -> None:
        req = (enc_int(0) + enc_long(0) + enc_int(session_timeout_ms)
               + enc_long(0) + enc_buffer(b"\x00" * 16))
        self._send(req)
        r = Reader(self._recv())
        r.int()                      # protocol version
        self.negotiated_timeout = r.int()
        self.session_id = r.long()
        r.buffer()                   # session password

    def _request(self, op: int, payload: bytes) -> Reader:
        self.xid += 1
        self._send(enc_int(self.xid) + enc_int(op) + payload)
        r = Reader(self._recv())
        r.int()                      # xid
        r.long()                     # zxid
        err = r.int()
        if err != OK:
            raise ZkError(err, f"op {op}")
        return r

    # ops ------------------------------------------------------------------

    def create(self, path: str, data: bytes,
               acls=OPEN_ACL_UNSAFE, flags: int = 0) -> str:
        r = self._request(CREATE, enc_string(path) + enc_buffer(data)
                          + enc_acls(acls) + enc_int(flags))
        return r.string()

    def get_data(self, path: str) -> tuple[bytes, Stat]:
        r = self._request(GET_DATA, enc_string(path) + enc_bool(False))
        data = r.buffer() or b""
        return data, Stat.read(r)

    def set_data(self, path: str, data: bytes, version: int = -1) -> Stat:
        r = self._request(SET_DATA, enc_string(path) + enc_buffer(data)
                          + enc_int(version))
        return Stat.read(r)

    def exists(self, path: str) -> Optional[Stat]:
        try:
            r = self._request(EXISTS, enc_string(path) + enc_bool(False))
            return Stat.read(r)
        except ZkError as e:
            if e.code == NONODE:
                return None
            raise

    def delete(self, path: str, version: int = -1) -> None:
        self._request(DELETE, enc_string(path) + enc_int(version))

    def close(self) -> None:
        try:
            self.xid += 1
            self._send(enc_int(self.xid) + enc_int(CLOSE))
        except OSError:
            pass
        finally:
            self.sock.close()
