"""RabbitMQ test suite — queue semantics and a queue-backed mutex.

Mirrors the reference's rabbitmq suite
(`/root/reference/rabbitmq/src/jepsen/rabbitmq.clj`): deb package
install with a shared erlang cookie and config (`:26-75`), a queue
workload (enqueue with publisher confirms, dequeue-and-ack, final
drain, `:128-178`) checked by total-queue, and the *mutex-as-queue*
workload — a single token job; holding it = holding the lock; release
re-publishes (`:180-230`) — checked linearizably against the mutex
model on device.

Where the reference speaks AMQP through the langohr driver, this
client uses RabbitMQ's management HTTP API (publish/get with
ack_requeue_false), which exposes the same enqueue/dequeue/ack
semantics over plain HTTP — no driver dependency, same test meaning.
Hermetic tests run against an in-process fake of that API."""

from __future__ import annotations

import base64
import itertools
import json
import logging
import threading
import urllib.error
import urllib.request

from .. import checker, cli, client as jclient, control, models
from .. import db as jdb
from .. import generator as gen
from ..checker import linear
from ..control import util as cu
from ..os_ import debian
from . import std_opts, std_test

log = logging.getLogger(__name__)

MGMT_PORT = 15672
VHOST = "%2F"
DEFAULT_VERSION = "3.8.9"
COOKIE = "jepsen-rabbitmq"


class DB(jdb.DB, jdb.Process, jdb.LogFiles):
    """deb install + shared erlang cookie + clustering via rabbitmqctl
    join_cluster to the first node (`rabbitmq.clj:26-96`)."""

    def __init__(self, version: str = DEFAULT_VERSION):
        self.version = version

    def setup(self, test, node):
        with control.su():
            log.info("%s installing rabbitmq %s", node, self.version)
            debian.install(["erlang-nox", "rabbitmq-server"])
            control.exec_("service", "rabbitmq-server", "stop")
            control.exec_("sh", "-c",
                          f"echo '{COOKIE}' > "
                          f"/var/lib/rabbitmq/.erlang.cookie")
            control.exec_("chmod", "600",
                          "/var/lib/rabbitmq/.erlang.cookie")
            control.exec_("service", "rabbitmq-server", "start")
            control.exec_("rabbitmq-plugins", "enable",
                          "rabbitmq_management")
            primary = test["nodes"][0]
            if node != primary:
                control.exec_("rabbitmqctl", "stop_app")
                control.exec_("rabbitmqctl", "join_cluster",
                              f"rabbit@{primary}")
                control.exec_("rabbitmqctl", "start_app")
            control.exec_("rabbitmqctl", "add_user", "jepsen", "jepsen")
            control.exec_("rabbitmqctl", "set_user_tags", "jepsen",
                          "administrator")
            control.exec_("rabbitmqctl", "set_permissions", "-p", "/",
                          "jepsen", ".*", ".*", ".*")

    def start(self, test, node):
        with control.su():
            control.exec_("service", "rabbitmq-server", "start")

    def kill(self, test, node):
        with control.su():
            cu.grepkill("beam.smp")

    def teardown(self, test, node):
        with control.su():
            self.kill(test, node)
            control.exec_("rm", "-rf", "/var/lib/rabbitmq/mnesia")

    def log_files(self, test, node):
        return ["/var/log/rabbitmq/rabbit.log"]


def db(version: str = DEFAULT_VERSION) -> DB:
    return DB(version)


class MgmtClient(jclient.Client):
    """Queue ops over the management HTTP API."""

    QUEUE = "jepsen.queue"

    def __init__(self, timeout_s: float = 5.0):
        self.timeout_s = timeout_s
        self.base: str | None = None

    def open(self, test, node):
        c = type(self).__new__(type(self))
        c.__dict__.update(self.__dict__)
        fn = test.get("mgmt-url-fn")
        c.base = fn(node) if fn else f"http://{node}:{MGMT_PORT}"
        return c

    def _req(self, method: str, path: str, body: dict | None = None):
        req = urllib.request.Request(
            self.base + path,
            data=json.dumps(body).encode() if body is not None else None,
            method=method,
            headers={
                "Content-Type": "application/json",
                "Authorization": "Basic " + base64.b64encode(
                    b"jepsen:jepsen").decode(),
            })
        with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
            data = r.read()
            return json.loads(data) if data else None

    def setup(self, test):
        self._req("PUT", f"/api/queues/{VHOST}/{self.QUEUE}",
                  {"durable": True, "auto_delete": False})

    def publish(self, payload: str):
        r = self._req("POST",
                      f"/api/exchanges/{VHOST}/amq.default/publish",
                      {"routing_key": self.QUEUE, "payload": payload,
                       "payload_encoding": "string", "properties": {}})
        if not (r or {}).get("routed"):
            raise OSError("publish not routed")

    def get1(self):
        r = self._req("POST", f"/api/queues/{VHOST}/{self.QUEUE}/get",
                      {"count": 1, "ackmode": "ack_requeue_false",
                       "encoding": "auto"})
        if not r:
            return None
        return r[0]["payload"]


class QueueClient(MgmtClient):
    def invoke(self, test, op):
        try:
            if op["f"] == "enqueue":
                self.publish(str(op["value"]))
                return {**op, "type": "ok"}
            if op["f"] == "dequeue":
                v = self.get1()
                if v is None:
                    return {**op, "type": "fail", "error": "empty"}
                return {**op, "type": "ok", "value": int(v)}
            if op["f"] == "drain":
                out = []
                while True:
                    v = self.get1()
                    if v is None:
                        return {**op, "type": "ok", "value": out}
                    out.append(int(v))
            raise ValueError(f"unknown f {op['f']!r}")
        except (urllib.error.URLError, OSError, ValueError,
                KeyError) as e:
            # Dequeue/drain use ack_requeue_false: the broker removes
            # the message before we see the HTTP response, so a
            # transport error is indeterminate — the message may be
            # gone. Only :info keeps the total-queue checker sound.
            return {**op, "type": "info", "error": str(e)}


class MutexClient(MgmtClient):
    """The queue-as-mutex trick (`rabbitmq.clj:180-230`): one token job
    lives in the queue; acquire = dequeue it, release = re-publish.
    Each process tracks whether it holds the token, like the
    reference's `enqueued?` atom — releasing without holding must not
    mint new tokens."""

    QUEUE = "jepsen.semaphore"

    # guards the seeded flag in the shared test map: without it two
    # workers can both observe the empty list and mint two tokens
    _seed_lock = threading.Lock()

    def __init__(self, timeout_s: float = 5.0):
        super().__init__(timeout_s)
        self.held = False

    def setup(self, test):
        super().setup(test)
        with MutexClient._seed_lock:
            if not test.setdefault("_mutex-seeded", []):
                test["_mutex-seeded"].append(True)
                self.publish("token")

    def invoke(self, test, op):
        try:
            if op["f"] == "acquire":
                if self.held:
                    return {**op, "type": "fail",
                            "error": "already-held"}
                v = self.get1()
                if v is None:
                    return {**op, "type": "fail", "error": "not-free"}
                self.held = True
                return {**op, "type": "ok"}
            if op["f"] == "release":
                if not self.held:
                    return {**op, "type": "fail",
                            "error": "not-held"}
                self.held = False
                self.publish("token")
                return {**op, "type": "ok"}
            raise ValueError(f"unknown f {op['f']!r}")
        except (urllib.error.URLError, OSError, KeyError) as e:
            # an indeterminate release may or may not have re-minted
            # the token
            t = "fail" if op["f"] == "acquire" else "info"
            return {**op, "type": t, "error": str(e)}


def queue_workload(opts):
    values = itertools.count()

    def enq(test, ctx):
        return {"type": "invoke", "f": "enqueue", "value": next(values)}

    def deq(test, ctx):
        return {"type": "invoke", "f": "dequeue", "value": None}

    return {"client": QueueClient(),
            "generator": gen.mix([enq, deq]),
            "checker": checker.total_queue(),
            "final-generator": gen.each_thread(gen.once(
                {"type": "invoke", "f": "drain", "value": None}))}


def _acquire_release(test, ctx):
    return {"type": "invoke",
            "f": "acquire" if gen.rng.random() < 0.5 else "release",
            "value": None}


def mutex_workload(opts):
    return {
        "client": MutexClient(),
        "generator": gen.repeat(_acquire_release),
        "checker": linear.linearizable(models.mutex()),
    }


WORKLOADS = {"queue": queue_workload, "mutex": mutex_workload}


def rabbitmq_test(opts: dict) -> dict:
    workload_name = opts.get("workload", "queue")
    return std_test(
        opts, name=f"rabbitmq-{workload_name}",
        db=db(opts.get("version", DEFAULT_VERSION)),
        workload=WORKLOADS[workload_name](opts))


OPT_SPEC = std_opts(cli, WORKLOADS, "queue", DEFAULT_VERSION,
                    "rabbitmq-server version")


def main(argv=None):
    cli.run({**cli.single_test_cmd({"test_fn": rabbitmq_test,
                                    "opt_spec": OPT_SPEC}),
             **cli.serve_cmd()}, argv)


if __name__ == "__main__":
    main()
