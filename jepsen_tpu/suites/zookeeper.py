"""ZooKeeper test suite — the minimal complete exemplar.

Mirrors `zookeeper/src/jepsen/zookeeper.clj`: apt-installed ZK on
Debian nodes with per-node myid and a generated zoo.cfg quorum section
(:40-72), a CAS-register client over a single znode (:74-104, avout's
zk-atom becomes versioned setData — ZK's native compare-and-swap), a
random-halves partition nemesis, and the linearizable-register checker
running on device.
"""

from __future__ import annotations

import logging

from .. import checker, cli, client as jclient, control
from .. import db as jdb
from .. import generator as gen
from .. import models, testkit
from ..checker import timeline
from ..nemesis import partition
from ..os_ import debian
from . import zk_proto

log = logging.getLogger(__name__)

DEFAULT_VERSION = "3.4.13-6+deb10u1"
CLIENT_PORT = 2181
REGISTER_PATH = "/jepsen"

ZOO_CFG = """\
tickTime=2000
initLimit=10
syncLimit=5
dataDir=/var/lib/zookeeper
clientPort=2181
maxClientCnxns=0
"""


def zk_node_ids(test: dict) -> dict:
    """node name -> numeric id (`zookeeper.clj:20-25`)."""
    return {node: i for i, node in enumerate(test["nodes"])}


def zk_node_id(test: dict, node: str) -> int:
    return zk_node_ids(test)[node]


def zoo_cfg_servers(test: dict) -> str:
    """server.N=host:2888:3888 lines (`zookeeper.clj:33-38`)."""
    return "\n".join(f"server.{i}={node}:2888:3888"
                     for node, i in zk_node_ids(test).items())


class DB(jdb.DB, jdb.LogFiles):
    """ZooKeeper DB for a particular version (`zookeeper.clj:40-72`)."""

    def __init__(self, version: str = DEFAULT_VERSION):
        self.version = version

    def setup(self, test, node):
        with control.su():
            log.info("%s installing ZK %s", node, self.version)
            debian.install({"zookeeper": self.version,
                            "zookeeper-bin": self.version,
                            "zookeeperd": self.version})
            control.exec_("echo", str(zk_node_id(test, node)),
                          control.lit(">"), "/etc/zookeeper/conf/myid")
            control.exec_(
                "echo", ZOO_CFG + "\n" + zoo_cfg_servers(test),
                control.lit(">"), "/etc/zookeeper/conf/zoo.cfg")
            log.info("%s ZK restarting", node)
            control.exec_("service", "zookeeper", "restart")
            log.info("%s ZK ready", node)

    def teardown(self, test, node):
        log.info("%s tearing down ZK", node)
        with control.su():
            control.exec_("service", "zookeeper", "stop")
            control.exec_("rm", "-rf",
                          control.lit("/var/lib/zookeeper/version-*"),
                          control.lit("/var/log/zookeeper/*"))

    def log_files(self, test, node):
        return ["/var/log/zookeeper/zookeeper.log"]


def db(version: str = DEFAULT_VERSION) -> DB:
    return DB(version)


class ZkClient(jclient.Client):
    """A CAS-register client over one znode. CAS = setData conditioned
    on the read version — exactly what avout's swap!! compiles to
    (`zookeeper.clj:78-104`)."""

    def __init__(self, timeout_s: float = 5.0,
                 conn: zk_proto.ZooKeeper | None = None,
                 port: int = CLIENT_PORT):
        self.timeout_s = timeout_s
        self.conn = conn
        self.port = port

    def open(self, test, node):
        port = test.get("zk-port", self.port)
        host = test.get("zk-host-fn", lambda n: n)(node)
        conn = zk_proto.ZooKeeper(host, port, self.timeout_s)
        c = ZkClient(self.timeout_s, conn, port)
        return c

    def setup(self, test):
        try:
            self.conn.create(REGISTER_PATH, b"0")
        except zk_proto.ZkError as e:
            if e.code != zk_proto.NODEEXISTS:
                raise

    def invoke(self, test, op):
        f = op["f"]
        if f not in ("read", "write", "cas"):
            raise ValueError(f"unknown f {f!r}")
        try:
            if f == "read":
                data, _stat = self.conn.get_data(REGISTER_PATH)
                return {**op, "type": "ok", "value": int(data)}
            if f == "write":
                self.conn.set_data(REGISTER_PATH,
                                   str(op["value"]).encode(), -1)
                return {**op, "type": "ok"}
            old, new = op["value"]
            data, stat = self.conn.get_data(REGISTER_PATH)
            if int(data) != old:
                return {**op, "type": "fail"}
            try:
                self.conn.set_data(REGISTER_PATH, str(new).encode(),
                                   stat.version)
                return {**op, "type": "ok"}
            except zk_proto.ZkError as e:
                if e.code == zk_proto.BADVERSION:
                    # someone else wrote between our read and write
                    return {**op, "type": "fail"}
                raise
        except zk_proto.ZkError as e:
            return {**op, "type": "fail" if f == "read" else "info",
                    "error": ["zookeeper", e.code]}
        except (OSError, ValueError) as e:
            return {**op, "type": "fail" if f == "read" else "info",
                    "error": ["timeout", str(e)]}

    def close(self, test):
        if self.conn is not None:
            self.conn.close()


def r(test, ctx):
    return {"type": "invoke", "f": "read", "value": None}


def w(test, ctx):
    return {"type": "invoke", "f": "write", "value": gen.rng.randrange(5)}


def cas(test, ctx):
    return {"type": "invoke", "f": "cas",
            "value": [gen.rng.randrange(5), gen.rng.randrange(5)]}


def zk_test(opts: dict) -> dict:
    """Options map -> test map (`zookeeper.clj:106-129`)."""
    time_limit = opts.get("time-limit", opts.get("time_limit", 15))
    return {
        **testkit.noop_test(),
        **{k: v for k, v in opts.items() if isinstance(k, str)},
        "name": "zookeeper",
        "os": debian.os,
        "db": db(opts.get("version", DEFAULT_VERSION)),
        "client": ZkClient(),
        "nemesis": partition.partition_random_halves(),
        "generator": gen.time_limit(
            time_limit,
            gen.nemesis(
                gen.cycle(gen.phases(
                    gen.sleep(5),
                    gen.once({"type": "info", "f": "start",
                              "value": None}),
                    gen.sleep(5),
                    gen.once({"type": "info", "f": "stop",
                              "value": None}))),
                gen.stagger(1, gen.mix([r, w, cas])))),
        "model": models.cas_register(0),
        "checker": checker.compose({
            "perf": checker.perf_checker(),
            "timeline": timeline.html(),
            "linear": checker.linearizable(models.cas_register(0)),
        }),
    }


OPT_SPEC = [
    cli.opt("--version", default=DEFAULT_VERSION,
            help="ZooKeeper package version to install"),
]


def main(argv=None):
    """`-main` parity (`zookeeper.clj:131-137`)."""
    cli.run({**cli.single_test_cmd({"test_fn": zk_test,
                                    "opt_spec": OPT_SPEC}),
             **cli.serve_cmd()}, argv)


if __name__ == "__main__":
    main()
