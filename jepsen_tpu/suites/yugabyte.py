"""YugaByte DB test suite — dual-API (YCQL + YSQL).

Mirrors the reference's yugabyte suite
(`/root/reference/yugabyte/src/yugabyte/`): community-edition
master/tserver automation (`auto.clj:334-445`), the master/tserver
process nemesis plus partitions and clock skew (`nemesis.clj:12-120`,
`core.clj:128-165`), and both API surfaces (`core.clj:75-105`):

  * YCQL (Cassandra-compatible, port 9042) — bank, counter, set,
    set-index, long-fork, single-key-acid, multi-key-acid, driven
    through the hand-rolled CQL wire client (`cql_proto.py`) instead
    of the DataStax driver (`ycql/client.clj`).
  * YSQL (Postgres-compatible, port 5433) — bank, bank-multitable,
    counter, set, long-fork, single-key-acid, multi-key-acid,
    append (elle list-append, `ysql/append.clj`), default-value
    (`ysql/default_value.clj`) — via the Postgres wire client
    (`pg_proto.py`) instead of JDBC (`ysql/client.clj`).

Workload names are namespaced exactly like the reference's CLI:
``ycql/bank``, ``ysql/append``, ... (`core.clj:75-105`).
"""

from __future__ import annotations

import itertools
import logging
import re

from .. import checker, cli, client as jclient, control
from .. import db as jdb
from .. import generator as gen
from .. import independent, models
from ..checker import linear, timeline
from ..control import util as cu
from ..nemesis import Nemesis, compose as nemesis_compose
from ..nemesis import combined, partition as npartition, time as ntime
from ..workloads import append as append_w, bank as bank_w, \
    long_fork as long_fork_w
from . import std_test
from .cql_proto import CQLError, Conn as CQLConn, \
    ERR_ALREADY_EXISTS, ERR_INVALID, ERR_SYNTAX
from .pg_proto import Conn as PGConn, PGError

log = logging.getLogger(__name__)

DIR = "/home/yugabyte"
DATA_DIR = f"{DIR}/data"
MASTER_BIN = f"{DIR}/bin/yb-master"
TSERVER_BIN = f"{DIR}/bin/yb-tserver"
MASTER_LOG_DIR = f"{DATA_DIR}/yb-data/master/logs"
TSERVER_LOG_DIR = f"{DATA_DIR}/yb-data/tserver/logs"
MASTER_LOGFILE = f"{MASTER_LOG_DIR}/stdout"
TSERVER_LOGFILE = f"{TSERVER_LOG_DIR}/stdout"
MASTER_PIDFILE = f"{DIR}/master.pid"
TSERVER_PIDFILE = f"{DIR}/tserver.pid"
INSTALLED_URL_FILE = f"{DIR}/installed-url"

MASTER_RPC_PORT = 7100
YCQL_PORT = 9042
YSQL_PORT = 5433

KEYSPACE = "jepsen"
DEFAULT_VERSION = "1.3.1.0"

LIMITS_CONF = "* hard nofile 1048576\n* soft nofile 1048576"


def download_url(version: str) -> str:
    """`auto.clj:258-261`."""
    return f"https://downloads.yugabyte.com/yugabyte-{version}-linux.tar.gz"


def replication_factor(test: dict) -> int:
    return int(test.get("replication-factor", 3))


def master_nodes(test: dict) -> list:
    """Masters run on the first RF nodes (`auto.clj:57-66`)."""
    nodes = test["nodes"][:replication_factor(test)]
    if len(nodes) < replication_factor(test):
        raise ValueError(
            f"need {replication_factor(test)} master nodes, have "
            f"{test['nodes']}")
    return nodes


def master_node(test: dict, node: str) -> bool:
    return node in master_nodes(test)


def master_addresses(test: dict) -> str:
    """"n1:7100,n2:7100,..." (`auto.clj:72-80`)."""
    return ",".join(f"{n}:{MASTER_RPC_PORT}" for n in master_nodes(test))


def api_of(test: dict) -> str:
    """'ycql' or 'ysql', from the namespaced workload name."""
    api = test.get("api")
    if api:
        return api
    w = test.get("workload", "ycql/bank")
    return w.split("/", 1)[0] if "/" in w else "ycql"


class DB(jdb.DB, jdb.Process, jdb.Pause, jdb.Primary, jdb.LogFiles):
    """Community-edition automation (`auto.clj:334-445`): install the
    release tarball + post_install once per URL, raise ulimits, start
    yb-master on the first RF nodes and yb-tserver everywhere, wait
    for both via yb-admin."""

    def __init__(self, version: str = DEFAULT_VERSION):
        self.version = version

    # -- install / configure -------------------------------------------------

    def _install(self, test):
        url = test.get("url") or test.get("tarball") \
            or download_url(test.get("version", self.version))
        installed = control.exec_(
            "bash", "-c", f"cat {INSTALLED_URL_FILE} 2>/dev/null || true")
        if installed.strip() == url:
            return
        log.info("installing yugabyte from %s", url)
        cu.install_archive(url, DIR)
        with control.cd(DIR):
            control.exec_("./bin/post_install.sh")
            control.exec_("bash", "-c",
                          f"echo '{url}' > {INSTALLED_URL_FILE}")

    def _configure(self):
        """ulimit raise (`auto.clj:358-366`)."""
        control.exec_("bash", "-c",
                      f"echo '{LIMITS_CONF}' > "
                      "/etc/security/limits.d/jepsen.conf")

    # -- lifecycle -----------------------------------------------------------

    def setup(self, test, node):
        with control.su():
            self._install(test)
            self._configure()
            self.start(test, node)

    def teardown(self, test, node):
        self.kill(test, node)
        with control.su():
            control.exec_("rm", "-rf", DATA_DIR)

    def shared_opts(self, node) -> list:
        """`auto.clj:284-300`."""
        return ["--fs_data_dirs", DATA_DIR,
                "--memory_limit_hard_bytes", "2147483648",
                "--yb_num_shards_per_tserver", "4",
                "--rpc_bind_addresses", node]

    def start_master(self, test, node):
        api = api_of(test)
        with control.su():
            control.exec_("mkdir", "-p", MASTER_LOG_DIR)
            args = self.shared_opts(node) + [
                "--master_addresses", master_addresses(test),
                "--replication_factor", str(replication_factor(test))]
            if api == "ysql":
                args.append("--use_initial_sys_catalog_snapshot")
            cu.start_daemon(
                {"logfile": MASTER_LOGFILE, "pidfile": MASTER_PIDFILE,
                 "chdir": DIR},
                MASTER_BIN, *args)

    def start_tserver(self, test, node):
        api = api_of(test)
        with control.su():
            control.exec_("mkdir", "-p", TSERVER_LOG_DIR)
            args = self.shared_opts(node) + [
                "--tserver_master_addrs", master_addresses(test),
                "--enable_tracing",
                "--rpc_slow_query_threshold_ms", "1000",
                "--load_balancer_max_concurrent_adds", "10"]
            if api == "ysql":
                args += ["--start_pgsql_proxy",
                         "--pgsql_proxy_bind_address", node]
            cu.start_daemon(
                {"logfile": TSERVER_LOGFILE, "pidfile": TSERVER_PIDFILE,
                 "chdir": DIR},
                TSERVER_BIN, *args)

    def stop_master(self, test, node):
        with control.su():
            cu.stop_daemon(MASTER_PIDFILE, cmd="yb-master")

    def stop_tserver(self, test, node):
        with control.su():
            cu.stop_daemon(TSERVER_PIDFILE, cmd="yb-tserver")
            cu.grepkill("postgres")

    def kill_master(self, test, node):
        with control.su():
            cu.grepkill("yb-master")
        self.stop_master(test, node)

    def kill_tserver(self, test, node):
        with control.su():
            cu.grepkill("yb-tserver")
        self.stop_tserver(test, node)

    def start(self, test, node):
        """Master (if a master node) then tserver (`auto.clj:180-194`)."""
        if master_node(test, node):
            self.start_master(test, node)
        self.start_tserver(test, node)

    def kill(self, test, node):
        self.kill_tserver(test, node)
        if master_node(test, node):
            self.kill_master(test, node)

    def pause(self, test, node):
        with control.su():
            cu.signal("yb-master", "STOP")
            cu.signal("yb-tserver", "STOP")

    def resume(self, test, node):
        with control.su():
            cu.signal("yb-master", "CONT")
            cu.signal("yb-tserver", "CONT")

    def setup_primary(self, test, node):
        pass

    def log_files(self, test, node):
        return [MASTER_LOGFILE, TSERVER_LOGFILE]


def db(version: str = DEFAULT_VERSION) -> DB:
    return DB(version)


# ---------------------------------------------------------------------------
# YCQL data plane (`ycql/client.clj`)
# ---------------------------------------------------------------------------

# Messages that mean the transaction *definitely* failed
# (`ycql/client.clj:234-240`).
_CQL_DEFINITE_FAIL = re.compile(
    r"Value write after transaction start"
    r"|Conflicts with higher priority transaction"
    r"|Conflicts with committed transaction"
    r"|Operation expired: .*status: COMMITTED .*Transaction expired")


def _cql_connect(test, node) -> CQLConn:
    fn = test.get("cql-conn-fn")
    if fn is not None:
        return fn(node)
    return CQLConn(node, YCQL_PORT, timeout_s=10.0)


def _q(v) -> str:
    """Quote a scalar literal into CQL/SQL text."""
    if isinstance(v, bool):
        raise ValueError("no boolean literals here")
    if isinstance(v, int):
        return str(v)
    s = str(v)
    if "'" in s or "\\" in s:
        raise ValueError(f"unquotable literal {s!r}")
    return f"'{s}'"


def _close_quietly(conn) -> None:
    if conn is not None:
        try:
            conn.close()
        except Exception:  # noqa: BLE001 — already broken
            pass


def _reconn_fail(op) -> dict:
    # A failed reconnect proves the op was never sent, so "fail" is
    # safe for reads and writes alike.
    return {**op, "type": "fail", "error": ["conn", "reconnect-failed"]}


class _CQLClient(jclient.Client):
    """Shared open/close + the with-errors classification
    (`ycql/client.clj:197-245`): unavailable -> fail; timeouts ->
    fail when the op was idempotent, else info; messages that prove
    the txn failed -> fail; everything else indeterminate."""

    # ops that are safe to call :fail on error
    idempotent: frozenset = frozenset({"read"})

    def __init__(self):
        self.conn: CQLConn | None = None

    def open(self, test, node):
        c = type(self).__new__(type(self))
        c.__dict__.update(self.__dict__)
        c.node = node
        c.conn = _cql_connect(test, node)
        return c

    def close(self, test):
        if self.conn is not None:
            self.conn.close()

    def _drop_conn(self):
        """Discard a desynchronized connection. After a socket-level
        timeout the server's late response frame would otherwise be
        read as the next query's result (the raw socket has no
        stream-id correlation the way the reference's DataStax driver
        does, `ycql/client.clj:197`), so the socket must never be
        reused."""
        _close_quietly(self.conn)
        self.conn = None

    def _ensure_keyspace(self, test):
        self.conn.query(
            f"CREATE KEYSPACE IF NOT EXISTS {KEYSPACE} WITH replication"
            " = {'class': 'SimpleStrategy', 'replication_factor': "
            f"{replication_factor(test)}}}")

    def invoke(self, test, op):
        crash = "fail" if op["f"] in self.idempotent else "info"
        if self.conn is None:
            try:
                self.conn = _cql_connect(test, self.node)
            except (ConnectionError, OSError, CQLError) as e:
                # CQLError covers an ERROR frame during STARTUP — a
                # recovering tserver answering Overloaded/ServerError.
                return {**op, "type": "fail", "error": ["conn", str(e)]}
        try:
            return self._invoke(test, op)
        except CQLError as e:
            if e.unavailable:
                return {**op, "type": "fail",
                        "error": ["unavailable", e.message]}
            if e.timeout:
                return {**op, "type": crash, "error": "timed-out"}
            if _CQL_DEFINITE_FAIL.search(e.message):
                return {**op, "type": "fail", "error": e.message}
            if e.code in (ERR_SYNTAX, ERR_ALREADY_EXISTS):
                raise
            if e.code == ERR_INVALID:
                if re.search(r"RPC to .+ timed out after", e.message):
                    return {**op, "type": crash,
                            "error": ["rpc-timed-out", e.message]}
                raise
            return {**op, "type": crash,
                    "error": ["cql", e.code, e.message]}
        except (ConnectionError, OSError) as e:
            self._drop_conn()
            return {**op, "type": crash, "error": ["conn", str(e)]}

    def _invoke(self, test, op):
        raise NotImplementedError


class CQLBank(_CQLClient):
    """Single-table bank over BEGIN/END TRANSACTION batches
    (`ycql/bank.clj:20-59`)."""

    idempotent = frozenset({"read"})

    def setup(self, test):
        self._ensure_keyspace(test)
        self.conn.query(
            f"CREATE TABLE IF NOT EXISTS {KEYSPACE}.accounts "
            "(id INT PRIMARY KEY, balance BIGINT) "
            "WITH transactions = { 'enabled' : true }",
            timeout_s=30.0)
        accounts = test.get("accounts", list(range(8)))
        total = test.get("total-amount", 100)
        for a in accounts:
            bal = total if a == accounts[0] else 0
            self.conn.query(
                f"INSERT INTO {KEYSPACE}.accounts (id, balance) "
                f"VALUES ({_q(a)}, {_q(bal)})")

    def _invoke(self, test, op):
        if op["f"] == "read":
            rows, _ = self.conn.query(
                f"SELECT id, balance FROM {KEYSPACE}.accounts")
            return {**op, "type": "ok",
                    "value": {int(r[0]): int(r[1]) for r in rows}}
        v = op["value"]
        frm, to, amount = v["from"], v["to"], v["amount"]
        self.conn.query(
            "BEGIN TRANSACTION "
            f"UPDATE {KEYSPACE}.accounts SET balance = balance - "
            f"{amount} WHERE id = {frm};"
            f"UPDATE {KEYSPACE}.accounts SET balance = balance + "
            f"{amount} WHERE id = {to};"
            "END TRANSACTION;")
        return {**op, "type": "ok"}


class CQLCounter(_CQLClient):
    """One counter row (`ycql/counter.clj:13-37`)."""

    idempotent = frozenset({"read"})

    def setup(self, test):
        self._ensure_keyspace(test)
        self.conn.query(
            f"CREATE TABLE IF NOT EXISTS {KEYSPACE}.counter "
            "(id INT PRIMARY KEY, count COUNTER)", timeout_s=30.0)
        self.conn.query(f"UPDATE {KEYSPACE}.counter SET count = count + 0"
                        " WHERE id = 0")

    def _invoke(self, test, op):
        if op["f"] == "add":
            v = op["value"]
            delta = f"+ {v}" if v >= 0 else f"- {-v}"
            self.conn.query(
                f"UPDATE {KEYSPACE}.counter SET count = count {delta} "
                "WHERE id = 0")
            return {**op, "type": "ok"}
        rows, _ = self.conn.query(
            f"SELECT count FROM {KEYSPACE}.counter WHERE id = 0")
        return {**op, "type": "ok",
                "value": int(rows[0][0]) if rows else 0}


class CQLSet(_CQLClient):
    """Set via per-element counter rows (`ycql/set.clj:11-33`)."""

    idempotent = frozenset({"read"})

    def setup(self, test):
        self._ensure_keyspace(test)
        self.conn.query(
            f"CREATE TABLE IF NOT EXISTS {KEYSPACE}.elements "
            "(val INT PRIMARY KEY, count COUNTER)", timeout_s=30.0)

    def _invoke(self, test, op):
        if op["f"] == "add":
            self.conn.query(
                f"UPDATE {KEYSPACE}.elements SET count = count + 1 "
                f"WHERE val = {op['value']}")
            return {**op, "type": "ok"}
        rows, _ = self.conn.query(
            f"SELECT val, count FROM {KEYSPACE}.elements")
        out = []
        for val, count in rows:
            out.extend([int(val)] * int(count))
        return {**op, "type": "ok", "value": sorted(out)}


GROUP_COUNT = 8   # `ycql/set.clj:35-37`


class CQLSetIndex(_CQLClient):
    """Set read through a secondary index (`ycql/set.clj:39-72`)."""

    idempotent = frozenset({"read"})

    def setup(self, test):
        self._ensure_keyspace(test)
        self.conn.query(
            f"CREATE TABLE IF NOT EXISTS {KEYSPACE}.elements2 "
            "(key INT PRIMARY KEY, val INT, grp INT) "
            "WITH transactions = { 'enabled' : true }", timeout_s=30.0)
        try:
            self.conn.query(
                f"CREATE INDEX elements_by_group ON {KEYSPACE}.elements2"
                " (grp) INCLUDE (val)", timeout_s=30.0)
        except CQLError as e:
            if "already exists" not in e.message:
                raise

    def _invoke(self, test, op):
        if op["f"] == "add":
            v = op["value"]
            self.conn.query(
                f"INSERT INTO {KEYSPACE}.elements2 (key, val, grp) "
                f"VALUES ({v}, {v}, {gen.rng.randrange(GROUP_COUNT)})")
            return {**op, "type": "ok"}
        groups = ", ".join(str(g) for g in range(GROUP_COUNT))
        rows, _ = self.conn.query(
            f"SELECT val FROM {KEYSPACE}.elements2 WHERE grp IN "
            f"({groups})")
        return {**op, "type": "ok",
                "value": sorted(int(r[0]) for r in rows)}


class CQLLongFork(_CQLClient):
    """Long-fork reads via the key2 index (`ycql/long_fork.clj:13-55`).
    Nothing is idempotent here — reads carry txn rewrites."""

    idempotent = frozenset()

    def setup(self, test):
        self._ensure_keyspace(test)
        self.conn.query(
            f"CREATE TABLE IF NOT EXISTS {KEYSPACE}.long_fork "
            "(key INT PRIMARY KEY, key2 INT, val INT) "
            "WITH transactions = { 'enabled' : true }", timeout_s=30.0)
        try:
            self.conn.query(
                f"CREATE INDEX long_forks ON {KEYSPACE}.long_fork (key2)"
                " INCLUDE (val)", timeout_s=30.0)
        except CQLError as e:
            if "already exists" not in e.message:
                raise

    def _invoke(self, test, op):
        txn = op["value"]
        if op["f"] == "read":
            ks = ", ".join(str(k) for _f, k, _v in txn)
            rows, _ = self.conn.query(
                f"SELECT key2, val FROM {KEYSPACE}.long_fork "
                f"WHERE key2 IN ({ks})")
            vs = {int(k): int(v) for k, v in rows}
            txn2 = [[f, k, vs.get(k)] for f, k, _ in txn]
            return {**op, "type": "ok", "value": txn2}
        [[_f, k, v]] = txn
        self.conn.query(
            f"INSERT INTO {KEYSPACE}.long_fork (key, key2, val) "
            f"VALUES ({k}, {k}, {v})")
        return {**op, "type": "ok"}


class CQLSingleKey(_CQLClient):
    """Independent per-key linearizable registers
    (`ycql/single_key_acid.clj:15-48`)."""

    idempotent = frozenset({"read"})

    def setup(self, test):
        self._ensure_keyspace(test)
        self.conn.query(
            f"CREATE TABLE IF NOT EXISTS {KEYSPACE}.single_key_acid "
            "(id INT PRIMARY KEY, val INT)", timeout_s=30.0)

    def _invoke(self, test, op):
        k, v = op["value"]
        if op["f"] == "write":
            self.conn.query(
                f"INSERT INTO {KEYSPACE}.single_key_acid (id, val) "
                f"VALUES ({k}, {v})")
            return {**op, "type": "ok"}
        if op["f"] == "cas":
            expected, new = v
            rows, cols = self.conn.query(
                f"UPDATE {KEYSPACE}.single_key_acid SET val = {new} "
                f"WHERE id = {k} IF val = {expected}")
            applied = bool(rows and rows[0][cols.index("[applied]")])
            return {**op, "type": "ok" if applied else "fail"}
        rows, _ = self.conn.query(
            f"SELECT val FROM {KEYSPACE}.single_key_acid "
            f"WHERE id = {k}")
        val = int(rows[0][0]) if rows and rows[0][0] is not None else None
        return {**op, "type": "ok",
                "value": independent.ktuple(k, val)}


class CQLMultiKey(_CQLClient):
    """Transactional multi-key writes, independent by ik
    (`ycql/multi_key_acid.clj:13-66`)."""

    idempotent = frozenset({"read"})

    def setup(self, test):
        self._ensure_keyspace(test)
        self.conn.query(
            f"CREATE TABLE IF NOT EXISTS {KEYSPACE}.multi_key_acid "
            "(id INT, ik INT, val INT, PRIMARY KEY (id, ik)) "
            "WITH transactions = { 'enabled' : true }", timeout_s=30.0)

    def _invoke(self, test, op):
        ik, txn = op["value"]
        if op["f"] == "read":
            ks = ", ".join(str(k) for _f, k, _v in txn)
            rows, _ = self.conn.query(
                f"SELECT id, val FROM {KEYSPACE}.multi_key_acid "
                f"WHERE ik = {ik} AND id IN ({ks})")
            vs = {int(r[0]): int(r[1]) for r in rows if r[1] is not None}
            txn2 = [[f, k, vs.get(k)] for f, k, _ in txn]
            return {**op, "type": "ok",
                    "value": independent.ktuple(ik, txn2)}
        stmts = "".join(
            f"INSERT INTO {KEYSPACE}.multi_key_acid (id, ik, val) "
            f"VALUES ({k}, {ik}, {v});"
            for f, k, v in txn)
        self.conn.query(f"BEGIN TRANSACTION {stmts}END TRANSACTION;")
        return {**op, "type": "ok"}


# ---------------------------------------------------------------------------
# YSQL data plane (`ysql/client.clj`)
# ---------------------------------------------------------------------------

# SQLSTATEs proving rollback (serialization failure, deadlock, aborted
# txn) — safe to :fail (`ysql/client.clj:166-186` message classes).
YSQL_DEFINITE_ABORT = {"40001", "40P01", "25P02"}

_YSQL_FAIL_MSG = re.compile(
    r"conflicts with [- a-z]+ transaction"
    r"|catalog version mismatch"
    r"|try again"
    r"|restart read required", re.I)
_YSQL_INFO_MSG = re.compile(
    r"error during commit.*expired"
    r"|timed out after deadline expired", re.I)


def _ysql_connect(test, node) -> PGConn:
    fn = test.get("sql-conn-fn")
    if fn is not None:
        return fn(node)
    return PGConn(node, YSQL_PORT, user="postgres", database="postgres",
                  timeout_s=30.0)


class _YSQLClient(jclient.Client):
    """Shared open/close, txn wrapper, and exception->op
    classification (`ysql/client.clj:153-253`)."""

    def __init__(self):
        self.conn: PGConn | None = None

    def open(self, test, node):
        c = type(self).__new__(type(self))
        c.__dict__.update(self.__dict__)
        c.node = node
        c._test = test
        c.conn = _ysql_connect(test, node)
        return c

    def close(self, test):
        if self.conn is not None:
            self.conn.close()

    def _drop_conn(self):
        """Discard a connection after a socket-level error: a late
        response to a timed-out query would otherwise corrupt the next
        query's result. The reference routes ysql conns through
        jepsen.reconnect for the same reason (`ysql/client.clj:60`)."""
        _close_quietly(self.conn)
        self.conn = None

    def _ensure_conn(self) -> bool:
        if self.conn is None:
            try:
                self.conn = _ysql_connect(self._test, self.node)
            except (ConnectionError, OSError, PGError):
                # PGError covers a failed startup handshake — a
                # recovering node answering 57P03 "starting up" or
                # closing the socket mid-handshake (08006).
                self.conn = None
                return False
        return True

    def _capture(self, op, e: Exception, read_only: bool) -> dict:
        # SQLSTATE class 08 is a connection exception (pg_proto
        # synthesizes 08006 when the server closes the socket
        # mid-response) — socket-level, so the conn must be dropped
        # like any OSError.
        if isinstance(e, PGError) and not e.code.startswith("08"):
            definite = (e.code in YSQL_DEFINITE_ABORT
                        or (_YSQL_FAIL_MSG.search(e.message)
                            and not _YSQL_INFO_MSG.search(e.message)))
            if definite or read_only:
                return {**op, "type": "fail",
                        "error": ["sql", e.code, e.message]}
            return {**op, "type": "info",
                    "error": ["sql", e.code, e.message]}
        self._drop_conn()
        return {**op, "type": "fail" if read_only else "info",
                "error": ["conn", str(e)]}

    def _txn(self, stmts_fn, op, read_only=False):
        if not self._ensure_conn():
            return _reconn_fail(op)
        conn = self.conn
        try:
            conn.query("begin")
            out = stmts_fn(conn)
            conn.query("commit")
            return {**op, "type": "ok", **out}
        except Exception as e:  # noqa: BLE001 — classified below
            # Rolling back on a desynced socket would just stall for
            # another timeout; _capture drops the conn for those.
            socket_dead = (isinstance(e, (OSError, ConnectionError))
                           or (isinstance(e, PGError)
                               and e.code.startswith("08")))
            if not socket_dead:
                try:
                    conn.query("rollback")
                except Exception:  # noqa: BLE001 — conn is dead
                    self._drop_conn()
            if isinstance(e, (PGError, OSError, ConnectionError)):
                return self._capture(op, e, read_only)
            raise

    def _run(self, body_fn, op, read_only=False):
        """Single-statement op outside an explicit txn."""
        if not self._ensure_conn():
            return _reconn_fail(op)
        try:
            return {**op, "type": "ok", **body_fn(self.conn)}
        except (PGError, OSError, ConnectionError) as e:
            return self._capture(op, e, read_only)


def _upsert(conn, table: str, where_col: str, where_val, insert_sql: str,
            update_sql: str) -> None:
    """Update-then-insert, the reference's pattern for YB's lack of
    reliable upsert (`ysql/append.clj:56-68`)."""
    n, _ = conn.query(update_sql)
    if not n:
        conn.query(insert_sql)


class YSQLBank(_YSQLClient):
    """Single-table bank (`ysql/bank.clj:20-75`). The menu constructs
    it with negative balances allowed, as the reference does
    (`core.clj:95-96`, `->YSQLBankClient true`)."""

    def __init__(self, allow_negatives: bool = True):
        super().__init__()
        self.allow_negatives = allow_negatives

    def setup(self, test):
        self.conn.query("create table if not exists accounts "
                        "(id int primary key, balance bigint)")
        accounts = test.get("accounts", list(range(8)))
        total = test.get("total-amount", 100)
        for a in accounts:
            bal = total if a == accounts[0] else 0
            self.conn.query(
                f"insert into accounts (id, balance) values "
                f"({_q(a)}, {_q(bal)}) on conflict (id) do update set "
                f"balance = {_q(bal)}")

    def invoke(self, test, op):
        if op["f"] == "read":
            def read_body(conn):
                rows, _ = conn.query("select id, balance from accounts")
                return {"value": {int(r[0]): int(r[1]) for r in rows}}
            return self._txn(read_body, op, read_only=True)

        v = op["value"]
        frm, to, amount = v["from"], v["to"], v["amount"]

        def transfer_body(conn):
            rows, _ = conn.query(
                f"select balance from accounts where id = {_q(frm)}")
            b1 = int(rows[0][0]) - amount
            rows, _ = conn.query(
                f"select balance from accounts where id = {_q(to)}")
            b2 = int(rows[0][0]) + amount
            if b1 < 0 and not self.allow_negatives:
                raise _InsufficientFunds(frm, b1)
            conn.query(f"update accounts set balance = {_q(b1)} "
                       f"where id = {_q(frm)}")
            conn.query(f"update accounts set balance = {_q(b2)} "
                       f"where id = {_q(to)}")
            return {}

        try:
            return self._txn(transfer_body, op)
        except _InsufficientFunds as e:
            return {**op, "type": "fail",
                    "value": ["negative", e.account, e.balance]}


class _InsufficientFunds(Exception):
    def __init__(self, account, balance):
        super().__init__(f"{account} would go to {balance}")
        self.account = account
        self.balance = balance


class YSQLMultiBank(_YSQLClient):
    """Bank with one table per account (`ysql/bank.clj:77-123`);
    negative balances allowed at construction like the reference's
    `->YSQLMultiBankClient true` (`core.clj:97`)."""

    def __init__(self, allow_negatives: bool = True):
        super().__init__()
        self.allow_negatives = allow_negatives

    def setup(self, test):
        accounts = test.get("accounts", list(range(8)))
        total = test.get("total-amount", 100)
        for a in accounts:
            self.conn.query(f"create table if not exists accounts{a} "
                            "(id int primary key, balance bigint)")
            bal = total if a == accounts[0] else 0
            self.conn.query(
                f"insert into accounts{a} (id, balance) values "
                f"({_q(a)}, {_q(bal)}) on conflict (id) do update set "
                f"balance = {_q(bal)}")

    def invoke(self, test, op):
        accounts = test.get("accounts", list(range(8)))
        if op["f"] == "read":
            def read_body(conn):
                out = {}
                for a in accounts:
                    rows, _ = conn.query(
                        f"select balance from accounts{a} "
                        f"where id = {_q(a)}")
                    out[a] = int(rows[0][0])
                return {"value": out}
            return self._txn(read_body, op, read_only=True)

        v = op["value"]
        frm, to, amount = v["from"], v["to"], v["amount"]

        def transfer_body(conn):
            rows, _ = conn.query(
                f"select balance from accounts{frm} where id = {_q(frm)}")
            b1 = int(rows[0][0]) - amount
            rows, _ = conn.query(
                f"select balance from accounts{to} where id = {_q(to)}")
            b2 = int(rows[0][0]) + amount
            if b1 < 0 and not self.allow_negatives:
                raise _InsufficientFunds(frm, b1)
            conn.query(f"update accounts{frm} set balance = {_q(b1)} "
                       f"where id = {_q(frm)}")
            conn.query(f"update accounts{to} set balance = {_q(b2)} "
                       f"where id = {_q(to)}")
            return {}

        try:
            return self._txn(transfer_body, op)
        except _InsufficientFunds as e:
            return {**op, "type": "fail",
                    "value": ["negative", e.account, e.balance]}


class YSQLCounter(_YSQLClient):
    """Single-row counter (`ysql/counter.clj`)."""

    def setup(self, test):
        self.conn.query("create table if not exists counter "
                        "(id int primary key, count bigint)")
        self.conn.query("insert into counter (id, count) values (0, 0) "
                        "on conflict (id) do update set count = count")

    def invoke(self, test, op):
        if op["f"] == "add":
            v = op["value"]
            expr = f"count + {v}" if v >= 0 else f"count - {-v}"
            return self._run(
                lambda conn: (conn.query(
                    f"update counter set count = {expr} where id = 0"),
                    {})[1],
                op)
        def read_body(conn):
            rows, _ = conn.query("select count from counter where id = 0")
            return {"value": int(rows[0][0])}
        return self._run(read_body, op, read_only=True)


class YSQLSet(_YSQLClient):
    """Grow-only set of inserted rows (`ysql/set.clj:14-45`)."""

    def setup(self, test):
        self.conn.query("create table if not exists elements "
                        "(val int primary key)")

    def invoke(self, test, op):
        if op["f"] == "add":
            v = op["value"]
            return self._run(
                lambda conn: (conn.query(
                    f"insert into elements (val) values ({_q(v)})"),
                    {})[1],
                op)
        def read_body(conn):
            rows, _ = conn.query("select val from elements")
            return {"value": sorted(int(r[0]) for r in rows)}
        return self._run(read_body, op, read_only=True)


class YSQLLongFork(_YSQLClient):
    """Long-fork over a plain table (`ysql/long_fork.clj`)."""

    def setup(self, test):
        self.conn.query("create table if not exists long_fork "
                        "(key int primary key, val int)")

    def invoke(self, test, op):
        txn = op["value"]
        if op["f"] == "read":
            def read_body(conn):
                vs = {}
                for _f, k, _v in txn:
                    rows, _ = conn.query(
                        f"select val from long_fork where key = {_q(k)}")
                    if rows:
                        vs[k] = int(rows[0][0])
                return {"value": [[f, k, vs.get(k)] for f, k, _ in txn]}
            return self._txn(read_body, op, read_only=True)
        [[_f, k, v]] = txn
        return self._run(
            lambda conn: (conn.query(
                f"insert into long_fork (key, val) values "
                f"({_q(k)}, {_q(v)})"), {})[1],
            op)


class YSQLSingleKey(_YSQLClient):
    """Independent per-key registers (`ysql/single_key_acid.clj`)."""

    def setup(self, test):
        self.conn.query("create table if not exists single_key_acid "
                        "(id int primary key, val int)")

    def invoke(self, test, op):
        k, v = op["value"]
        if op["f"] == "write":
            return self._run(
                lambda conn: (_upsert(
                    conn, "single_key_acid", "id", k,
                    f"insert into single_key_acid (id, val) values "
                    f"({_q(k)}, {_q(v)})",
                    f"update single_key_acid set val = {_q(v)} "
                    f"where id = {_q(k)}"), {})[1],
                op)
        if op["f"] == "cas":
            expected, new = v

            def cas_body(conn):
                rows, _ = conn.query(
                    f"select val from single_key_acid where id = {_q(k)}"
                    " for update")
                cur = int(rows[0][0]) if rows else None
                if cur != expected:
                    raise _CasFailed()
                conn.query(f"update single_key_acid set val = {_q(new)} "
                           f"where id = {_q(k)}")
                return {}
            try:
                return self._txn(cas_body, op)
            except _CasFailed:
                try:
                    self.conn.query("rollback")
                except Exception:  # noqa: BLE001
                    pass
                return {**op, "type": "fail"}

        def read_body(conn):
            rows, _ = conn.query(
                f"select val from single_key_acid where id = {_q(k)}")
            val = int(rows[0][0]) if rows and rows[0][0] is not None \
                else None
            return {"value": independent.ktuple(k, val)}
        return self._run(read_body, op, read_only=True)


class _CasFailed(Exception):
    pass


class YSQLMultiKey(_YSQLClient):
    """Transactional multi-key writes (`ysql/multi_key_acid.clj`)."""

    def setup(self, test):
        self.conn.query("create table if not exists multi_key_acid "
                        "(rowkey varchar(32) primary key, ik int, "
                        "id int, val int)")

    def invoke(self, test, op):
        ik, txn = op["value"]
        if op["f"] == "read":
            def read_body(conn):
                vs = {}
                for _f, k, _v in txn:
                    rows, _ = conn.query(
                        "select val from multi_key_acid where rowkey = "
                        f"{_q(f'{ik}_{k}')}")
                    if rows and rows[0][0] is not None:
                        vs[k] = int(rows[0][0])
                return {"value": independent.ktuple(
                    ik, [[f, k, vs.get(k)] for f, k, _ in txn])}
            return self._txn(read_body, op, read_only=True)

        def write_body(conn):
            for _f, k, v in txn:
                rk = _q(f"{ik}_{k}")
                _upsert(conn, "multi_key_acid", "rowkey", f"{ik}_{k}",
                        f"insert into multi_key_acid (rowkey, ik, id, "
                        f"val) values ({rk}, {_q(ik)}, {_q(k)}, {_q(v)})",
                        f"update multi_key_acid set val = {_q(v)} "
                        f"where rowkey = {rk}")
            return {}
        return self._txn(write_body, op)


# -- ysql append (`ysql/append.clj`) -----------------------------------------

TABLE_COUNT = 5       # `append.clj:19-22`
KEYS_PER_ROW = 2      # `append.clj:33`


def append_table_for(k) -> str:
    return f"append{hash(k) % TABLE_COUNT}"


def append_row_for(k) -> int:
    return k // KEYS_PER_ROW


def append_col_for(k) -> str:
    return f"v{k % KEYS_PER_ROW}"


class YSQLAppend(_YSQLClient):
    """Elle list-append over text-concat columns, multiple keys per
    row across several tables (`ysql/append.clj:18-140`)."""

    def setup(self, test):
        cols = ", ".join(f"{append_col_for(i)} text"
                         for i in range(KEYS_PER_ROW))
        for i in range(TABLE_COUNT):
            self.conn.query(
                f"create table if not exists append{i} "
                f"(k int primary key, k2 int, {cols})")

    def _mop(self, conn, mop):
        f, k, v = mop
        table, row, col = (append_table_for(k), append_row_for(k),
                           append_col_for(k))
        if f == "r":
            rows, _ = conn.query(
                f"select {col} from {table} where k = {_q(row)}")
            raw = rows[0][0] if rows else None
            vals = [int(x) for x in (raw or "").split(",") if x != ""]
            return [f, k, vals]
        # append (`append.clj:56-68`)
        n, _ = conn.query(
            f"update {table} set {col} = concat({col}, ',', {_q(v)}) "
            f"where k = {_q(row)}")
        if not n:
            conn.query(
                f"insert into {table} (k, k2, {col}) values "
                f"({_q(row)}, {_q(row)}, {_q(v)})")
        return [f, k, v]

    def invoke(self, test, op):
        txn = op["value"]
        if len(txn) > 1:
            def txn_body(conn):
                return {"value": [self._mop(conn, m) for m in txn]}
            return self._txn(txn_body, op)
        return self._run(
            lambda conn: {"value": [self._mop(conn, m) for m in txn]},
            op)


# -- ysql default-value (`ysql/default_value.clj`) ---------------------------

DV_TABLE = "foo"


class YSQLDefaultValue(_YSQLClient):
    """DDL/DML race client (`ysql/default_value.clj:100-123`)."""

    def invoke(self, test, op):
        f = op["f"]
        if not self._ensure_conn():
            return _reconn_fail(op)
        try:
            if f == "create-table":
                self.conn.query(
                    f"create table if not exists {DV_TABLE} "
                    "(dummy int, v int default 0)")
                return {**op, "type": "ok"}
            if f == "drop-table":
                self.conn.query(f"drop table if exists {DV_TABLE}")
                return {**op, "type": "ok"}
            if f == "insert":
                self.conn.query(
                    f"insert into {DV_TABLE} (dummy) values (1)")
                return {**op, "type": "ok"}
            if f == "read":
                rows, _ = self.conn.query(f"select v from {DV_TABLE}")
                return {**op, "type": "ok",
                        "value": [None if r[0] is None else int(r[0])
                                  for r in rows]}
            raise ValueError(f"unknown f {f!r}")
        except PGError as e:
            if re.search(r"does(n't| not) exist", e.message):
                return {**op, "type": "fail", "error": "table-missing"}
            return self._capture(op, e, read_only=(f == "read"))
        except (OSError, ConnectionError) as e:
            return self._capture(op, e, read_only=(f == "read"))


def default_value_checker() -> checker.Checker:
    """No ok read may observe a row whose v is null
    (`default_value.clj:35-76` in the shared workload file)."""
    def check(test, hist, opts):
        bad = []
        reads = 0
        for op in hist:
            if op.get("type") == "ok" and op.get("f") == "read":
                reads += 1
                if any(v is None for v in (op.get("value") or [])):
                    bad.append(op)
        return {"valid?": not bad, "read-count": reads,
                "bad-read-count": len(bad), "bad-reads": bad[:16]}
    return checker.coerce(check)


# ---------------------------------------------------------------------------
# Workloads (`core.clj:75-105` + the shared workload files)
# ---------------------------------------------------------------------------

def _naturals():
    return itertools.count()


def bank_workload(opts, client) -> dict:
    """`bank.clj:9-15` — negative balances allowed in both APIs."""
    w = bank_w.test({"negative-balances?": True})
    return {"client": client, "generator": w["generator"],
            "final-generator": w.get("final-generator"),
            "checker": checker.compose({
                "bank": w["checker"], "timeline": timeline.html()})}


def counter_workload(opts, client) -> dict:
    """Increment-only counter (`counter.clj:9-24`). Function
    generators: bare dicts are one-shot, which would cap the run at
    ~101 ops with at most a single read."""
    def add(test, ctx):
        return {"type": "invoke", "f": "add", "value": 1}

    def r(test, ctx):
        return {"type": "invoke", "f": "read", "value": None}

    return {"client": client,
            "generator": gen.mix([r] + [add] * 100),
            "checker": checker.compose({
                "timeline": timeline.html(),
                "counter": checker.counter(),
                "counter-plot": checker.counter_plot()})}


def set_workload(opts, client) -> dict:
    """Half the threads add, half read (`set.clj:10-26`)."""
    adds = ({"type": "invoke", "f": "add", "value": i}
            for i in _naturals())
    reads = {"type": "invoke", "f": "read", "value": None}
    n = max(1, opts.get("concurrency", 5) // 2)
    return {"client": client,
            "generator": gen.reserve(n, adds, reads),
            "final-generator": gen.each_thread(gen.once(
                {"type": "invoke", "f": "read", "value": None})),
            "checker": checker.set_full()}


def long_fork_workload(opts, client) -> dict:
    w = long_fork_w.workload(3)
    return {"client": client, "generator": w["generator"],
            "checker": w["checker"]}


def single_key_acid_workload(opts, client) -> dict:
    """2n threads per key: n writers/cas, n readers
    (`single_key_acid.clj:31-49`)."""
    n = len(opts.get("nodes", ["n1", "n2", "n3", "n4", "n5"]))

    def r(test, ctx):
        return {"type": "invoke", "f": "read", "value": None}

    def w(test, ctx):
        return {"type": "invoke", "f": "write",
                "value": gen.rng.randrange(5)}

    def cas(test, ctx):
        return {"type": "invoke", "f": "cas",
                "value": (gen.rng.randrange(5), gen.rng.randrange(5))}

    stagger = opts.get("acid-stagger", 1)

    def fgen(k):
        return gen.process_limit(
            20, gen.stagger(stagger,
                            gen.reserve(n, gen.mix([w, cas, cas]), r)))

    return {"client": client,
            "generator": independent.concurrent_generator(
                2 * n, _naturals(), fgen),
            "checker": independent.checker(checker.compose({
                "timeline": timeline.html(),
                "linear": linear.linearizable(
                    models.cas_register(0))}))}


MK_KEYS = (0, 1, 2)   # `multi_key_acid.clj:41-43`


def multi_key_acid_workload(opts, client) -> dict:
    """Transactional reads/writes over 3 subkeys per independent key,
    checked against the MultiRegister model
    (`multi_key_acid.clj:16-75`)."""
    n = len(opts.get("nodes", ["n1", "n2", "n3", "n4", "n5"]))

    def subset():
        ks = [k for k in MK_KEYS if gen.rng.random() < 0.5]
        return ks or [gen.rng.choice(MK_KEYS)]

    def r(test, ctx):
        return {"type": "invoke", "f": "read",
                "value": [["r", k, None] for k in subset()]}

    def w(test, ctx):
        return {"type": "invoke", "f": "write",
                "value": [["w", k, gen.rng.randrange(5)]
                          for k in subset()]}

    stagger = opts.get("acid-stagger", 1)

    def fgen(k):
        return gen.process_limit(
            20, gen.stagger(stagger, gen.reserve(n, gen.mix([w]), r)))

    return {"client": client,
            "generator": independent.concurrent_generator(
                2 * n, _naturals(), fgen),
            "checker": independent.checker(checker.compose({
                "timeline": timeline.html(),
                "linear": linear.linearizable(
                    models.multi_register())}))}


def append_workload(opts, client) -> dict:
    """Elle list-append (`append.clj:12-19`); YugaByte claims
    serializability, so the realtime precedence graph joins the cycle
    search (`append.clj:17` `:additional-graphs [cycle/realtime-graph]`)."""
    w = append_w.workload({"key-count": 32, "max-txn-length": 4,
                           "max-writes-per-key": 1024,
                           "additional-graphs": ("realtime",)})
    return {"client": client, "generator": w["generator"],
            "checker": w["checker"]}


def default_value_workload(opts, client) -> dict:
    """Concurrent create/drop-table + insert/read
    (`default_value.clj:13-29`). Function generators: every op class
    recurs for the whole run (bare dicts are one-shot, which both
    capped runs at ~52 ops and let the single create-table land after
    every read with probability ~1/26 — a zero-ok class the stats
    checker flags)."""
    def _dv(f):
        return lambda test, ctx: {"type": "invoke", "f": f,
                                  "value": None}

    return {"client": client,
            "generator": gen.mix(
                [_dv("create-table"), _dv("drop-table")]
                + [_dv("read"), _dv("insert")] * 25),
            "checker": default_value_checker()}


WORKLOADS = {
    "ycql/bank": lambda o: bank_workload(o, CQLBank()),
    "ycql/counter": lambda o: counter_workload(o, CQLCounter()),
    "ycql/set": lambda o: set_workload(o, CQLSet()),
    "ycql/set-index": lambda o: set_workload(o, CQLSetIndex()),
    "ycql/long-fork": lambda o: long_fork_workload(o, CQLLongFork()),
    "ycql/single-key-acid":
        lambda o: single_key_acid_workload(o, CQLSingleKey()),
    "ycql/multi-key-acid":
        lambda o: multi_key_acid_workload(o, CQLMultiKey()),
    "ysql/bank": lambda o: bank_workload(o, YSQLBank()),
    "ysql/bank-multitable": lambda o: bank_workload(o, YSQLMultiBank()),
    "ysql/counter": lambda o: counter_workload(o, YSQLCounter()),
    "ysql/set": lambda o: set_workload(o, YSQLSet()),
    "ysql/long-fork": lambda o: long_fork_workload(o, YSQLLongFork()),
    "ysql/single-key-acid":
        lambda o: single_key_acid_workload(o, YSQLSingleKey()),
    "ysql/multi-key-acid":
        lambda o: multi_key_acid_workload(o, YSQLMultiKey()),
    "ysql/append": lambda o: append_workload(o, YSQLAppend()),
    "ysql/default-value":
        lambda o: default_value_workload(o, YSQLDefaultValue()),
}


# ---------------------------------------------------------------------------
# Nemesis (`nemesis.clj:12-120`)
# ---------------------------------------------------------------------------

class ProcessNemesis(Nemesis):
    """Kill/stop/pause master and tserver processes on random subsets;
    start/resume heal everywhere (`nemesis.clj:12-45`)."""

    FS = {"start-master", "start-tserver", "stop-master", "stop-tserver",
          "kill-master", "kill-tserver", "pause-master", "pause-tserver",
          "resume-master", "resume-tserver"}

    def fs(self):
        return set(self.FS)

    def invoke(self, test, op):
        f = op["f"]
        db_ = test["db"]
        if f in ("start-tserver", "resume-tserver"):
            nodes = list(test["nodes"])
        elif f in ("start-master", "resume-master"):
            nodes = master_nodes(test)
        elif f.endswith("master"):
            nodes = combined.random_nonempty_subset(master_nodes(test))
        else:
            nodes = combined.random_nonempty_subset(test["nodes"])

        def act(t, node):
            if f == "start-master":
                return db_.start_master(t, node) or "started"
            if f == "start-tserver":
                return db_.start_tserver(t, node) or "started"
            if f == "stop-master":
                return db_.stop_master(t, node) or "stopped"
            if f == "stop-tserver":
                return db_.stop_tserver(t, node) or "stopped"
            if f == "kill-master":
                return db_.kill_master(t, node) or "killed"
            if f == "kill-tserver":
                return db_.kill_tserver(t, node) or "killed"
            with control.su():
                proc = "yb-master" if f.endswith("master") else \
                    "yb-tserver"
                cu.signal(proc, "STOP" if f.startswith("pause") else
                          "CONT")
            return "paused" if f.startswith("pause") else "resumed"

        return {**op, "value": control.on_nodes(test, act, nodes)}


def _op(f, value=None):
    return {"type": "info", "f": f, "value": value}


def _role_gen(role: str, kind: str):
    """kill/pause cycles for one process role."""
    if kind == "kill":
        return itertools.cycle([_op(f"kill-{role}"),
                                _op(f"start-{role}")])
    return itertools.cycle([_op(f"pause-{role}"),
                            _op(f"resume-{role}")])


def nemesis_package(opts: dict) -> dict:
    """Compose the process nemesis with partitioner + clock
    (`nemesis.clj:69-84`, generators at `nemesis.clj:86-160`)."""
    faults = set(opts.get("faults") or ())
    nemeses = []
    gens = []
    finals = []
    perf = []
    if faults & {"kill-master", "kill-tserver", "pause-master",
                 "pause-tserver"}:
        nemeses.append((frozenset(ProcessNemesis.FS), ProcessNemesis()))
        for f in sorted(faults):
            if f.startswith(("kill-", "pause-")):
                kind, role = f.split("-", 1)
                gens.append(_role_gen(role, kind))
        finals += [_op("resume-tserver"), _op("resume-master"),
                   _op("start-tserver"), _op("start-master")]
        perf += [{"name": "kill master", "start": {"kill-master",
                                                   "stop-master"},
                  "stop": {"start-master"}, "fill-color": "#E9A4A0"},
                 {"name": "kill tserver", "start": {"kill-tserver",
                                                    "stop-tserver"},
                  "stop": {"start-tserver"}, "fill-color": "#E9C3A0"},
                 {"name": "pause master", "start": {"pause-master"},
                  "stop": {"resume-master"}, "fill-color": "#A0B1E9"},
                 {"name": "pause tserver", "start": {"pause-tserver"},
                  "stop": {"resume-tserver"}, "fill-color": "#B8A0E9"}]
    if "partition" in faults:
        nemeses.append((frozenset({"start-partition", "stop-partition"}),
                        npartition.partitioner()))

        def start_partition(test, ctx):
            style = gen.rng.choice(["one", "half", "ring"])
            nodes = list(test["nodes"])
            gen.rng.shuffle(nodes)
            if style == "one":
                grudge = npartition.complete_grudge(
                    npartition.split_one(nodes))
            elif style == "half":
                grudge = npartition.complete_grudge(
                    npartition.bisect(nodes))
            else:
                grudge = npartition.majorities_ring(nodes)
            return {"type": "info", "f": "start-partition",
                    "value": grudge, "partition-type": style}

        gens.append(itertools.cycle(
            [start_partition, _op("stop-partition")]))
        finals.append(_op("stop-partition"))
        perf.append({"name": "partition", "start": {"start-partition"},
                     "stop": {"stop-partition"},
                     "fill-color": "#888888"})
    if "clock" in faults:
        nemeses.append((frozenset({"reset", "bump", "strobe",
                                   "check-offsets"}),
                        ntime.clock_nemesis()))
        gens.append(ntime.clock_gen())
        finals.append(_op("reset"))
        perf.append({"name": "clock skew",
                     "start": {"bump", "strobe"}, "stop": {"reset"},
                     "fill-color": "#D2E9A0"})
    if not nemeses:
        from .. import nemesis as jnemesis
        return {"nemesis": jnemesis.noop, "generator": None,
                "final-generator": None, "perf": []}

    interval = opts.get("nemesis-interval", 10)

    def spaced(g):
        return gen.stagger(interval, g)

    return {
        "nemesis": nemesis_compose(nemeses),
        "generator": gen.mix([spaced(g) for g in gens]),
        "final-generator": finals,
        "perf": perf,
    }


# ---------------------------------------------------------------------------
# Test construction + CLI (`core.clj:198-275`, `runner.clj`)
# ---------------------------------------------------------------------------

YB_FAULTS = ["partition", "kill-master", "kill-tserver", "pause-master",
             "pause-tserver", "clock", "none"]


def yugabyte_test(opts: dict) -> dict:
    workload_name = opts.get("workload", "ycql/bank")
    api = workload_name.split("/", 1)[0]
    opts = {**opts, "api": api}
    workload = WORKLOADS[workload_name](opts)
    faults = [f for f in (opts.get("faults") or ["partition"])
              if f != "none"]
    pkg = nemesis_package({**opts, "faults": faults})
    return std_test(
        opts,
        name=f"yb-{workload_name.replace('/', '-')}",
        db=db(opts.get("version", DEFAULT_VERSION)),
        workload=workload,
        nemesis_package=pkg,
        extra={"api": api,
               "replication-factor": opts.get("replication-factor", 3)})


OPT_SPEC = [
    cli.opt("--workload", "-w", default="ycql/bank",
            choices=sorted(WORKLOADS), help="Which workload to run"),
    cli.opt("--rate", type=float, default=10,
            help="approximate op rate per second"),
    cli.opt("--faults", action="append", choices=YB_FAULTS,
            help="faults to inject (repeatable)"),
    cli.opt("--nemesis-interval", type=float, default=10,
            help="seconds between nemesis operations"),
    cli.opt("--version", default=DEFAULT_VERSION,
            help="yugabyte version to install"),
    cli.opt("--replication-factor", type=int, default=3,
            help="number of master nodes / replicas"),
]


def main(argv=None):
    cli.run({**cli.single_test_cmd({"test_fn": yugabyte_test,
                                    "opt_spec": OPT_SPEC}),
             **cli.serve_cmd()}, argv)


if __name__ == "__main__":
    main()
