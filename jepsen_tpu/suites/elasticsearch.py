"""Elasticsearch test suite — lost-update set tests over the REST API.

Mirrors `/root/reference/elasticsearch/src/jepsen/elasticsearch/`:
deb install with unicast discovery zen config, and two set
implementations (`sets.clj:40-180`):

  * create-set: every add creates an independent document; the final
    read flushes and scrolls the whole index — lost documents are lost
    inserts.
  * cas-set: one document holding the whole set, updated with MVCC
    version preconditions — version conflicts are definite fails.

Where the reference speaks the Java transport client, this suite uses
the REST API (the same surface ES ships for every other language).
Hermetic tests run against `tests/fake_es_ignite.py`."""

from __future__ import annotations

import json
import logging
import urllib.error
import urllib.request

from .. import checker, cli, client as jclient, control
from .. import db as jdb
from ..control import util as cu
from ..control.core import RemoteError
from ..os_ import debian
from . import std_opts, std_test

log = logging.getLogger(__name__)

PORT = 9200
INDEX = "jepsen-index"
DEFAULT_VERSION = "1.5.0"

ES_CONF = """\
cluster.name: jepsen
node.name: {node}
network.host: 0.0.0.0
discovery.zen.ping.multicast.enabled: false
discovery.zen.ping.unicast.hosts: [{hosts}]
discovery.zen.minimum_master_nodes: {quorum}
"""


class DB(jdb.DB, jdb.Process, jdb.LogFiles):
    """deb install + unicast discovery (`core.clj:150-260`)."""

    def __init__(self, version: str = DEFAULT_VERSION):
        self.version = version

    def setup(self, test, node):
        debian.install_jdk11()
        with control.su():
            url = test.get("deb-url") or (
                "https://download.elastic.co/elasticsearch/"
                f"elasticsearch/elasticsearch-{self.version}.deb")
            control.exec_("dpkg", "-i", "--force-confnew",
                          cu.cached_wget(url))
            hosts = ", ".join(f'"{n}"' for n in test["nodes"])
            cu.write_file(ES_CONF.format(
                node=node, hosts=hosts,
                quorum=len(test["nodes"]) // 2 + 1),
                "/etc/elasticsearch/elasticsearch.yml")
            self.start(test, node)
            cu.await_tcp_port(PORT)

    def start(self, test, node):
        with control.su():
            control.exec_("service", "elasticsearch", "start")

    def kill(self, test, node):
        with control.su():
            try:
                control.exec_("service", "elasticsearch", "stop")
            except RemoteError:
                pass
            cu.grepkill("elasticsearch")

    def teardown(self, test, node):
        self.kill(test, node)
        with control.su():
            try:
                control.exec_("rm", "-rf",
                              "/var/lib/elasticsearch/jepsen")
            except RemoteError:
                pass

    def log_files(self, test, node):
        return ["/var/log/elasticsearch/jepsen.log"]


def db(version: str = DEFAULT_VERSION) -> DB:
    return DB(version)


class ESClient(jclient.Client):
    def __init__(self, timeout_s: float = 5.0):
        self.timeout_s = timeout_s
        self.base: str | None = None

    def open(self, test, node):
        c = type(self)(self.timeout_s)
        fn = test.get("es-url-fn")
        c.base = fn(node) if fn else f"http://{node}:{PORT}"
        return c

    def _req(self, method: str, path: str, body=None,
             ok_statuses=(200, 201)):
        # str bodies go raw (ES 1.x scroll continuation takes the bare
        # scroll id, not JSON — JSON bodies arrived in ES 2.0)
        data = None
        if body is not None:
            data = body.encode() if isinstance(body, str) \
                else json.dumps(body).encode()
        req = urllib.request.Request(
            self.base + path, data=data, method=method,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req,
                                        timeout=self.timeout_s) as r:
                return r.status, json.loads(r.read() or b"{}")
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read() or b"{}")


class CreateSetClient(ESClient):
    """Each add is an independent document (`sets.clj:40-95`)."""

    def invoke(self, test, op):
        try:
            if op["f"] == "add":
                status, _ = self._req(
                    "POST", f"/{INDEX}/number",
                    {"num": op["value"]})
                if status in (200, 201):
                    return {**op, "type": "ok"}
                return {**op, "type": "info", "error": status}
            if op["f"] == "read":
                # flush, then scroll the WHOLE index: one bounded
                # search would silently truncate past its size cap
                self._req("POST", f"/{INDEX}/_flush")
                status, out = self._req(
                    "GET", f"/{INDEX}/_search?scroll=10s&size=1000",
                    {"query": {"match_all": {}}})
                if status != 200:
                    return {**op, "type": "fail", "error": status}
                vals = []
                while True:
                    hits = out.get("hits", {}).get("hits", [])
                    if not hits:
                        break
                    vals.extend(h["_source"]["num"] for h in hits)
                    sid = out.get("_scroll_id")
                    if sid is None:
                        break
                    status, out = self._req(
                        "POST", "/_search/scroll?scroll=10s", sid)
                    if status != 200:
                        return {**op, "type": "fail", "error": status}
                return {**op, "type": "ok", "value": sorted(vals)}
            raise ValueError(f"unknown f {op['f']!r}")
        except (OSError, KeyError) as e:
            t = "fail" if op["f"] == "read" else "info"
            return {**op, "type": t, "error": str(e)}


class CASSetClient(ESClient):
    """One document holding the set, updated with MVCC version
    preconditions (`sets.clj:95-180`)."""

    DOC = "0"

    def setup(self, test):
        self._req("PUT", f"/{INDEX}/cas/{self.DOC}?op_type=create",
                  {"values": []})

    def invoke(self, test, op):
        try:
            if op["f"] == "add":
                status, cur = self._req("GET",
                                        f"/{INDEX}/cas/{self.DOC}")
                if status != 200 or not cur.get("found", True):
                    return {**op, "type": "fail",
                            "error": "no-current-doc"}
                version = cur["_version"]
                values = cur["_source"]["values"] + [op["value"]]
                status, _ = self._req(
                    "PUT", f"/{INDEX}/cas/{self.DOC}?version={version}",
                    {"values": values})
                if status in (200, 201):
                    return {**op, "type": "ok"}
                if status == 409:   # version conflict: definitely lost
                    return {**op, "type": "fail", "error": "conflict"}
                return {**op, "type": "info", "error": status}
            if op["f"] == "read":
                status, cur = self._req("GET",
                                        f"/{INDEX}/cas/{self.DOC}")
                if status != 200:
                    return {**op, "type": "fail", "error": status}
                return {**op, "type": "ok",
                        "value": sorted(cur["_source"]["values"])}
            raise ValueError(f"unknown f {op['f']!r}")
        except (OSError, KeyError) as e:
            t = "fail" if op["f"] == "read" else "info"
            return {**op, "type": t, "error": str(e)}


def _set_workload(client) -> dict:
    from .. import generator as gen
    import itertools

    values = itertools.count()

    def add(test, ctx):
        return {"type": "invoke", "f": "add", "value": next(values)}

    return {
        "client": client,
        "generator": add,
        "checker": checker.set_checker(),
        "final-generator": gen.each_thread(gen.once(
            {"type": "invoke", "f": "read", "value": None})),
    }


WORKLOADS = {
    "create-set": lambda opts: _set_workload(CreateSetClient()),
    "cas-set": lambda opts: _set_workload(CASSetClient()),
}


def elasticsearch_test(opts: dict) -> dict:
    workload_name = opts.get("workload", "create-set")
    return std_test(
        opts, name=f"elasticsearch-{workload_name}",
        db=db(opts.get("version", DEFAULT_VERSION)),
        workload=WORKLOADS[workload_name](opts))


OPT_SPEC = std_opts(cli, WORKLOADS, "create-set", DEFAULT_VERSION,
                    "elasticsearch deb version")


def main(argv=None):
    cli.run({**cli.single_test_cmd({"test_fn": elasticsearch_test,
                                    "opt_spec": OPT_SPEC}),
             **cli.serve_cmd()}, argv)


if __name__ == "__main__":
    main()
