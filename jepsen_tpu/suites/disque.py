"""Disque test suite — distributed message queue semantics.

Mirrors the reference's disque suite
(`/root/reference/disque/src/jepsen/disque.clj`): build from source on
each node (`:40-54`), single-config cluster joined via CLUSTER MEET to
the primary (`:96-106`), and the queue workload — enqueue with
configurable replication/retry, dequeue as GETJOB+ACKJOB
(`:195-210`), drain at the end — checked by total-queue.

The client speaks RESP directly (`resp_proto.py`); hermetic tests run
against an in-process RESP fake (tests/fake_disque.py).
"""

from __future__ import annotations

import itertools
import logging

from .. import checker, cli, client as jclient, control
from .. import db as jdb
from .. import generator as gen
from ..control import util as cu
from ..os_ import debian
from . import std_opts, std_test
from .resp_proto import Conn, RESPError

log = logging.getLogger(__name__)

DIR = "/opt/disque"
DATA_DIR = "/var/lib/disque"
PIDFILE = "/var/run/disque.pid"
BINARY = f"{DIR}/src/disque-server"
CONTROL_BIN = f"{DIR}/src/disque"
CONFIG = f"{DIR}/disque.conf"
LOGFILE = f"{DATA_DIR}/log"
PORT = 7711

DEFAULT_VERSION = "master"

CONFIG_BODY = f"""\
port {PORT}
daemonize no
dir {DATA_DIR}
"""


class DB(jdb.DB, jdb.Process, jdb.LogFiles):
    """git clone + make, then CLUSTER MEET everyone to the first node
    (`disque.clj:40-54,96-106`)."""

    def __init__(self, version: str = DEFAULT_VERSION):
        self.version = version

    def setup(self, test, node):
        with control.su():
            log.info("%s installing disque %s", node, self.version)
            debian.install(["git-core", "build-essential"])
            with control.cd("/opt"):
                if not cu.exists(DIR):
                    control.exec_("git", "clone",
                                  "https://github.com/antirez/disque.git")
            with control.cd(DIR):
                control.exec_("git", "fetch", "--all")
                control.exec_("git", "reset", "--hard", self.version)
                control.exec_("make")
            control.exec_("sh", "-c",
                          f"echo '{CONFIG_BODY}' > {CONFIG}")
            control.exec_("mkdir", "-p", DATA_DIR)
            self.start(test, node)
            cu.await_tcp_port(PORT)
        # join everyone to the first node
        primary = test["nodes"][0]
        if node != primary:
            with control.su():
                out = control.exec_(CONTROL_BIN, "-p", str(PORT),
                                    "cluster", "meet", primary,
                                    str(PORT))
                assert "OK" in str(out)

    def start(self, test, node):
        with control.su():
            cu.start_daemon(
                {"logfile": LOGFILE, "pidfile": PIDFILE, "chdir": DIR},
                BINARY, CONFIG)

    def kill(self, test, node):
        with control.su():
            cu.grepkill("disque-server")

    def teardown(self, test, node):
        log.info("%s wiping disque", node)
        with control.su():
            self.kill(test, node)
            control.exec_("rm", "-rf", f"{DATA_DIR}/*", LOGFILE, PIDFILE)

    def log_files(self, test, node):
        return [LOGFILE]


def db(version: str = DEFAULT_VERSION) -> DB:
    return DB(version)


def _connect(test, node) -> Conn:
    fn = test.get("resp-conn-fn")
    if fn is not None:
        return fn(node)
    return Conn(node, PORT)


class QueueClient(jclient.Client):
    """enqueue = ADDJOB (replicate/retry per test opts), dequeue =
    GETJOB + ACKJOB, drain = dequeue until empty
    (`disque.clj:180-240`)."""

    QUEUE = "jepsen"

    def __init__(self, timeout_ms: int = 100):
        self.timeout_ms = timeout_ms
        self.conn: Conn | None = None

    def open(self, test, node):
        c = QueueClient(self.timeout_ms)
        c.conn = _connect(test, node)
        return c

    def close(self, test):
        if self.conn is not None:
            self.conn.close()

    def _dequeue1(self):
        jobs = self.conn.call("GETJOB", "TIMEOUT", self.timeout_ms,
                              "COUNT", 1, "FROM", self.QUEUE)
        if not jobs:
            return None
        queue, job_id, body = jobs[0][0], jobs[0][1], jobs[0][2]
        self.conn.call("ACKJOB", job_id)
        return int(body)

    def invoke(self, test, op):
        try:
            if op["f"] == "enqueue":
                args = ["ADDJOB", self.QUEUE, str(op["value"]),
                        self.timeout_ms]
                replicate = test.get("replicate")
                if replicate:
                    args += ["REPLICATE", replicate]
                retry = test.get("retry-s")
                if retry is not None:
                    args += ["RETRY", retry]
                self.conn.call(*args)
                return {**op, "type": "ok"}
            if op["f"] == "dequeue":
                v = self._dequeue1()
                if v is None:
                    return {**op, "type": "fail", "error": "empty"}
                return {**op, "type": "ok", "value": v}
            if op["f"] == "drain":
                out = []
                while True:
                    v = self._dequeue1()
                    if v is None:
                        return {**op, "type": "ok", "value": out}
                    out.append(v)
            raise ValueError(f"unknown f {op['f']!r}")
        except (RESPError, OSError) as e:
            # enqueue may or may not have landed; dequeue without an
            # ack leaves the job for redelivery
            t = "info" if op["f"] == "enqueue" else "fail"
            return {**op, "type": t, "error": str(e)}


def queue_workload(opts):
    values = itertools.count()

    def enq(test, ctx):
        return {"type": "invoke", "f": "enqueue", "value": next(values)}

    def deq(test, ctx):
        return {"type": "invoke", "f": "dequeue", "value": None}

    return {"client": QueueClient(),
            "generator": gen.mix([enq, deq]),
            "checker": checker.total_queue(),
            "final-generator": gen.each_thread(gen.once(
                {"type": "invoke", "f": "drain", "value": None}))}


WORKLOADS = {"queue": queue_workload}


def disque_test(opts: dict) -> dict:
    workload_name = opts.get("workload", "queue")
    return std_test(
        opts, name=f"disque-{workload_name}",
        db=db(opts.get("version", DEFAULT_VERSION)),
        workload=WORKLOADS[workload_name](opts))


OPT_SPEC = std_opts(cli, WORKLOADS, "queue", DEFAULT_VERSION,
                    "disque git rev to build") + [
    cli.opt("--replicate", type=int,
            help="ADDJOB REPLICATE level (default: server default)"),
]


def main(argv=None):
    cli.run({**cli.single_test_cmd({"test_fn": disque_test,
                                    "opt_spec": OPT_SPEC}),
             **cli.serve_cmd()}, argv)


if __name__ == "__main__":
    main()
