"""Minimal ReQL (RethinkDB query protocol) wire client.

The reference's rethinkdb suite drives the clj-rethinkdb driver
(`rethinkdb/src/jepsen/rethinkdb.clj:24-27,108-120`); this module
speaks the JSON protocol directly: the V0_4 handshake (magic +
auth-key + JSON protocol magic, each little-endian) followed by
queries as [QueryType, term, opts] framed by an 8-byte token and a
4-byte length. Terms are the standard nested arrays
([term-id, args, opts]); only the subset the suite needs is exposed.
Hermetic tests run against `tests/fake_rethinkdb.py`."""

from __future__ import annotations

import json
import socket

from .netutil import nodelay
import struct
import threading

V0_4 = 0x400C2D20
PROTOCOL_JSON = 0x7E6970C7

# query types
Q_START = 1
Q_CONTINUE = 2

# response types
R_SUCCESS_ATOM = 1
R_SUCCESS_SEQUENCE = 2
R_SUCCESS_PARTIAL = 3
R_CLIENT_ERROR = 16
R_COMPILE_ERROR = 17
R_RUNTIME_ERROR = 18

# term ids (ql2.proto)
T_DB = 14
T_TABLE = 15
T_GET = 16
T_EQ = 17
T_ERROR = 12
T_FUNC = 69
T_VAR = 10
T_BRANCH = 65
T_GET_FIELD = 31
T_INSERT = 56
T_UPDATE = 53
T_DB_CREATE = 57
T_TABLE_CREATE = 60
T_DEFAULT = 92
T_WAIT = 177
T_DATUM_OBJ = 3   # MAKE_OBJ is implicit via plain dicts


class ReQLError(Exception):
    def __init__(self, rtype: int, message: str):
        super().__init__(f"reql error {rtype}: {message}")
        self.rtype = rtype
        self.message = message


# -- term builders -----------------------------------------------------------

def db(name):
    return [T_DB, [name]]


def table(dbname, tbl, read_mode=None):
    opts = {"read_mode": read_mode} if read_mode else {}
    return [T_TABLE, [db(dbname), tbl], opts] if opts \
        else [T_TABLE, [db(dbname), tbl]]


def get(tbl_term, key):
    return [T_GET, [tbl_term, key]]


def get_field(row, name):
    return [T_GET_FIELD, [row, name]]


def default(term, fallback):
    return [T_DEFAULT, [term, fallback]]


def insert(tbl_term, doc, conflict=None):
    opts = {"conflict": conflict} if conflict else {}
    return [T_INSERT, [tbl_term, _datum(doc)], opts] if opts \
        else [T_INSERT, [tbl_term, _datum(doc)]]


def update(target, func_or_doc):
    return [T_UPDATE, [target, func_or_doc]]


def branch(cond, then, otherwise):
    return [T_BRANCH, [cond, then, otherwise]]


def eq(a, b):
    return [T_EQ, [a, b]]


def error(msg):
    return [T_ERROR, [msg]]


def func(body):
    """One-arg row function: var 1 is the row."""
    return [T_FUNC, [[2, [1]], body]]  # [MAKE_ARRAY, [1]]


def var(n):
    return [T_VAR, [n]]


def db_create(name):
    return [T_DB_CREATE, [name]]


def table_create(dbname, tbl, replicas=None):
    opts = {"replicas": replicas} if replicas else {}
    return [T_TABLE_CREATE, [db(dbname), tbl], opts] if opts \
        else [T_TABLE_CREATE, [db(dbname), tbl]]


def wait(tbl_term):
    return [T_WAIT, [tbl_term]]


def _datum(doc: dict):
    """Literal objects are sent as plain JSON objects in ReQL."""
    return doc


class Conn:
    """One RethinkDB connection in V0_4/JSON mode."""

    def __init__(self, host: str, port: int = 28015,
                 auth_key: str = "", timeout_s: float = 10.0):
        self.sock = socket.create_connection((host, port), timeout_s)
        nodelay(self.sock)
        self.token = 0
        self.lock = threading.Lock()
        key = auth_key.encode()
        self.sock.sendall(struct.pack("<I", V0_4)
                          + struct.pack("<I", len(key)) + key
                          + struct.pack("<I", PROTOCOL_JSON))
        greeting = b""
        while not greeting.endswith(b"\x00"):
            chunk = self.sock.recv(64)
            if not chunk:
                raise ReQLError(R_CLIENT_ERROR, "handshake EOF")
            greeting += chunk
        if b"SUCCESS" not in greeting:
            raise ReQLError(R_CLIENT_ERROR,
                            greeting.decode(errors="replace"))

    def _read_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ReQLError(R_CLIENT_ERROR,
                                "connection closed by server")
            buf += chunk
        return buf

    def run(self, term, **opts):
        """Run a term; returns the response datum (atom or sequence)."""
        with self.lock:
            self.token += 1
            token = self.token
            q = json.dumps([Q_START, term, opts]).encode()
            self.sock.sendall(struct.pack("<q", token)
                              + struct.pack("<I", len(q)) + q)
            rtoken, = struct.unpack("<q", self._read_exact(8))
            rlen, = struct.unpack("<I", self._read_exact(4))
            resp = json.loads(self._read_exact(rlen))
        if rtoken != token:
            raise ReQLError(R_CLIENT_ERROR,
                            f"token mismatch {rtoken} != {token}")
        t = resp.get("t")
        if t == R_SUCCESS_ATOM:
            return resp["r"][0]
        if t in (R_SUCCESS_SEQUENCE, R_SUCCESS_PARTIAL):
            return resp["r"]
        raise ReQLError(t, "; ".join(map(str, resp.get("r", []))))

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass
