"""Postgres-RDS test suite — a single managed-Postgres endpoint.

Mirrors the reference's postgres-rds suite
(`/root/reference/postgres-rds/src/jepsen/postgres_rds.clj`): there is
no DB automation at all — the system under test is an external managed
instance reached by hostname (`--endpoint`) — and the workload is a
CAS register over one row, read/write/cas in explicit transactions.
Nemeses default to none (you can't partition RDS from here), matching
the reference.

The client reuses the Postgres wire client (`pg_proto.py`)."""

from __future__ import annotations

import logging

from .. import cli, client as jclient, models
from .. import db as jdb
from .. import generator as gen
from ..checker import linear
from . import std_opts, std_test
from .pg_proto import Conn, PGError

log = logging.getLogger(__name__)

PG_PORT = 5432
DEFINITE_ABORT = {"40001", "40P01"}


def _connect(test, node) -> Conn:
    fn = test.get("sql-conn-fn")
    if fn is not None:
        return fn(node)
    host = test.get("endpoint") or node
    return Conn(host, test.get("port", PG_PORT),
                user=test.get("user", "jepsen"),
                database=test.get("database", "jepsen"))


class RegisterClient(jclient.Client):
    """One-row CAS register (`postgres_rds.clj:60-140`)."""

    def __init__(self):
        self.conn: Conn | None = None

    def open(self, test, node):
        c = RegisterClient()
        c.conn = _connect(test, node)
        return c

    def setup(self, test):
        self.conn.query("create table if not exists registers "
                        "(id int primary key, val int)")
        self.conn.query("insert into registers (id, val) values (0, 0) "
                        "on conflict (id) do update set val = val")

    def close(self, test):
        if self.conn is not None:
            self.conn.close()

    def invoke(self, test, op):
        try:
            if op["f"] == "read":
                rows, _ = self.conn.query(
                    "select val from registers where id = 0")
                v = None if not rows or rows[0][0] is None \
                    else int(rows[0][0])
                return {**op, "type": "ok", "value": v}
            self.conn.query("begin")
            try:
                if op["f"] == "write":
                    self.conn.query(f"update registers set "
                                    f"val = {op['value']} where id = 0")
                    self.conn.query("commit")
                    return {**op, "type": "ok"}
                old, new = op["value"]
                rows, _ = self.conn.query(
                    "select val from registers where id = 0")
                cur = None if not rows or rows[0][0] is None \
                    else int(rows[0][0])
                if cur != old:
                    self.conn.query("rollback")
                    return {**op, "type": "fail"}
                self.conn.query(f"update registers set val = {new} "
                                f"where id = 0")
                self.conn.query("commit")
                return {**op, "type": "ok"}
            except Exception:
                try:
                    self.conn.query("rollback")
                except Exception:  # noqa: BLE001 — conn may be dead
                    pass
                raise
        except PGError as e:
            definite = e.code in DEFINITE_ABORT or op["f"] == "read"
            return {**op, "type": "fail" if definite else "info",
                    "error": ["sql", e.code, e.message]}
        except OSError as e:
            return {**op,
                    "type": "fail" if op["f"] == "read" else "info",
                    "error": str(e)}


def register_workload(opts: dict) -> dict:
    def r(test, ctx):
        return {"type": "invoke", "f": "read", "value": None}

    def w(test, ctx):
        return {"type": "invoke", "f": "write",
                "value": gen.rng.randrange(5)}

    def cas(test, ctx):
        return {"type": "invoke", "f": "cas",
                "value": (gen.rng.randrange(5), gen.rng.randrange(5))}

    return {
        "client": RegisterClient(),
        "generator": gen.mix([r, w, cas]),
        "checker": linear.linearizable(models.cas_register(0)),
    }


WORKLOADS = {"register": register_workload}


def postgres_rds_test(opts: dict) -> dict:
    workload_name = opts.get("workload", "register")
    return std_test(
        opts, name=f"postgres-rds-{workload_name}",
        db=jdb.noop, default_faults=(),
        workload=WORKLOADS[workload_name](opts))


OPT_SPEC = std_opts(cli, WORKLOADS, "register") + [
    cli.opt("--endpoint", help="RDS endpoint hostname"),
    cli.opt("--user", default="jepsen", help="database user"),
    cli.opt("--database", default="jepsen", help="database name"),
]


def main(argv=None):
    cli.run({**cli.single_test_cmd({"test_fn": postgres_rds_test,
                                    "opt_spec": OPT_SPEC}),
             **cli.serve_cmd()}, argv)


if __name__ == "__main__":
    main()
