"""Dgraph suite (`dgraph/src/jepsen/dgraph/`, 2,599 LoC) — a
distributed graph database offering snapshot isolation (and, with
server-side ordering, linearizability).

The reference drives dgraph through the JVM gRPC driver
(`client.clj:52-81`); this client speaks dgraph's HTTP API on the
alpha instead (the same transactional surface: /alter, /query,
/mutate, /commit with start-ts snapshot reads and commit-time
conflict detection), so no driver or grpc stack is needed.

**Tracing is first-class here**, as in the reference: every client
call runs inside a `jepsen_tpu.trace` span (`client.clj` wraps each
call in `with-trace`; `trace.clj:40-49`), and the bank workload
annotates spans with checker violations found *during the run*
(`bank.clj:155-168`). Configure with the test's "tracing" option — a
file path or Jaeger HTTP endpoint; spans land in the store dir by
default when "tracing" is true.

Workloads: bank, upsert, delete, set, uid-set, sequential,
linearizable-register, uid-linearizable-register, long-fork, wr.
Nemeses: alpha/zero killers, alpha fixer, tablet mover, clock bump,
partitions (`nemesis.clj`).
"""

from __future__ import annotations

import http.client
import itertools
import json
import re
import socket
import threading
import time as _time

from .. import checker, cli, client as jclient, control, db as jdb
from .. import generator as gen, independent, trace
from ..checker import timeline
from ..nemesis import (Nemesis, compose as n_compose, f_map as n_fmap,
                       node_start_stopper)
from ..nemesis import combined as ncomb
from ..nemesis import partition as npart
from ..nemesis import time as ntime
from ..os_ import debian
from ..plot import merged_windows  # window algebra for spot plots
from ..workloads import linearizable_register as lr
from ..workloads import long_fork, wr as wrw

ALPHA_HTTP_PORT = 8080
ZERO_HTTP_PORT = 6080
DEADLINE_S = 30.0


# ---------------------------------------------------------------------------
# Wire client (`client.clj`)
# ---------------------------------------------------------------------------

class DgraphError(Exception):
    """An error from dgraph's HTTP API (message from the errors
    array)."""

    def __init__(self, message: str, status: int = 400):
        super().__init__(message)
        self.message = message
        self.status = status


# with-conflict-as-fail's message->completion table
# (`client.clj:143-245`): first match wins; type 'fail' is definite,
# 'info' indeterminate.
ERROR_TABLE: tuple[tuple[str, str, str], ...] = (
    (r"DEADLINE_EXCEEDED", "info", "timeout-deadline-exceeded"),
    (r"context deadline exceeded", "info",
     "timeout-context-deadline-exceeded"),
    (r"Conflicts with pending transaction\. Please abort\.", "fail",
     "conflict"),
    (r"Transaction has been aborted\. Please retry", "fail", "conflict"),
    (r"readTs: \d+ less than minTs: \d+ for key", "fail",
     "old-timestamp"),
    (r"StartTs: (\d+) is from before MoveTs: (\d+) for pred", "fail",
     "start-ts-before-move-ts"),
    (r"Predicate is being moved, please retry later", "fail",
     "predicate-moving"),
    (r"Tablet isn't being served by this instance", "fail",
     "tablet-not-served-by-instance"),
    (r"Request sent to wrong server", "fail", "wrong-server"),
    (r"Please retry again, server is not ready to accept requests",
     "fail", "not-ready-for-requests"),
    (r"No connection exists", "fail", "no-connection"),
    (r"all SubConns are in TransientFailure", "info",
     "unavailable-all-subconns-transient-failure"),
    (r"transport is closing", "info", "unavailable-transport-closing"),
    (r"Network closed for unknown reason", "info",
     "unavailable-network-closed-unknown-reason"),
    (r"Unhealthy connection", "info", "unhealthy-connection"),
    (r"Only leader can decide to commit or abort", "fail",
     "only-leader-can-commit"),
    (r"This server doesn't serve group id:", "fail",
     "server-doesn't-serve-group"),
    (r"ABORTED", "fail", "transaction-aborted"),
    (r"Attribute .+ not indexed", "fail", "not-indexed"),
    (r"Schema not defined for predicate", "fail", "schema-not-defined"),
)

# errors worth a backoff before the next op. The reference's
# with-unavailable-backoff (`client.clj:128-137`) guards on :fail,
# which its own table makes unreachable for the unavailable-* and
# unhealthy-connection entries (they classify :info); we back off on
# the error name alone so a down node isn't hammered at full rate.
BACKOFF_ERRORS = frozenset({"predicate-moving", "unhealthy-connection"})


class DgraphConn:
    """One HTTP connection to an alpha (`client.clj:52-81` opens a
    gRPC channel; same lifecycle)."""

    def __init__(self, node: str, port: int = ALPHA_HTTP_PORT,
                 timeout_s: float = DEADLINE_S):
        self.node, self.port = node, port
        self.timeout_s = timeout_s
        self._http = http.client.HTTPConnection(node, port,
                                                timeout=timeout_s)

    def post(self, path: str, body, content_type: str) -> dict:
        data = body if isinstance(body, (bytes, str)) \
            else json.dumps(body)
        if isinstance(data, str):
            data = data.encode()
        try:
            self._http.request("POST", path, body=data,
                               headers={"Content-Type": content_type})
            resp = self._http.getresponse()
            raw = resp.read()
        except Exception:
            self._http.close()   # desynced HTTP pipeline
            raise
        try:
            doc = json.loads(raw)
        except ValueError:
            raise DgraphError(raw.decode(errors="replace"), resp.status)
        if doc.get("errors"):
            raise DgraphError(doc["errors"][0].get("message", ""),
                              resp.status)
        return doc

    def close(self):
        self._http.close()


def open_conn(test: dict, node: str) -> DgraphConn:
    with trace.span("client.open"):
        fn = test.get("dgraph-conn-fn")
        if fn is not None:
            return fn(node)
        return DgraphConn(node)


class Txn:
    """One SI transaction: start-ts assigned by the server on first
    use, reads snapshot at start-ts, writes buffered server-side,
    conflicts detected at /commit (`client.clj:106-126` with-txn)."""

    def __init__(self, conn: DgraphConn):
        self.conn = conn
        self.start_ts: int | None = None
        self.keys: list = []
        self.preds: list = []
        self.finished = False

    def _ts_arg(self) -> str:
        return f"?startTs={self.start_ts}" if self.start_ts else ""

    def _absorb(self, doc: dict) -> None:
        txn = (doc.get("extensions") or {}).get("txn") or {}
        if self.start_ts is None and txn.get("start_ts"):
            self.start_ts = txn["start_ts"]
        self.keys.extend(txn.get("keys") or [])
        self.preds.extend(txn.get("preds") or [])

    def query(self, q: str, vars: dict | None = None) -> dict:
        """graphql+- query; vars are $-prefixed like the reference's
        query-with-vars (`client.clj:350-387`)."""
        with trace.span("client.query"):
            body = {"query": q,
                    "vars": {f"${k}": str(v)
                             for k, v in (vars or {}).items()}}
            doc = self.conn.post(f"/query{self._ts_arg()}", body,
                                 "application/json")
            self._absorb(doc)
            return doc.get("data") or {}

    def mutate(self, set_obj) -> dict:
        """JSON set-mutation; returns map of blank names to UIDs
        (`client.clj:285-296`)."""
        with trace.span("client.mutate"):
            doc = self.conn.post(
                f"/mutate{self._ts_arg()}", {"set": [set_obj]},
                "application/json")
            self._absorb(doc)
            return (doc.get("data") or {}).get("uids") or {}

    def delete(self, target) -> None:
        """Delete by uid string (all edges) or JSON object
        (`client.clj:319-331`)."""
        with trace.span("client.delete"):
            if isinstance(target, str):
                target = {"uid": target}
            doc = self.conn.post(
                f"/mutate{self._ts_arg()}", {"delete": [target]},
                "application/json")
            self._absorb(doc)

    def commit(self) -> None:
        if self.finished or self.start_ts is None:
            self.finished = True
            return
        with trace.span("client.commit"):
            self.finished = True
            self.conn.post(f"/commit?startTs={self.start_ts}",
                           {"keys": self.keys, "preds": self.preds},
                           "application/json")

    def discard(self) -> None:
        if self.finished or self.start_ts is None:
            self.finished = True
            return
        with trace.span("client.abort-txn"):
            self.finished = True
            try:
                self.conn.post(
                    f"/commit?startTs={self.start_ts}&abort=true", {},
                    "application/json")
            except (DgraphError, OSError):
                pass


class txn:  # noqa: N801 — context manager mirroring with-txn
    """with txn(conn) as t: ... — commits on clean exit, discards on
    exception (`client.clj:106-126`)."""

    def __init__(self, conn: DgraphConn):
        self.t = Txn(conn)

    def __enter__(self) -> Txn:
        return self.t

    def __exit__(self, et, ev, tb):
        if et is None:
            self.t.commit()
        else:
            self.t.discard()
        return False


def alter_schema(conn: DgraphConn, *schemata: str, tries: int = 10,
                 sleep_s: float = 0.2) -> None:
    """Idempotent schema alteration with retries
    (`client.clj:264-283`)."""
    with trace.span("client.alter-schema"):
        while True:
            try:
                conn.post("/alter", {"schema": "\n".join(schemata)},
                          "application/json")
                return
            except (DgraphError, ConnectionError, OSError):
                tries -= 1
                if tries <= 0:
                    raise
                _time.sleep(sleep_s)


def with_conflict_as_fail(op: dict, thunk, test: dict | None = None):
    """Evaluate thunk, classifying dgraph/network failures
    (`client.clj:143-245`), with the unavailable backoff
    (`client.clj:128-137`)."""
    pause = (test or {}).get("dgraph-conn-retry-delay", 1.0)
    try:
        out = thunk()
    except ConnectionRefusedError as e:
        _time.sleep(pause)
        out = {**op, "type": "fail", "error": "connection-refused"}
    except (socket.timeout, TimeoutError) as e:
        out = {**op, "type": "info", "error": ["timeout", str(e)]}
    except (ConnectionError, OSError) as e:
        msg = str(e)
        if "Connection refused" in msg:
            _time.sleep(pause)
            out = {**op, "type": "fail", "error": "connection-refused"}
        elif "Connection reset" in msg:
            out = {**op, "type": "info", "error": "connection-reset"}
        else:
            out = {**op, "type": "info", "error": ["io", msg]}
    except DgraphError as e:
        for pat, typ, name in ERROR_TABLE:
            if re.search(pat, e.message):
                out = {**op, "type": typ, "error": name}
                break
        else:
            raise
    err = out.get("error")
    if isinstance(err, str) and (err in BACKOFF_ERRORS
                                 or err.startswith("unavailable")):
        _time.sleep(gen.rng.random() * 2 * pause)
    return out


def retry_conflicts(thunk, attempts: int = 10, sleep_s: float = 0.1):
    """Retry a transaction body on conflict aborts
    (`client.clj:247-258` retry-conflicts)."""
    while True:
        try:
            return thunk()
        except DgraphError as e:
            attempts -= 1
            if attempts <= 0 or not re.search(
                    r"abort|Abort|ABORTED|Conflicts", e.message):
                raise
            _time.sleep(gen.rng.random() * sleep_s)


def upsert(t: Txn, pred: str, record: dict):
    """Query-then-insert-or-update upsert on a predicate
    (`client.clj:424-455`). Returns the mutation's uid map, or None
    when a matching record already exists and was updated in place."""
    with trace.span("client.upsert"):
        value = record[pred]
        res = t.query(
            "{ all(func: eq(" + pred + ", $a)) { uid } }", {"a": value})
        matches = res.get("all") or []
        if len(matches) == 0:
            return t.mutate(record)
        if len(matches) == 1:
            t.mutate({**record, "uid": matches[0]["uid"]})
            return None
        raise DgraphError(
            f"unexpected multiple results for upsert of {pred}")


def gen_pred(prefix: str, n: int, k) -> str:
    """Stripe keys over n predicates (`client.clj:457-467`)."""
    return f"{prefix}_{hash(k) % n}"


def gen_preds(prefix: str, n: int) -> list[str]:
    return [f"{prefix}_{i}" for i in range(n)]


class _DgraphClient(jclient.Client):
    def __init__(self):
        self.conn: DgraphConn | None = None

    def open(self, test, node):
        c = type(self).__new__(type(self))
        c.__dict__.update(self.__dict__)
        c.conn = open_conn(test, node)
        return c

    def close(self, test):
        if self.conn is not None:
            with trace.span("client.close"):
                self.conn.close()


# ---------------------------------------------------------------------------
# Generic transactional client (`client.clj:469-571` TxnClient)
# ---------------------------------------------------------------------------

class TxnClient(_DgraphClient):
    """Executes [f k v] micro-op transactions over striped key/value
    predicates — the client behind the wr and long-fork workloads."""

    def __init__(self, key_preds: int = 5, val_preds: int = 5,
                 blind_insert: bool = False):
        super().__init__()
        self.key_preds = key_preds
        self.val_preds = val_preds
        self.blind_insert = blind_insert

    def setup(self, test):
        ks = [f"{p}: int @index(int)"
              + (" @upsert" if test.get("upsert-schema") else "") + " ."
              for p in gen_preds("key", self.key_preds)]
        vs = [f"{p}: int ." for p in gen_preds("val", self.val_preds)]
        alter_schema(self.conn, *(ks + vs))

    def _mop(self, t: Txn, mop):
        f, k, v = mop
        kp = gen_pred("key", self.key_preds, k)
        vp = gen_pred("val", self.val_preds, k)
        if f == "r":
            reads = t.query(
                "{ q(func: eq(" + kp + ", $key)) { " + vp + " } }",
                {"key": k}).get("q") or []
            if len(reads) > 1:
                raise DgraphError(
                    f"unexpected multiple results for key {k}")
            return [f, k, int(reads[0][vp]) if reads
                    and reads[0].get(vp) is not None else None]
        if self.blind_insert:
            t.mutate({kp: k, vp: v})
        else:
            upsert(t, kp, {kp: k, vp: v})
        return list(mop)

    def invoke(self, test, op):
        def body():
            with txn(self.conn) as t:
                out = [self._mop(t, m) for m in op["value"]]
            return {**op, "type": "ok", "value": out}
        return with_conflict_as_fail(op, body, test)


# ---------------------------------------------------------------------------
# bank (`bank.clj`)
# ---------------------------------------------------------------------------

BANK_PREDS = 7


class BankClient(_DgraphClient):
    """Accounts striped across key/amount/type predicate families;
    every client call traced, checker violations annotated onto the
    live span (`bank.clj:104-199`)."""

    def setup(self, test):
        with trace.span("bank.setup"):
            schemata = (
                [f"{p}: int @index(int)"
                 + (" @upsert" if test.get("upsert-schema") else "")
                 + " ." for p in gen_preds("key", BANK_PREDS)]
                + [f"{p}: string @index(exact) ."
                   for p in gen_preds("type", BANK_PREDS)]
                + [f"{p}: int ." for p in gen_preds("amount", BANK_PREDS)])
            alter_schema(self.conn, *schemata)
            k = test.get("accounts", list(range(8)))[0]
            kp = gen_pred("key", BANK_PREDS, k)

            def seed():
                with txn(self.conn) as t:
                    upsert(t, kp, {
                        kp: k,
                        gen_pred("type", BANK_PREDS, k): "account",
                        gen_pred("amount", BANK_PREDS, k):
                            test.get("total-amount", 100)})
            # all clients race to seed the first account
            # (`bank.clj:138-147` retry-conflicts)
            retry_conflicts(seed)

    def _read_accounts(self, t: Txn) -> dict:
        """All accounts across every type predicate
        (`bank.clj:36-58`)."""
        with trace.span("read-accounts"):
            fields = " ".join(gen_preds("key", BANK_PREDS)
                              + gen_preds("amount", BANK_PREDS))
            out = {}
            for tp in gen_preds("type", BANK_PREDS):
                rows = t.query(
                    "{ q(func: eq(" + tp + ", $type)) { " + fields
                    + " } }", {"type": "account"}).get("q") or []
                for r in rows:
                    key = amount = None
                    for pred, v in r.items():
                        if pred.startswith("key_"):
                            key = v
                        elif pred.startswith("amount_"):
                            amount = v
                    out[key] = amount
            return dict(sorted(out.items()))

    def _find_account(self, t: Txn, k) -> dict:
        with trace.span("find-account"):
            kp = gen_pred("key", BANK_PREDS, k)
            ap = gen_pred("amount", BANK_PREDS, k)
            rows = t.query(
                "{ q(func: eq(" + kp + ", $key)) { uid " + kp + " "
                + ap + " } }", {"key": k}).get("q") or []
            if rows:
                r = rows[0]
                return {"uid": r["uid"], "key": r.get(kp),
                        "amount": r.get(ap)}
            return {"key": k, "type": "account", "amount": 0}

    def _write_account(self, t: Txn, account: dict) -> None:
        with trace.span("write-account"):
            k = account["key"]
            kp = gen_pred("key", BANK_PREDS, k)
            ap = gen_pred("amount", BANK_PREDS, k)
            tp = gen_pred("type", BANK_PREDS, k)
            if account["amount"] == 0 and account.get("uid"):
                t.delete({"uid": account["uid"],
                          kp: None, ap: None, tp: None})
            else:
                rec = {tp: "account", kp: k, ap: account["amount"]}
                if account.get("uid"):
                    rec["uid"] = account["uid"]
                t.mutate(rec)

    def invoke(self, test, op):
        with trace.span("bank.invoke"):
            def body():
                with txn(self.conn) as t:
                    if op["f"] == "read":
                        with trace.span("bank.invoke.read"):
                            out = {**op, "type": "ok",
                                   "value": self._read_accounts(t)}
                            from ..workloads import bank as bankw
                            err = bankw.check_op(
                                set(test.get("accounts",
                                             list(range(8)))),
                                test.get("total-amount", 100), False,
                                out)
                            if err:
                                # annotate the live span so the trace
                                # carries the violation
                                # (`bank.clj:155-168`)
                                trace.attribute("checker_violation",
                                                "true")
                                msg = {k: v for k, v in err.items()
                                       if k != "op"}
                                msg.update(trace.context())
                                out["message"] = msg
                                out["error"] = "checker-violation"
                            return out
                    with trace.span("bank.invoke.transfer"):
                        v = op["value"]
                        frm = self._find_account(t, v["from"])
                        to = self._find_account(t, v["to"])
                        frm2 = {**frm, "amount": (frm["amount"] or 0)
                                - v["amount"]}
                        to2 = {**to, "amount": (to["amount"] or 0)
                               + v["amount"]}
                        if frm2["amount"] < 0:
                            t.discard()
                            return {**op, "type": "fail",
                                    "error": "insufficient-funds"}
                        self._write_account(t, frm2)
                        self._write_account(t, to2)
                        return {**op, "type": "ok"}
            return with_conflict_as_fail(op, body, test)


def bank_workload(opts: dict) -> dict:
    from ..workloads import bank as bankw
    w = bankw.test()
    return {**w, "client": BankClient()}


# ---------------------------------------------------------------------------
# upsert (`upsert.clj`)
# ---------------------------------------------------------------------------

class UpsertClient(_DgraphClient):
    """At most one upsert per key may succeed (`upsert.clj:13-52`)."""

    def setup(self, test):
        alter_schema(self.conn, "email: string @index(exact)"
                     + (" @upsert" if test.get("upsert-schema", True)
                        else "") + " .")

    def invoke(self, test, op):
        def body():
            k, _ = op["value"]
            with txn(self.conn) as t:
                if op["f"] == "upsert":
                    inserted = upsert(t, "email", {"email": str(k)})
                    if inserted:
                        return {**op, "type": "ok",
                                "value": independent.ktuple(
                                    k, next(iter(inserted.values())))}
                    return {**op, "type": "fail", "error": "present"}
                uids = sorted(
                    r["uid"] for r in (t.query(
                        "{ q(func: eq(email, $email)) { uid } }",
                        {"email": str(k)}).get("q") or []))
                return {**op, "type": "ok",
                        "value": independent.ktuple(k, uids)}
        return with_conflict_as_fail(op, body, test)


class UpsertChecker(checker.Checker):
    """At most one UID ever visible per key (`upsert.clj:54-70`)."""

    def check(self, test, hist, opts):
        reads = [o for o in hist
                 if o.get("type") == "ok" and o.get("f") == "read"]
        upserts = [o for o in hist
                   if o.get("type") == "ok" and o.get("f") == "upsert"]
        bad_reads = [o for o in reads if len(o.get("value") or []) > 1]
        return {"valid?": not bad_reads and len(upserts) <= 1,
                "bad-reads": bad_reads,
                "ok-upserts": len(upserts)}


def upsert_workload(opts: dict) -> dict:
    n = min(int(opts.get("concurrency", 10)),
            2 * (len(opts.get("nodes", [])) or 5))

    def fgen(k):
        return gen.phases(
            gen.each_thread(gen.once({"type": "invoke", "f": "upsert",
                                      "value": None})),
            gen.each_thread(gen.once({"type": "invoke", "f": "read",
                                      "value": None})))

    return {"client": UpsertClient(),
            "checker": independent.checker(UpsertChecker()),
            "generator": independent.concurrent_generator(
                n, itertools.count(), fgen)}


# ---------------------------------------------------------------------------
# delete (`delete.clj`)
# ---------------------------------------------------------------------------

class DeleteClient(_DgraphClient):
    """Create/delete an indexed record; reads must see the index in
    sync (`delete.clj:22-62`)."""

    def setup(self, test):
        alter_schema(self.conn, "key: int @index(int)"
                     + (" @upsert" if test.get("upsert-schema") else "")
                     + " .")

    def invoke(self, test, op):
        def body():
            k, _ = op["value"]
            with txn(self.conn) as t:
                if op["f"] == "read":
                    rows = t.query(
                        "{ q(func: eq(key, $key)) { uid key } }",
                        {"key": k}).get("q") or []
                    return {**op, "type": "ok",
                            "value": independent.ktuple(k, rows)}
                if op["f"] == "upsert":
                    if upsert(t, "key", {"key": k}):
                        return {**op, "type": "ok"}
                    return {**op, "type": "fail", "error": "present"}
                rows = t.query("{ q(func: eq(key, $key)) { uid } }",
                               {"key": k}).get("q") or []
                if not rows:
                    return {**op, "type": "fail", "error": "not-found"}
                t.delete(rows[0]["uid"])
                return {**op, "type": "ok", "uid": rows[0]["uid"]}
        return with_conflict_as_fail(op, body, test)


class DeleteChecker(checker.Checker):
    """Every read finds nothing, or exactly one {uid key} record for
    this key (`delete.clj:64-88`)."""

    def check(self, test, hist, opts):
        k = opts.get("history-key")
        bad = []
        for o in hist:
            if o.get("type") != "ok" or o.get("f") != "read":
                continue
            v = o.get("value") or []
            ok = (len(v) == 0
                  or (len(v) == 1 and set(v[0]) == {"uid", "key"}
                      and (k is None or v[0]["key"] == k)))
            if not ok:
                bad.append(o)
        return {"valid?": not bad, "bad-reads": bad}


def delete_workload(opts: dict) -> dict:
    def r(test, ctx):
        return {"type": "invoke", "f": "read", "value": None}

    def u(test, ctx):
        return {"type": "invoke", "f": "upsert", "value": None}

    def d(test, ctx):
        return {"type": "invoke", "f": "delete", "value": None}

    n = 2 * (len(opts.get("nodes", [])) or 5)

    def fgen(k):
        return gen.stagger(opts.get("delete-stagger", 1 / 10),
                           gen.limit(opts.get("ops-per-key", 1000),
                                     gen.mix([r, u, d])))

    return {"client": DeleteClient(),
            "checker": independent.checker(checker.compose({
                "deletes": DeleteChecker(),
                "timeline": timeline.html()})),
            "generator": independent.concurrent_generator(
                n, itertools.count(), fgen)}


# ---------------------------------------------------------------------------
# set (`set.clj`)
# ---------------------------------------------------------------------------

class SetClient(_DgraphClient):
    """Index-read set (`set.clj:14-46`)."""

    def setup(self, test):
        alter_schema(self.conn,
                     "jepsen-type: string @index(exact)"
                     + (" @upsert" if test.get("upsert-schema") else "")
                     + " .", "value: int .")

    def invoke(self, test, op):
        def body():
            with txn(self.conn) as t:
                if op["f"] == "add":
                    uids = t.mutate({"jepsen-type": "element",
                                     "value": op["value"]})
                    return {**op, "type": "ok",
                            "uid": next(iter(uids.values()), None)}
                rows = t.query(
                    '{ q(func: eq(jepsen-type, $type)) { uid value } }',
                    {"type": "element"}).get("q") or []
                return {**op, "type": "ok",
                        "value": sorted(r["value"] for r in rows)}
        return with_conflict_as_fail(op, body, test)


class UidSetClient(_DgraphClient):
    """Set variant storing every value on one UID, no indices
    (`set.clj:61-105`); adds annotate their value onto the span."""

    def __init__(self):
        super().__init__()
        self.uid_box: dict = {}
        self.lock = threading.Lock()

    def setup(self, test):
        alter_schema(self.conn, "value: [int] .")
        with txn(self.conn) as t:
            uids = t.mutate({"value": -1})
        with self.lock:
            self.uid_box.setdefault("uid",
                                    next(iter(uids.values())))

    def invoke(self, test, op):
        def body():
            uid = self.uid_box.get("uid")
            if op["f"] == "add":
                with trace.span("set-add"):
                    trace.attribute("value", str(op["value"]))
                    with txn(self.conn) as t:
                        t.mutate({"uid": uid, "value": op["value"]})
                    return {**op, "type": "ok", "uid": uid}
            with txn(self.conn) as t:
                rows = t.query("{ q(func: uid($u)) { uid value } }",
                               {"u": uid}).get("q") or []
            vals = sorted({v for r in rows
                           for v in (r.get("value") or []
                                     if isinstance(r.get("value"), list)
                                     else [r.get("value")])
                           if v is not None and v != -1})
            return {**op, "type": "ok", "value": vals}
        return with_conflict_as_fail(op, body, test)


def set_workload(opts: dict) -> dict:
    adds = gen.IterGen({"type": "invoke", "f": "add", "value": i}
                       for i in itertools.count())
    return {
        "client": SetClient(),
        "checker": checker.set_checker(),
        "generator": gen.stagger(opts.get("set-stagger", 1 / 10), adds),
        "final-generator": gen.each_thread(gen.once(
            {"type": "invoke", "f": "read", "value": None})),
    }


def uid_set_workload(opts: dict) -> dict:
    return {**set_workload(opts), "client": UidSetClient()}


# ---------------------------------------------------------------------------
# sequential (`sequential.clj`)
# ---------------------------------------------------------------------------

class SequentialClient(_DgraphClient):
    """Read-only and read-inc-write txns on keyed registers
    (`sequential.clj:66-103`)."""

    def setup(self, test):
        alter_schema(self.conn, "key: int @index(int)"
                     + (" @upsert" if test.get("upsert-schema") else "")
                     + " .", "value: int @index(int) .")

    def invoke(self, test, op):
        def body():
            k, _ = op["value"]
            with txn(self.conn) as t:
                rows = t.query(
                    "{ q(func: eq(key, $key)) { uid value } }",
                    {"key": k}).get("q") or []
                row = rows[0] if rows else None
                if op["f"] == "inc":
                    value = (row.get("value") if row else 0) or 0
                    value += 1
                    if row:
                        t.mutate({"uid": row["uid"], "value": value})
                    else:
                        t.mutate({"key": k, "value": value})
                    return {**op, "type": "ok",
                            "value": independent.ktuple(k, value)}
                return {**op, "type": "ok",
                        "value": independent.ktuple(
                            k, (row.get("value") if row else 0) or 0)}
        return with_conflict_as_fail(op, body, test)


class SequentialChecker(checker.Checker):
    """Per-process monotonicity of the register value
    (`sequential.clj:105-136`)."""

    def check(self, test, hist, opts):
        last: dict = {}
        errs = []
        for o in hist:
            if o.get("type") != "ok":
                continue
            p = o.get("process")
            v = o.get("value") or 0
            pv = (last.get(p) or {}).get("value") or 0
            if v < pv:
                errs.append([last[p], o])
            last[p] = o
        return {"valid?": not errs, "non-monotonic": errs}




class SequentialPlotter(checker.Checker):
    """SVG per-process value plots around non-monotonic spots
    (`sequential.clj:160-215`; gnuplot in the reference, our plot
    library renders SVG into the store dir)."""

    def check(self, test, hist, opts):
        from ..checker.perf import out_path
        from ..plot import (Plot, process_series, regression_spots,
                            write as plot_write)

        ops = [o for o in hist
               if o.get("type") == "ok" and o.get("value") is not None]
        # spots mirror SequentialChecker: per-process regressions
        spots = regression_spots(
            [(o.get("process"), o.get("value") or 0) for o in ops])
        if spots and test.get("store-dir"):
            # per-key filenames: this runs under independent.checker,
            # where every key shares the test's store dir
            k = (opts or {}).get("history-key")
            tag = "" if k is None else f"key-{k}-"
            for wi, (lo, hi) in enumerate(merged_windows(32, spots)):
                window = ops[max(lo, 0):min(hi + 1, len(ops))]
                by_process: dict = {}
                for o in window:
                    by_process.setdefault(o.get("process"), []).append(
                        (o.get("time", 0) / 1e9, o.get("value") or 0))
                p = Plot(title=f"{test.get('name', '')} sequential "
                               f"by process",
                         ylabel="register value",
                         series=process_series(by_process))
                try:
                    plot_write(p, out_path(
                        test, opts, f"sequential-{tag}{wi}.svg"))
                except Exception:  # noqa: BLE001 — plots are best-effort
                    pass
        return {"valid?": True}


def sequential_workload(opts: dict) -> dict:
    def inc_gen(test, ctx):
        return {"type": "invoke", "f": "inc",
                "value": independent.ktuple(gen.rng.randrange(8), None)}

    def read_gen(test, ctx):
        return {"type": "invoke", "f": "read",
                "value": independent.ktuple(gen.rng.randrange(8), None)}

    return {"client": SequentialClient(),
            "checker": independent.checker(checker.compose({
                "sequential": SequentialChecker(),
                "plot": SequentialPlotter(),
                "timeline": timeline.html()})),
            "generator": gen.mix([inc_gen, read_gen])}


# ---------------------------------------------------------------------------
# linearizable register (`linearizable_register.clj`)
# ---------------------------------------------------------------------------

def _read_info_to_fail(out: dict) -> dict:
    """Read timeouts are safe failures — reads are idempotent
    (`linearizable_register.clj:26-33`)."""
    if out.get("f") == "read" and out.get("type") == "info":
        return {**out, "type": "fail"}
    return out


class LinearizableRegisterClient(_DgraphClient):
    """Single-predicate linearizable read/write/cas
    (`linearizable_register.clj:35-72`)."""

    def setup(self, test):
        alter_schema(self.conn, "key: int @index(int)"
                     + (" @upsert" if test.get("upsert-schema") else "")
                     + " .", "value: int .")

    def _read(self, t: Txn, k):
        rows = t.query("{ q(func: eq(key, $key)) { uid value } }",
                       {"key": k}).get("q") or []
        if len(rows) > 1:
            raise DgraphError(
                f"expected at most one record for key {k}")
        return rows[0] if rows else None

    def invoke(self, test, op):
        def body():
            k, v = op["value"]
            with txn(self.conn) as t:
                if op["f"] == "read":
                    row = self._read(t, k)
                    return {**op, "type": "ok",
                            "value": independent.ktuple(
                                k, row.get("value") if row else None)}
                if op["f"] == "write":
                    row = self._read(t, k)
                    if row:
                        t.mutate({"uid": row["uid"], "value": v})
                    else:
                        t.mutate({"key": k, "value": v})
                    return {**op, "type": "ok"}
                expected, new = v
                row = self._read(t, k)
                if row and row.get("value") == expected:
                    t.mutate({"uid": row["uid"], "value": new})
                    return {**op, "type": "ok"}
                t.discard()
                return {**op, "type": "fail", "error": "value-mismatch"}
        return _read_info_to_fail(with_conflict_as_fail(op, body, test))


class UidRegisterClient(LinearizableRegisterClient):
    """Variant addressing registers by UID to avoid @upsert-schema
    linearization points (`linearizable_register.clj:81-160`)."""

    def __init__(self):
        super().__init__()
        self.uids: dict = {}
        self.lock = threading.Lock()

    def setup(self, test):
        alter_schema(self.conn, "value: int .")

    def _uid_read(self, t: Txn, k):
        u = self.uids.get(k)
        if u is None:
            return None
        rows = t.query("{ q(func: uid($u)) { uid value } }",
                       {"u": u}).get("q") or []
        return rows[0] if rows else None

    def invoke(self, test, op):
        def body():
            k, v = op["value"]
            with txn(self.conn) as t:
                if op["f"] == "read":
                    row = self._uid_read(t, k)
                    return {**op, "type": "ok",
                            "value": independent.ktuple(
                                k, row.get("value") if row else None)}
                if op["f"] == "write":
                    u = self.uids.get(k)
                    if u is not None:
                        t.mutate({"uid": u, "value": v})
                        return {**op, "type": "ok"}
                    u = next(iter(t.mutate({"value": v}).values()))
                    with self.lock:
                        winner = self.uids.setdefault(k, u)
                    if winner == u:
                        return {**op, "type": "ok"}
                    return {**op, "type": "fail",
                            "error": "lost-uid-race"}
                expected, new = v
                row = self._uid_read(t, k)
                if row and row.get("value") == expected:
                    t.mutate({"uid": row["uid"], "value": new})
                    return {**op, "type": "ok"}
                t.discard()
                return {**op, "type": "fail", "error": "value-mismatch"}
        return _read_info_to_fail(with_conflict_as_fail(op, body, test))


def linearizable_register_workload(opts: dict) -> dict:
    w = lr.test(opts)
    return {**w, "client": LinearizableRegisterClient(),
            "generator": gen.stagger(1 / 100, w["generator"])}


def uid_linearizable_register_workload(opts: dict) -> dict:
    w = lr.test(opts)
    return {**w, "client": UidRegisterClient(),
            "generator": gen.stagger(1 / 100, w["generator"])}


# ---------------------------------------------------------------------------
# long-fork + wr (`long_fork.clj`, `wr.clj`)
# ---------------------------------------------------------------------------

def long_fork_workload(opts: dict) -> dict:
    w = long_fork.workload(n=2)
    return {**w, "client": TxnClient()}


def wr_workload(opts: dict) -> dict:
    """Elle rw-register over the generic txn client. Dgraph offers
    snapshot isolation, so G2-item (write skew) is permitted — the
    anomaly set is the reference's `[:G0 :G1c :G-single :G1a :G1b
    :internal]` (`wr.clj:22-26`), i.e. everything up to SI — with the
    realtime precedence graph unioned into the cycle search
    (`wr.clj:26` `:additional-graphs [cycle/realtime-graph]`)."""
    w = wrw.workload({"anomalies": ("G0", "G1", "G-single"),
                      "key-count": 4, "min-txn-length": 2,
                      "max-txn-length": 4, "max-writes-per-key": 16,
                      "additional-graphs": ("realtime",)})
    return {**w, "client": TxnClient()}


# ---------------------------------------------------------------------------
# types (`types.clj`)
# ---------------------------------------------------------------------------

def _type_cases() -> list[tuple[str, int]]:
    """[attribute, value] probes around integer-width boundaries
    (`types.clj:133-158`): byte/short/int/long maxima, the largest
    exactly-float- and double-representable integers, and values well
    outside signed 64-bit range."""
    points = [0, 2**7 - 1, 2**15 - 1, 2**31 - 1, 2**63 - 1,
              16777217, 9007199254740993, 3 * (2**63 - 1)]
    vals: list[int] = []
    for x in points:
        vals.extend(range(x - 8, x + 8))
        vals.extend(range(-x - 8, -x + 8))
    return [(a, v) for a in ("foo", "int64") for v in vals]


class TypesClient(_DgraphClient):
    """Writes boundary integers as fresh entities, then reads them
    back by uid (`types.clj:24-57`)."""

    def __init__(self):
        super().__init__()
        self.entities: list = []
        self.lock = threading.Lock()

    def setup(self, test):
        alter_schema(self.conn, "key: int @index(int) .",
                     "int64: int .", "foo: int .")

    def invoke(self, test, op):
        def body():
            e, a, v = op["value"]
            with txn(self.conn) as t:
                if op["f"] == "write":
                    uids = t.mutate({a: v})
                    uid = next(iter(uids.values()))
                    with self.lock:
                        self.entities.append(uid)
                    return {**op, "type": "ok", "value": [uid, a, v]}
                rows = t.query("{ q(func: uid($entity)) { " + a + " } }",
                               {"entity": e}).get("q") or []
                got = rows[0].get(a) if rows else None
                return {**op, "type": "ok", "value": [e, a, got]}
        return with_conflict_as_fail(op, body, test)


class TypesChecker(checker.Checker):
    """Everything written must read back bit-identical
    (`types.clj:59-125`); written-but-never-read entities degrade the
    verdict to unknown."""

    def check(self, test, hist, opts):
        state: dict = {}
        for o in hist:
            if o.get("type") == "ok" and o.get("f") == "write":
                e, a, v = o["value"]
                state[(e, a)] = v
        read_keys = set()
        errs = []
        for o in hist:
            if o.get("type") != "ok" or o.get("f") != "read":
                continue
            e, a, v = o["value"]
            read_keys.add((e, a))
            if (e, a) in state and v != state[(e, a)]:
                errs.append({"entity": e, "attribute": a,
                             "wrote": state[(e, a)], "read": v})
        unread = sorted(k for k in state if k not in read_keys)
        # distinct errors, preserving order
        seen = set()
        distinct = []
        for err in errs:
            key = (err["entity"], err["attribute"], str(err["wrote"]),
                   str(err["read"]))
            if key not in seen:
                seen.add(key)
                distinct.append(err)
        return {"valid?": (False if errs else
                           "unknown" if unread else True),
                "error-count": len(distinct),
                "bad-read-count": len(errs),   # raw, pre-dedup (3x reads)
                "unread-count": len(unread),
                "errors": distinct,
                "unread": unread[:16]}


TYPES_STAGGER_DEFAULT = 1 / 10
TYPES_SETTLE_DEFAULT = 10.0


def types_workload(opts: dict) -> dict:
    client = TypesClient()
    cases = _type_cases()
    if opts.get("type-cases"):
        # sample evenly (ceil stride, no truncation) so shortened runs
        # still hit the 2^53+ tail for both attributes
        stride = -(-len(cases) // opts["type-cases"])
        cases = cases[::stride]
    writes = gen.IterGen(
        {"type": "invoke", "f": "write", "value": [None, a, v]}
        for a, v in cases)

    def reads(test, ctx):
        attrs = sorted({a for a, _ in cases})
        with client.lock:
            ents = list(client.entities)
        ops = [{"type": "invoke", "f": "read", "value": [e, a, None]}
               for _ in range(3) for e in ents for a in attrs]
        gen.rng.shuffle(ops)
        return gen.stagger(opts.get("types-stagger", TYPES_STAGGER_DEFAULT),
                           gen.IterGen(iter(ops)))

    return {"client": client,
            "checker": TypesChecker(),
            "generator": gen.phases(
                gen.stagger(opts.get("types-stagger", TYPES_STAGGER_DEFAULT), writes),
                gen.sleep(opts.get("types-settle", TYPES_SETTLE_DEFAULT)),
                gen.derefer(reads))}


# ---------------------------------------------------------------------------
# Support: zero/alpha daemons (`support.clj`)
# ---------------------------------------------------------------------------

DGRAPH_DIR = "/opt/dgraph"
ALPHA_PIDFILE = f"{DGRAPH_DIR}/alpha.pid"
ZERO_PIDFILE = f"{DGRAPH_DIR}/zero.pid"
ALPHA_LOG = f"{DGRAPH_DIR}/alpha.log"
ZERO_LOG = f"{DGRAPH_DIR}/zero.log"


class DgraphDB(jdb.DB, jdb.Process, jdb.LogFiles):
    """Install the dgraph binary, run zero + alpha daemons
    (`support.clj:40-248`)."""

    def __init__(self, version: str = "1.0.11"):
        self.version = version

    def _url(self) -> str:
        return (f"https://github.com/dgraph-io/dgraph/releases/download/"
                f"v{self.version}/dgraph-linux-amd64.tar.gz")

    def setup(self, test, node):
        from ..control import util as cu
        from .. import core
        debian.install(["curl", "tar"])
        with control.su():
            cu.install_archive(self._url(), DGRAPH_DIR)
            idx = test["nodes"].index(node) + 1
            zero0 = test["nodes"][0]
            self.start_zero(test, node, idx=idx, peer=zero0)
            core.synchronize(test)
            self.start_alpha(test, node, zero=zero0)

    def start_zero(self, test, node, idx: int = 1, peer: str | None = None):
        from ..control import util as cu
        args = ["--idx", str(idx), "--my", f"{node}:5080",
                "--replicas", str(test.get("replicas", 3))]
        if peer and peer != node:
            args += ["--peer", f"{peer}:5080"]
        cu.start_daemon({"logfile": ZERO_LOG, "pidfile": ZERO_PIDFILE,
                         "chdir": DGRAPH_DIR},
                        f"{DGRAPH_DIR}/dgraph", "zero", *args)

    def start_alpha(self, test, node, zero: str | None = None) -> str:
        from ..control import util as cu
        return cu.start_daemon(
            {"logfile": ALPHA_LOG, "pidfile": ALPHA_PIDFILE,
             "chdir": DGRAPH_DIR},
            f"{DGRAPH_DIR}/dgraph",
            "alpha" if self.version >= "1.1" else "server",
            "--my", f"{node}:7080",
            "--zero", f"{zero or node}:5080")

    def stop_alpha(self, test, node):
        from ..control import util as cu
        cu.stop_daemon(ALPHA_PIDFILE)

    def stop_zero(self, test, node):
        from ..control import util as cu
        cu.stop_daemon(ZERO_PIDFILE)

    def start(self, test, node):
        # rejoin the existing zero cluster with this node's raft id —
        # setup-time defaults here would duplicate nodes[0]'s id
        self.start_zero(test, node, idx=test["nodes"].index(node) + 1,
                        peer=test["nodes"][0])
        self.start_alpha(test, node, zero=test["nodes"][0])

    def kill(self, test, node):
        self.stop_alpha(test, node)
        self.stop_zero(test, node)

    def teardown(self, test, node):
        self.kill(test, node)
        with control.su():
            control.exec_("rm", "-rf", DGRAPH_DIR)

    def log_files(self, test, node):
        return [ALPHA_LOG, ZERO_LOG]


# -- zero cluster state (`support.clj` zero-state / move-tablet) -------------

def zero_state(test: dict, node: str):
    """GET /state from a zero: groups, tablets, leader
    (`nemesis.clj:57-63` consumes it)."""
    fn = test.get("dgraph-zero-state-fn")
    if fn is not None:
        return fn(node)
    conn = http.client.HTTPConnection(node, ZERO_HTTP_PORT, timeout=5)
    try:
        conn.request("GET", "/state")
        return json.loads(conn.getresponse().read())
    finally:
        conn.close()


def move_tablet(test: dict, node: str, pred: str, group: int) -> None:
    fn = test.get("dgraph-move-tablet-fn")
    if fn is not None:
        return fn(node, pred, group)
    conn = http.client.HTTPConnection(node, ZERO_HTTP_PORT, timeout=5)
    try:
        conn.request("GET", f"/moveTablet?tablet={pred}&group={group}")
        conn.getresponse().read()
    finally:
        conn.close()


# ---------------------------------------------------------------------------
# Nemesis (`nemesis.clj`)
# ---------------------------------------------------------------------------

def alpha_killer() -> Nemesis:
    """Kill/restart alpha on all nodes (`nemesis.clj:17-23`)."""
    return node_start_stopper(
        lambda test, nodes: nodes,
        lambda test, node: test["db"].stop_alpha(test, node) or "killed",
        lambda test, node: test["db"].start_alpha(
            test, node, zero=test["nodes"][0]) or "restarted")


def zero_killer() -> Nemesis:
    """Kill/restart zero on a random subset (`nemesis.clj:43-49`)."""
    return node_start_stopper(
        lambda test, nodes: ncomb.random_nonempty_subset(nodes),
        lambda test, node: test["db"].stop_zero(test, node) or "killed",
        lambda test, node: test["db"].start_zero(
            test, node, idx=test["nodes"].index(node) + 1,
            peer=test["nodes"][0]) or "restarted")


class AlphaFixer(Nemesis):
    """Speculative alpha restarts — alpha falls over when zero is
    missing at startup (`nemesis.clj:25-41`). start-stop-daemon
    reports whether alpha was actually down, so already-running nodes
    are recorded as such, as the reference does."""

    def fs(self):
        return {"fix-alpha"}

    def invoke(self, test, op):
        def fix(t, node):
            res = test["db"].start_alpha(t, node, zero=test["nodes"][0])
            return "restarted" if res == "started" else res
        nodes = ncomb.random_nonempty_subset(test["nodes"])
        return {**op, "value": control.on_nodes(test, fix, nodes)}


class TabletMover(Nemesis):
    """Shuffle tablets between groups via zero (`nemesis.clj:51-102`)."""

    def fs(self):
        return {"move-tablet"}

    def invoke(self, test, op):
        node = test["nodes"][gen.rng.randrange(len(test["nodes"]))]
        try:
            state = zero_state(test, node)
        except (OSError, ValueError):
            return {**op, "value": "timeout"}
        if not isinstance(state, dict):
            return {**op, "value": "timeout"}
        groups = list((state.get("groups") or {}).keys())
        moved = {}
        for gid, ginfo in (state.get("groups") or {}).items():
            for pred, tablet in (ginfo.get("tablets") or {}).items():
                if not groups:
                    continue
                target = groups[gen.rng.randrange(len(groups))]
                if target != gid:
                    try:
                        move_tablet(test, node, pred, int(target))
                        moved[pred] = [gid, target]
                    except (OSError, ValueError):
                        pass
        return {**op, "value": moved}


class BumpTime(Nemesis):
    """Bump clocks on random subsets by dt ms; reset heals
    (`nemesis.clj:104-140`)."""

    def __init__(self, dt_ms: int = 15_000):
        self.dt_ms = dt_ms

    def fs(self):
        return {"bump", "reset-time"}

    def invoke(self, test, op):
        if op["f"] == "bump":
            nodes = ncomb.random_nonempty_subset(test["nodes"])

            def bump(t, node):
                return ntime.bump_time(self.dt_ms)
            return {**op, "value": control.on_nodes(test, bump, nodes)}

        def reset(t, node):
            ntime.reset_time()
            return "reset"
        return {**op, "value": control.on_nodes(test, reset,
                                                list(test["nodes"]))}


NEMESIS_SPECS = frozenset({
    "kill-alpha", "kill-zero", "fix-alpha", "partition-halves",
    "partition-ring", "move-tablet", "skew-clock"})


def dgraph_nemesis_package(opts: dict) -> dict:
    """Composed nemesis + generator for the enabled specs
    (`nemesis.clj:142-202`)."""
    nemeses = []
    gens: list = []
    interval = opts.get("interval", 10)

    def _op(f):
        return {"type": "info", "f": f, "value": None}

    # a bare op dict is a ONE-SHOT generator: recurring fault streams
    # must cycle their op pairs (the yugabyte _role_gen pattern), else
    # each fault fires once and the rest of the run is fault-free
    if opts.get("kill-alpha"):
        nemeses.append(n_fmap(
            lambda f: {"start": "stop-alpha",
                       "stop": "start-alpha"}.get(f, f), alpha_killer()))
        gens.append(itertools.cycle([_op("stop-alpha"),
                                     _op("start-alpha")]))
    if opts.get("kill-zero"):
        nemeses.append(n_fmap(
            lambda f: {"start": "stop-zero",
                       "stop": "start-zero"}.get(f, f), zero_killer()))
        gens.append(itertools.cycle([_op("stop-zero"),
                                     _op("start-zero")]))
    if opts.get("fix-alpha"):
        nemeses.append(AlphaFixer())
        gens.append(itertools.cycle([_op("fix-alpha")]))
    if opts.get("partition-halves") or opts.get("partition-ring"):
        nemeses.append(n_fmap(
            lambda f: {"start": "start-partition",
                       "stop": "stop-partition"}.get(f, f),
            npart.partitioner()))
        if opts.get("partition-halves"):
            def halves(test, ctx):
                nodes = list(test["nodes"])
                gen.rng.shuffle(nodes)
                return {"type": "info", "f": "start-partition",
                        "value": npart.complete_grudge(
                            npart.bisect(nodes))}
            gens += [halves, itertools.cycle([_op("stop-partition")])]
        if opts.get("partition-ring"):
            def ring(test, ctx):
                return {"type": "info", "f": "start-partition",
                        "value": npart.majorities_ring(
                            list(test["nodes"]))}
            gens += [ring, itertools.cycle([_op("stop-partition")])]
    if opts.get("move-tablet"):
        nemeses.append(TabletMover())
        gens.append(itertools.cycle([_op("move-tablet")]))
    if opts.get("skew-clock"):
        nemeses.append(BumpTime())
        gens.append(itertools.cycle([_op("bump"), _op("reset-time")]))
    if not nemeses:
        return ncomb.noop
    finals = []
    if opts.get("partition-halves") or opts.get("partition-ring"):
        finals.append(_op("stop-partition"))
    if opts.get("kill-alpha"):
        finals.append(_op("start-alpha"))
    if opts.get("kill-zero"):
        finals.append(_op("start-zero"))
    if opts.get("skew-clock"):
        finals.append(_op("reset-time"))
    return {"nemesis": n_compose(nemeses),
            "generator": gen.stagger(interval, gen.mix(gens)),
            "final-generator": gen.IterGen(iter(finals)),
            "perf": [{"name": "partition",
                      "start": ["start-partition"],
                      "stop": ["stop-partition"]}]}


# ---------------------------------------------------------------------------
# Runner (`core.clj`)
# ---------------------------------------------------------------------------

WORKLOADS = {
    "bank": bank_workload,
    "upsert": upsert_workload,
    "delete": delete_workload,
    "set": set_workload,
    "uid-set": uid_set_workload,
    "sequential": sequential_workload,
    "linearizable-register": linearizable_register_workload,
    "uid-linearizable-register": uid_linearizable_register_workload,
    "long-fork": long_fork_workload,
    "wr": wr_workload,
    "types": types_workload,
}

# the test-all sweep runs everything but types, as the reference does
# (`core.clj:43-45`); consumed by main()'s test-all command
STANDARD_WORKLOADS = sorted(set(WORKLOADS) - {"types"})

STANDARD_NEMESES = [
    {},
    {"kill-alpha": True, "kill-zero": True, "fix-alpha": True},
    {"partition-halves": True, "partition-ring": True},
    {"move-tablet": True},
    {"skew-clock": True},
]


def dgraph_test(opts: dict) -> dict:
    """Build the full test map (`core.clj:89-140`). "tracing" may be a
    Jaeger HTTP endpoint, a file path, or True (spans land in
    <store-dir>/traces.jsonl)."""
    from .. import testkit

    workload_name = opts.get("workload", "bank")
    time_limit = opts.get("time-limit", opts.get("time_limit", 60))
    nodes = opts.get("nodes") or ["n1", "n2", "n3", "n4", "n5"]
    opts = {**opts, "nodes": nodes}

    endpoint = opts.get("tracing")
    if endpoint is True:
        endpoint = (opts.get("store-dir", "store").rstrip("/")
                    + "/traces.jsonl")
    tracing_cfg = trace.tracing(endpoint or None)

    w = WORKLOADS[workload_name](opts)
    nem_opts = {f: True for f in (opts.get("nemesis") or [])}
    nem_opts["interval"] = opts.get("nemesis-interval", 10)
    pkg = dgraph_nemesis_package(nem_opts)

    rate = float(opts.get("rate", 30))
    client_gen = gen.clients(gen.stagger(1 / rate, w["generator"]))
    main_gen = gen.time_limit(
        time_limit,
        gen.any(client_gen, gen.nemesis(pkg["generator"]))
        if pkg.get("generator") is not None else client_gen)
    phases = [main_gen]
    if pkg.get("final-generator") is not None:
        phases.append(gen.nemesis(pkg["final-generator"]))
    if w.get("final-generator") is not None:
        phases.append(gen.clients(w["final-generator"]))

    return {
        **testkit.noop_test(),
        **{k: v for k, v in opts.items() if isinstance(k, str)},
        "name": f"dgraph {workload_name}",
        "os": debian.os,
        "db": DgraphDB(opts.get("version", "1.0.11")),
        "client": w["client"],
        "nemesis": pkg["nemesis"],
        "plot": {"nemeses": pkg.get("perf")},
        "tracing": tracing_cfg,
        "generator": gen.phases(*phases) if len(phases) > 1 else main_gen,
        "checker": checker.compose({
            "perf": checker.perf_checker(),
            "workload": w["checker"],
            "stats": checker.stats(),
            "exceptions": checker.unhandled_exceptions(),
        }),
    }


OPT_SPEC = [
    cli.opt("--workload", "-w", default="bank",
            choices=sorted(WORKLOADS), help="Which workload to run"),
    cli.opt("--rate", type=float, default=30,
            help="approximate op rate per second"),
    cli.opt("--nemesis", action="append",
            choices=sorted(NEMESIS_SPECS), help="fault types (repeatable)"),
    cli.opt("--nemesis-interval", type=float, default=10,
            help="seconds between nemesis operations"),
    cli.opt("--version", default="1.0.11", help="dgraph version"),
    cli.opt("--replicas", type=int, default=3,
            help="zero --replicas (group size)"),
    cli.opt("--upsert-schema", action="store_true",
            help="add @upsert to indexed predicates"),
    cli.opt("--tracing", default=None,
            help="Jaeger HTTP endpoint or file path for client spans"),
    cli.opt("--type-cases", type=int, default=None,
            help="types: sample this many boundary cases evenly"),
    cli.opt("--types-stagger", type=float,
            default=TYPES_STAGGER_DEFAULT,
            help="types: seconds between ops"),
    cli.opt("--types-settle", type=float, default=TYPES_SETTLE_DEFAULT,
            help="types: seconds between write and read phases"),
]


def _all_tests(opts):
    """One test per standard workload x nemesis set
    (`core.clj:215-231` all-tests)."""
    for nem in STANDARD_NEMESES:
        for w in STANDARD_WORKLOADS:
            yield dgraph_test({**opts, "workload": w,
                               "nemesis": sorted(nem)})


def main(argv=None):
    cli.run({**cli.single_test_cmd({"test_fn": dgraph_test,
                                    "opt_spec": OPT_SPEC}),
             **cli.test_all_cmd({"tests_fn": _all_tests,
                                 "opt_spec": OPT_SPEC}),
             **cli.serve_cmd()}, argv)


if __name__ == "__main__":
    main()
