"""MongoDB test suite — replica-set document CAS.

Mirrors the reference's mongodb suites
(`/root/reference/mongodb-rocks/src/jepsen/mongodb_rocks.clj`,
`mongodb-smartos/src/jepsen/mongodb_smartos/{core,document_cas}.clj`):
deb package install with a replSet config, replica set initiated from
the first node, and the *document CAS* workload — an independent-keyed
linearizable register over documents, reads with linearizable read
concern, writes/CAS with majority write concern via findAndModify —
plus a grow-only set workload over inserts.

The client speaks OP_MSG/BSON directly (`bson_proto.py`); hermetic
tests run against an in-process fake mongod (tests/fake_mongo.py)."""

from __future__ import annotations

import itertools
import logging

from .. import checker, cli, client as jclient, control
from .. import db as jdb
from .. import generator as gen
from .. import independent
from ..control import util as cu
from ..workloads import linearizable_register
from . import std_opts, std_test
from .bson_proto import Conn, MongoError, WriteConcernError

log = logging.getLogger(__name__)

PORT = 27017
CONF = "/etc/mongod.conf"
LOGFILE = "/var/log/mongodb/mongod.log"
REPL_SET = "jepsen"

DEFAULT_VERSION = "4.2.8"

# error codes that mean the write definitely did not commit
DEFINITE_FAIL = {
    11000,  # duplicate key
    112,    # WriteConflict
    10107,  # NotWritablePrimary
    13435,  # NotPrimaryNoSecondaryOk
    211,    # KeyNotFound
}


def config_body(engine: str) -> str:
    return (
        "storage:\n"
        f"  engine: {engine}\n"
        "  dbPath: /var/lib/mongodb\n"
        "systemLog:\n"
        "  destination: file\n"
        f"  path: {LOGFILE}\n"
        "net:\n"
        "  bindIp: 0.0.0.0\n"
        f"  port: {PORT}\n"
        "replication:\n"
        f"  replSetName: {REPL_SET}\n")


class DB(jdb.DB, jdb.Process, jdb.Pause, jdb.LogFiles):
    """mongodb-org-server deb + replSet config; the first node runs
    replSetInitiate over the wire (`mongodb_rocks.clj:29-63`,
    `core.clj` join!)."""

    def __init__(self, version: str = DEFAULT_VERSION,
                 engine: str = "wiredTiger"):
        self.version = version
        self.engine = engine

    def setup(self, test, node):
        with control.su():
            log.info("%s installing mongodb %s (%s)", node,
                     self.version, self.engine)
            deb = test.get("deb") or (
                f"https://repo.mongodb.org/apt/debian/dists/buster/"
                f"mongodb-org/4.2/main/binary-amd64/"
                f"mongodb-org-server_{self.version}_amd64.deb")
            path = cu.cached_wget(deb)
            control.upload(path, "/tmp/mongodb-server.deb")
            control.exec_("dpkg", "-i", "--force-confnew",
                          "/tmp/mongodb-server.deb")
            control.exec_("sh", "-c",
                          f"cat > {CONF} <<'EOF'\n"
                          f"{config_body(self.engine)}EOF")
            control.exec_("mkdir", "-p", "/var/lib/mongodb")
            self.start(test, node)
            cu.await_tcp_port(PORT)
        if node == test["nodes"][0]:
            conn = _connect(test, node)
            try:
                conn.command("admin", {"replSetInitiate": {
                    "_id": REPL_SET,
                    "members": [{"_id": i, "host": f"{n}:{PORT}"}
                                for i, n in enumerate(test["nodes"])],
                }})
            except MongoError as e:
                if "already initialized" not in str(e):
                    raise
            finally:
                conn.close()

    def start(self, test, node):
        with control.su():
            control.exec_("service", "mongod", "start")

    def kill(self, test, node):
        with control.su():
            cu.grepkill("mongod")

    def pause(self, test, node):
        with control.su():
            cu.signal("mongod", "STOP")

    def resume(self, test, node):
        with control.su():
            cu.signal("mongod", "CONT")

    def teardown(self, test, node):
        with control.su():
            self.kill(test, node)
            control.exec_("rm", "-rf", "/var/lib/mongodb", LOGFILE)

    def log_files(self, test, node):
        return [LOGFILE]


def db(version: str = DEFAULT_VERSION,
       engine: str = "wiredTiger") -> DB:
    return DB(version, engine)


def _connect(test, node) -> Conn:
    fn = test.get("mongo-conn-fn")
    if fn is not None:
        return fn(node)
    return Conn(node, PORT)


class DocumentCASClient(jclient.Client):
    """Independent-keyed CAS over documents {_id: k, value: v} in
    jepsen.cas (`document_cas.clj`): reads with linearizable read
    concern, writes upsert with majority write concern, CAS via
    findAndModify on {_id, value}."""

    DB_NAME = "jepsen"
    COLL = "cas"

    def __init__(self):
        self.conn: Conn | None = None

    def open(self, test, node):
        c = DocumentCASClient()
        c.conn = _connect(test, node)
        return c

    def close(self, test):
        if self.conn is not None:
            self.conn.close()

    def invoke(self, test, op):
        v = op["value"]
        if independent.is_tuple(v):
            k, inner = v

            def wrap(x):
                return independent.ktuple(k, x)
        else:
            k, inner = 0, v

            def wrap(x):
                return x
        k = int(k)
        try:
            if op["f"] == "read":
                r = self.conn.command(self.DB_NAME, {
                    "find": self.COLL, "filter": {"_id": k},
                    "limit": 1,
                    "readConcern": {"level": "linearizable"},
                })
                docs = r.get("cursor", {}).get("firstBatch", [])
                val = docs[0].get("value") if docs else None
                return {**op, "type": "ok", "value": wrap(val)}
            if op["f"] == "write":
                self.conn.command(self.DB_NAME, {
                    "update": self.COLL,
                    "updates": [{"q": {"_id": k},
                                 "u": {"$set": {"value": inner}},
                                 "upsert": True}],
                    "writeConcern": {"w": "majority"},
                })
                return {**op, "type": "ok"}
            if op["f"] == "cas":
                old, new = inner
                r = self.conn.command(self.DB_NAME, {
                    "findAndModify": self.COLL,
                    "query": {"_id": k, "value": old},
                    "update": {"$set": {"value": new}},
                    "writeConcern": {"w": "majority"},
                })
                ok = r.get("lastErrorObject",
                           {}).get("updatedExisting", False)
                return {**op, "type": "ok" if ok else "fail"}
            raise ValueError(f"unknown f {op['f']!r}")
        except WriteConcernError as e:
            # applied locally, durability unknown: always :info
            return {**op, "type": "info",
                    "error": ["mongo-write-concern", e.code, str(e)]}
        except MongoError as e:
            definite = op["f"] == "read" or e.code in DEFINITE_FAIL
            return {**op, "type": "fail" if definite else "info",
                    "error": ["mongo", e.code, str(e)]}
        except OSError as e:
            return {**op,
                    "type": "fail" if op["f"] == "read" else "info",
                    "error": str(e)}


class SetClient(jclient.Client):
    """Grow-only set: insert {value} docs, read = full collection scan
    (the sets workloads in the larger reference suites)."""

    DB_NAME = "jepsen"
    COLL = "set"

    def __init__(self):
        self.conn: Conn | None = None
        self.ids = itertools.count()

    def open(self, test, node):
        c = SetClient()
        c.conn = _connect(test, node)
        return c

    def close(self, test):
        if self.conn is not None:
            self.conn.close()

    def invoke(self, test, op):
        try:
            if op["f"] == "add":
                self.conn.command(self.DB_NAME, {
                    "insert": self.COLL,
                    "documents": [{"value": op["value"]}],
                    "writeConcern": {"w": "majority"},
                })
                return {**op, "type": "ok"}
            if op["f"] == "read":
                r = self.conn.command(self.DB_NAME, {
                    "find": self.COLL, "filter": {},
                    "readConcern": {"level": "majority"},
                    "batchSize": 10 ** 9,
                })
                vals = sorted(d["value"] for d in
                              r.get("cursor", {}).get("firstBatch", []))
                return {**op, "type": "ok", "value": vals}
            raise ValueError(f"unknown f {op['f']!r}")
        except WriteConcernError as e:
            # applied locally, durability unknown: always :info
            return {**op, "type": "info",
                    "error": ["mongo-write-concern", e.code, str(e)]}
        except MongoError as e:
            definite = op["f"] == "read" or e.code in DEFINITE_FAIL
            return {**op, "type": "fail" if definite else "info",
                    "error": ["mongo", e.code, str(e)]}
        except OSError as e:
            return {**op,
                    "type": "fail" if op["f"] == "read" else "info",
                    "error": str(e)}


def register_workload(opts: dict) -> dict:
    w = linearizable_register.test({
        "nodes": opts["nodes"],
        "per-key-limit": opts.get("ops-per-key", 100),
    })
    w["client"] = DocumentCASClient()
    return w


def set_workload(opts: dict) -> dict:
    adds = ({"type": "invoke", "f": "add", "value": i}
            for i in itertools.count())
    return {
        "client": SetClient(),
        "checker": checker.set_checker(),
        "generator": adds,
        "final-generator": gen.each_thread(gen.once(
            {"type": "invoke", "f": "read", "value": None})),
    }


WORKLOADS = {
    "register": register_workload,
    "set": set_workload,
}


def mongodb_test(opts: dict) -> dict:
    workload_name = opts.get("workload", "register")
    return std_test(
        opts, name=f"mongodb-{workload_name}",
        db=db(opts.get("version", DEFAULT_VERSION),
              opts.get("engine", "wiredTiger")),
        workload=WORKLOADS[workload_name](opts))


OPT_SPEC = std_opts(cli, WORKLOADS, "register", DEFAULT_VERSION,
                    "mongodb-org-server version") + [
    cli.opt("--engine", default="wiredTiger",
            choices=["wiredTiger", "rocksdb"],
            help="storage engine (rocksdb = the mongodb-rocks suite)"),
    cli.opt("--ops-per-key", type=int, default=100,
            help="ops per independent key (register workload)"),
]


def main(argv=None):
    cli.run({**cli.single_test_cmd({"test_fn": mongodb_test,
                                    "opt_spec": OPT_SPEC}),
             **cli.serve_cmd()}, argv)


if __name__ == "__main__":
    main()
