"""MySQL Cluster (NDB) test suite.

Mirrors `/root/reference/mysql-cluster/src/jepsen/mysql_cluster.clj`:
the three-daemon topology — management (ndb_mgmd, node ids 1+),
storage (ndbd, ids 11+, first four nodes), and SQL (mysqld, ids 21+)
— with generated config.ini role sections and a templated my.cnf
carrying the ndb connect string. The reference ships no workload
(`simple-test` is a noop, `mysql_cluster.clj:228-234`); since mysqld
speaks the MySQL wire protocol, this suite adds a register workload
over the `mysql_proto` client so the deployment is actually
exercised."""

from __future__ import annotations

import logging

from .. import cli, client as jclient, control, core, models
from .. import db as jdb
from ..checker import linear
from ..control import util as cu
from ..control.core import RemoteError
from ..os_ import debian
from . import std_opts, std_test
from .mysql_proto import Conn, MySQLError

log = logging.getLogger(__name__)

USER = "mysql"
MGMD_DIR = "/var/lib/mysql/cluster"
NDBD_DIR = "/var/lib/mysql/data"
MYSQLD_DIR = "/var/lib/mysql/mysql"
MGMD_ID_OFFSET = 1
NDBD_ID_OFFSET = 11
MYSQLD_ID_OFFSET = 21
BIN_DIR = "/opt/mysql/server-5.6/bin"
SQL_PORT = 3306

DEFAULT_VERSION = "7.4.6"

MY_CNF = """\
[mysqld]
user=mysql
ndbcluster
ndb-connectstring={connect_string}
datadir={data_dir}
server-id={node_id}
[mysql_cluster]
ndb-connectstring={connect_string}
"""

CONFIG_INI_HEAD = """\
[ndbd default]
NoOfReplicas=2
DataMemory=80M
IndexMemory=18M
[tcp default]
"""


def mgmd_id(test, node) -> int:
    return MGMD_ID_OFFSET + test["nodes"].index(node)


def ndbd_id(test, node) -> int:
    return NDBD_ID_OFFSET + test["nodes"].index(node)


def mysqld_id(test, node) -> int:
    return MYSQLD_ID_OFFSET + test["nodes"].index(node)


def ndbd_nodes(test) -> list:
    """Storage role runs on the first four nodes
    (`mysql_cluster.clj:96-99`)."""
    return sorted(test["nodes"])[:4]


def nodes_conf(test) -> str:
    """Role sections for every node (`mysql_cluster.clj:101-112`)."""
    parts = []
    for n in test["nodes"]:
        parts.append(f"[ndb_mgmd]\nNodeId={mgmd_id(test, n)}\n"
                     f"hostname={n}\ndatadir={MGMD_DIR}\n")
    for n in ndbd_nodes(test):
        parts.append(f"[ndbd]\nNodeId={ndbd_id(test, n)}\n"
                     f"hostname={n}\ndatadir={NDBD_DIR}\n")
    for n in test["nodes"]:
        parts.append(f"[mysqld]\nNodeId={mysqld_id(test, n)}\n"
                     f"hostname={n}\n")
    return "\n".join(parts)


def connect_string(test) -> str:
    return ",".join(test["nodes"])


class DB(jdb.DB, jdb.LogFiles):
    """deb install + three-daemon lifecycle
    (`mysql_cluster.clj:22-226`)."""

    def __init__(self, version: str = DEFAULT_VERSION):
        self.version = version

    def setup(self, test, node):
        debian.install({"libaio1": "0.3.110-1"})
        with control.su():
            url = test.get("deb-url") or (
                "https://dev.mysql.com/get/Downloads/MySQL-Cluster-7.4/"
                f"mysql-cluster-gpl-{self.version}-debian7-x86_64.deb")
            deb = cu.cached_wget(url)
            control.exec_("dpkg", "-i", "--force-confask",
                          "--force-confnew", deb)
            try:
                control.exec_("adduser", "--disabled-password",
                              "--gecos", "", USER)
            except RemoteError:
                pass  # user exists
            cu.write_file(MY_CNF.format(
                connect_string=connect_string(test),
                data_dir=MYSQLD_DIR,
                node_id=mysqld_id(test, node)), "/etc/my.cnf")
            control.exec_("mkdir", "-p", MGMD_DIR)
            cu.write_file(CONFIG_INI_HEAD + nodes_conf(test),
                          "/etc/my.config.ini")
            # daemons in lockstep phases: every mgmd must be up
            # before any ndbd registers, and every ndbd before mysqld
            # (`mysql_cluster.clj:190-202`)
            control.exec_(f"{BIN_DIR}/ndb_mgmd",
                          f"--ndb-nodeid={mgmd_id(test, node)}",
                          "-f", "/etc/my.config.ini")
        core.synchronize(test)
        with control.su():
            if node in ndbd_nodes(test):
                control.exec_("mkdir", "-p", NDBD_DIR)
                control.exec_(f"{BIN_DIR}/ndbd",
                              f"--ndb-nodeid={ndbd_id(test, node)}")
        core.synchronize(test)
        with control.su():
            control.exec_("mkdir", "-p", MYSQLD_DIR)
            control.exec_("chown", "-R", f"{USER}:{USER}", MYSQLD_DIR)
        with control.sudo(USER):
            control.exec_(f"{BIN_DIR}/mysqld_safe",
                          "--defaults-file=/etc/my.cnf")
        cu.await_tcp_port(SQL_PORT)

    def teardown(self, test, node):
        with control.su():
            for proc in ("mysqld", "ndbd", "ndb_mgmd"):
                cu.grepkill(proc)
            try:
                control.exec_raw(
                    f"rm -rf {MGMD_DIR}/* {NDBD_DIR}/* {MYSQLD_DIR}/*")
            except RemoteError:
                pass

    def log_files(self, test, node):
        return [f"{MGMD_DIR}/ndb_1_cluster.log",
                f"{MYSQLD_DIR}/mysqld.err"]


def db(version: str = DEFAULT_VERSION) -> DB:
    return DB(version)


class RegisterClient(jclient.Client):
    """Single-row NDB-table register over the MySQL wire protocol —
    the workload the reference's noop test never got."""

    def __init__(self):
        self.conn: Conn | None = None

    def open(self, test, node):
        c = RegisterClient()
        fn = test.get("sql-conn-fn")
        # connect without a schema: the database may not exist yet
        # (ER_BAD_DB_ERROR in the handshake would wedge every client)
        c.conn = fn(node) if fn else Conn(node, SQL_PORT, user="root")
        try:
            c.conn.query("create database if not exists jepsen")
            c.conn.query("use jepsen")
        except (MySQLError, OSError):
            pass
        return c

    def close(self, test):
        if self.conn is not None:
            self.conn.close()

    def setup(self, test):
        try:
            self.conn.query("create database if not exists jepsen")
            self.conn.query("use jepsen")
            self.conn.query(
                "create table if not exists registers "
                "(id int primary key, val int) engine=ndbcluster")
            self.conn.query(
                "insert into registers (id, val) values (0, 0) "
                "on duplicate key update id = id")
        except (MySQLError, OSError):
            pass  # another worker seeds

    def invoke(self, test, op):
        try:
            if op["f"] == "read":
                rows, _ = self.conn.query(
                    "select val from registers where id = 0")
                v = int(rows[0][0]) if rows and rows[0][0] is not None \
                    else None
                return {**op, "type": "ok", "value": v}
            if op["f"] == "write":
                self.conn.query(
                    f"update registers set val = "
                    f"{int(op['value'])} where id = 0")
                return {**op, "type": "ok"}
            raise ValueError(f"unknown f {op['f']!r}")
        except MySQLError as e:
            t = "fail" if op["f"] == "read" else "info"
            return {**op, "type": t, "error": ["sql", e.code, str(e)]}
        except OSError as e:
            return {**op,
                    "type": "fail" if op["f"] == "read" else "info",
                    "error": str(e)}


def register_workload(opts: dict) -> dict:
    from .. import generator as gen

    def r(test, ctx):
        return {"type": "invoke", "f": "read", "value": None}

    def w(test, ctx):
        return {"type": "invoke", "f": "write",
                "value": gen.rng.randrange(5)}

    return {
        "client": RegisterClient(),
        "generator": gen.mix([r, w]),
        "checker": linear.linearizable(models.register(0)),
    }


WORKLOADS = {"register": register_workload}


def mysql_cluster_test(opts: dict) -> dict:
    workload_name = opts.get("workload", "register")
    return std_test(
        opts, name=f"mysql-cluster-{workload_name}",
        db=db(opts.get("version", DEFAULT_VERSION)),
        workload=WORKLOADS[workload_name](opts))


OPT_SPEC = std_opts(cli, WORKLOADS, "register", DEFAULT_VERSION,
                    "MySQL Cluster version")


def main(argv=None):
    cli.run({**cli.single_test_cmd({"test_fn": mysql_cluster_test,
                                    "opt_spec": OPT_SPEC}),
             **cli.serve_cmd()}, argv)


if __name__ == "__main__":
    main()
