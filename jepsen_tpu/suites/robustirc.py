"""RobustIRC test suite — message delivery over a Raft-replicated IRC
network.

Mirrors `/root/reference/robustirc/src/jepsen/robustirc.clj`: build
via `go get`, TLS certs uploaded, the first node starts -singlenode
and the rest -join it; the set workload posts TOPIC changes to a
channel through the HTTP bridge (session create -> NICK/USER/JOIN ->
TOPIC :<n>) and the final read streams all messages back, extracting
topics (`robustirc.clj:103-184`). Verdict: the set checker — every
acknowledged topic must be readable."""

from __future__ import annotations

import hashlib
import json
import logging
import random
import ssl
import urllib.request

from .. import checker, cli, client as jclient, control, core
from .. import db as jdb
from ..control import util as cu
from ..control.core import RemoteError
from ..os_ import debian
from . import std_opts, std_test

log = logging.getLogger(__name__)

PORT = 13001
CHANNEL = "#jepsen"
NETWORK_PASSWORD = "secret"
BIN = "~/gocode/bin/robustirc"


def _meh(*cmd):
    try:
        control.exec_(*cmd)
    except RemoteError:
        pass


class DB(jdb.DB):
    """go-get build, cert upload, singlenode bootstrap + joins
    (`robustirc.clj:24-84`)."""

    def setup(self, test, node):
        with control.su():
            _meh("killall", "robustirc")
            for pkg in ("golang-go", "mercurial"):
                try:
                    control.exec_("dpkg-query", "-l", pkg)
                except RemoteError:
                    debian.install([pkg])
            control.exec_("env", "GOPATH=~/gocode", "go", "get", "-u",
                          "github.com/robustirc/robustirc")
            if test.get("certs-dir"):
                for f in ("cert.pem", "key.pem"):
                    control.upload(f"{test['certs-dir']}/{f}",
                                   f"/tmp/{f}")
            else:
                # the reference ships ONE pre-generated cert/key pair
                # to every node (`robustirc.clj:41-42`): the primary
                # generates, the control node relays the same pair to
                # everyone (per-node certs would fail -tls_ca_file
                # verification on join)
                if node == test["nodes"][0]:
                    control.exec_(
                        "openssl", "req", "-x509", "-newkey",
                        "rsa:2048", "-keyout", "/tmp/key.pem",
                        "-out", "/tmp/cert.pem", "-days", "365",
                        "-nodes", "-subj", "/CN=jepsen")
                    import tempfile
                    d = test.setdefault(
                        "_robustirc-certs",
                        tempfile.mkdtemp(prefix="robustirc-certs-"))
                    for f in ("cert.pem", "key.pem"):
                        control.download(f"/tmp/{f}", f"{d}/{f}")
        core.synchronize(test)
        with control.su():
            if not test.get("certs-dir") and node != test["nodes"][0]:
                d = test["_robustirc-certs"]
                for f in ("cert.pem", "key.pem"):
                    control.upload(f"{d}/{f}", f"/tmp/{f}")
            control.exec_("rm", "-rf", "/var/lib/robustirc")
            control.exec_("mkdir", "-p", "/var/lib/robustirc")
            common = (f"-listen={node}:{PORT}"
                      f" -network_password={NETWORK_PASSWORD}"
                      " -network_name=jepsen"
                      " -tls_cert_path=/tmp/cert.pem"
                      " -tls_ca_file=/tmp/cert.pem"
                      " -tls_key_path=/tmp/key.pem")
        # the primary bootstraps -singlenode; everyone else joins only
        # after it is up (`robustirc.clj:45-78` barriers + sleeps)
        core.synchronize(test)
        primary = test["nodes"][0]
        with control.su():
            if node == primary:
                control.exec_raw(
                    "/sbin/start-stop-daemon --start --background "
                    f"--exec {BIN} -- {common} -singlenode")
                cu.await_tcp_port(PORT)
        core.synchronize(test)
        with control.su():
            if node != primary:
                control.exec_raw(
                    "/sbin/start-stop-daemon --start --background "
                    f"--exec {BIN} -- {common} -join={primary}:{PORT}")
                cu.await_tcp_port(PORT)
        core.synchronize(test)

    def teardown(self, test, node):
        with control.su():
            _meh("killall", "robustirc")


def db() -> DB:
    return DB()


class Session:
    """One RobustIRC HTTP-bridge session (`robustirc.clj:103-121`)."""

    def __init__(self, base: str, timeout_s: float = 5.0):
        self.base = base
        self.timeout_s = timeout_s
        self.ctx = ssl._create_unverified_context() \
            if base.startswith("https") else None
        r = self._request("POST", "/robustirc/v1/session", None, {})
        self.session_id = r["Sessionid"]
        self.auth = r["Sessionauth"]

    def _request(self, method: str, path: str, auth, body):
        req = urllib.request.Request(
            self.base + path,
            data=json.dumps(body).encode() if body is not None else None,
            method=method,
            headers={"Content-Type": "application/json",
                     **({"X-Session-Auth": auth} if auth else {})})
        with urllib.request.urlopen(req, timeout=self.timeout_s,
                                    context=self.ctx) as r:
            data = r.read().decode()
        # the messages endpoint streams concatenated JSON docs
        docs = []
        dec = json.JSONDecoder()
        i = 0
        while i < len(data):
            while i < len(data) and data[i] in " \r\n\t":
                i += 1
            if i >= len(data):
                break
            doc, j = dec.raw_decode(data, i)
            docs.append(doc)
            i = j
        return docs[0] if len(docs) == 1 else docs

    def post(self, ircmessage: str):
        """ClientMessageId mirrors the reference's md5-or-random id
        (`robustirc.clj:115-121`)."""
        msgid = (random.getrandbits(31)
                 | int(hashlib.md5(ircmessage.encode())
                       .hexdigest()[17:], 16)) & 0x7FFFFFFF
        return self._request(
            "POST", f"/robustirc/v1/{self.session_id}/message",
            self.auth,
            {"Data": ircmessage, "ClientMessageId": msgid})

    def messages(self, budget_s: float = 1.0) -> list:
        """The real /messages endpoint is a never-closing long-poll
        stream: read incrementally under a wall-clock budget, keeping
        whatever parsed. This mirrors the reference's read-all exactly
        — `(util/timeout 1000 @out ...)` returns whatever accumulated
        and the read is still recorded :ok (`robustirc.clj:123-136`,
        `:172-177`) — so, like the reference, a read the budget
        truncated can under-report the set."""
        import time as _t
        req = urllib.request.Request(
            self.base + f"/robustirc/v1/{self.session_id}"
                        "/messages?lastseen=0.0",
            headers={"X-Session-Auth": self.auth})
        data = ""
        t0 = _t.monotonic()
        try:
            with urllib.request.urlopen(req, timeout=budget_s,
                                        context=self.ctx) as r:
                while _t.monotonic() - t0 < budget_s:
                    chunk = r.read(4096)
                    if not chunk:
                        break
                    data += chunk.decode()
        except OSError:
            pass  # stream timeout: keep what we have
        docs = []
        dec = json.JSONDecoder()
        i = 0
        while i < len(data):
            while i < len(data) and data[i] in " \r\n\t":
                i += 1
            if i >= len(data):
                break
            try:
                doc, i = dec.raw_decode(data, i)
            except ValueError:
                break  # trailing partial doc at the cut-off
            docs.append(doc)
        return docs


def _is_topic(msg: dict) -> bool:
    parts = (msg.get("Data") or "").split(" ")
    return len(parts) > 1 and parts[1] == "TOPIC"


def _topic_value(msg: dict) -> int:
    return int((msg.get("Data") or "").rsplit(":", 1)[-1])


class SetClient(jclient.Client):
    """Adds = TOPIC changes; the read streams every message and
    collects the topics seen (`robustirc.clj:150-184`)."""

    def __init__(self):
        self.session: Session | None = None
        self.node = None

    def open(self, test, node):
        c = SetClient()
        c.node = node
        fn = test.get("irc-url-fn")
        base = fn(node) if fn else f"https://{node}:{PORT}"
        c.session = Session(base)
        c.session.post(f"NICK {node}-{id(c) % 9973}")
        c.session.post("USER j j j j")
        c.session.post(f"JOIN {CHANNEL}")
        return c

    def invoke(self, test, op):
        try:
            if op["f"] == "add":
                self.session.post(f"TOPIC {CHANNEL} :{op['value']}")
                return {**op, "type": "ok"}
            if op["f"] == "read":
                msgs = self.session.messages()
                vals = sorted({_topic_value(m) for m in msgs
                               if _is_topic(m)})
                return {**op, "type": "ok", "value": vals}
            raise ValueError(f"unknown f {op['f']!r}")
        except (OSError, ValueError, KeyError) as e:
            if op["f"] == "read":
                return {**op, "type": "fail", "error": str(e)}
            return {**op, "type": "info", "error": str(e)}


def sets_workload(opts: dict) -> dict:
    from .. import generator as gen
    import itertools

    values = itertools.count()

    def add(test, ctx):
        return {"type": "invoke", "f": "add", "value": next(values)}

    return {
        "client": SetClient(),
        "generator": add,
        "checker": checker.set_checker(),
        "final-generator": gen.each_thread(gen.once(
            {"type": "invoke", "f": "read", "value": None})),
    }


WORKLOADS = {"set": sets_workload}


def robustirc_test(opts: dict) -> dict:
    workload_name = opts.get("workload", "set")
    return std_test(
        opts, name=f"robustirc-{workload_name}", db=db(),
        workload=WORKLOADS[workload_name](opts))


OPT_SPEC = std_opts(cli, WORKLOADS, "set") + [
    cli.opt("--certs-dir", default=None,
            help="directory holding cert.pem/key.pem to upload"),
]


def main(argv=None):
    cli.run({**cli.single_test_cmd({"test_fn": robustirc_test,
                                    "opt_spec": OPT_SPEC}),
             **cli.serve_cmd()}, argv)


if __name__ == "__main__":
    main()
